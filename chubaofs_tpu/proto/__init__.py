"""Wire protocol for the replicated data plane (proto/packet.go analog)."""

from chubaofs_tpu.proto.packet import (  # noqa: F401
    HEADER_SIZE, MAGIC, OP_CREATE_EXTENT, OP_CREATE_PARTITION,
    OP_GET_PARTITION_METRICS, OP_GET_WATERMARKS, OP_HEARTBEAT, OP_MARK_DELETE,
    OP_RANDOM_WRITE, OP_REPAIR_READ, OP_REPAIR_WRITE, OP_STREAM_READ,
    OP_TINY_DELETE_RECORD, OP_WRITE, Packet, ProtoError, RES_AGAIN,
    RES_CRC_MISMATCH, RES_DISK_ERR, RES_ERR, RES_NOT_EXIST, RES_NOT_LEADER,
    RES_OK, TINY_EXTENT_COUNT, TINY_EXTENT_MAX_ID, is_tiny_extent,
    next_req_id, recv_packet, send_packet,
)
