"""Wire protocol — the binary TCP packet every data-plane op rides.

Reference counterpart: proto/packet.go:238-257 (the `Packet` struct: Magic,
Opcode, ResultCode, RemainingFollowers, CRC, Size, ArgLen, PartitionID,
ExtentID, ExtentOffset, ReqID, KernelOffset; opcodes :50-69). Design choices
kept: fixed little-endian header followed by an opaque arg blob (JSON here,
where the reference packs follower addresses as a '/'-joined string) and the
data payload, CRC32 over the payload, and a RemainingFollowers byte that the
chain-replication leader decrements before forwarding (packet.go:243,
repl/repl_protocol.go:35-39). Not kept: the reference's ~150 opcodes collapse
to the data-plane set below — metadata ops travel through raft proposals
instead of this wire (metanode design in chubaofs_tpu/meta)."""

from __future__ import annotations

import json
import socket
import struct
import zlib
from dataclasses import dataclass, field

MAGIC = 0xCF

# -- opcodes (proto/packet.go:50-69 analog, data-plane subset) -----------------
OP_CREATE_EXTENT = 0x01  # OpCreateExtent: alloc a normal extent id on the dp
OP_WRITE = 0x02  # OpWrite: append to a normal or tiny extent
OP_STREAM_READ = 0x03  # OpStreamRead: read [offset, offset+size) of an extent
OP_RANDOM_WRITE = 0x04  # OpRandomWrite: in-place overwrite, routed via raft
OP_MARK_DELETE = 0x05  # OpMarkDelete: extent (or tiny range) delete
OP_GET_WATERMARKS = 0x06  # OpGetAllWatermarks: {extent_id: size} for repair
OP_REPAIR_READ = 0x07  # OpExtentRepairRead: repair-path stream read
OP_REPAIR_WRITE = 0x08  # repair-path write (bypasses replication; local only)
OP_GET_PARTITION_METRICS = 0x09  # used + extent counts, for master heartbeats
OP_HEARTBEAT = 0x0A  # liveness probe
OP_CREATE_PARTITION = 0x0B  # admin: host a new data partition
OP_TINY_DELETE_RECORD = 0x0C  # replicated tiny-range punch-hole record
OP_RAFT_CONFIG = 0x0D  # admin: single-server raft membership change
OP_REMOVE_PARTITION = 0x0E  # admin: drop a retired partition replica
# metadata plane (proto/packet.go:72-82 OpMeta* analog): one opcode, the op
# name rides the arg blob — the metanode partition SM already dispatches by
# name, so ~40 distinct OpMeta opcodes collapse to a tagged envelope
OP_META_OP = 0x20

# opcode -> short name, for metric labels and trace/track entries (bounded
# cardinality by construction: the opcode set IS the label set)
OP_NAMES = {
    OP_CREATE_EXTENT: "create_extent", OP_WRITE: "write",
    OP_STREAM_READ: "stream_read", OP_RANDOM_WRITE: "random_write",
    OP_MARK_DELETE: "mark_delete", OP_GET_WATERMARKS: "get_watermarks",
    OP_REPAIR_READ: "repair_read", OP_REPAIR_WRITE: "repair_write",
    OP_GET_PARTITION_METRICS: "partition_metrics", OP_HEARTBEAT: "heartbeat",
    OP_CREATE_PARTITION: "create_partition",
    OP_TINY_DELETE_RECORD: "tiny_delete", OP_RAFT_CONFIG: "raft_config",
    OP_REMOVE_PARTITION: "remove_partition", OP_META_OP: "meta_op",
}


def op_name(opcode: int) -> str:
    return OP_NAMES.get(opcode, f"op_{opcode:#x}")

# -- result codes (proto/packet.go OpOk/OpErr/... analog) ----------------------
RES_OK = 0x00
RES_ERR = 0x01
RES_AGAIN = 0x02
RES_NOT_LEADER = 0x03
RES_NOT_EXIST = 0x04
RES_DISK_ERR = 0x05
RES_CRC_MISMATCH = 0x06

# magic, opcode, result, remaining_followers, crc, size, arg_len,
# partition_id, extent_id, extent_offset, kernel_offset, req_id
_HEADER = struct.Struct("<BBBBIIIQQQQQ")
HEADER_SIZE = _HEADER.size  # 56 bytes

# receive-side sanity bounds on the header's u32 length fields. The largest
# legit payload is a multi-MiB extent/shard write; 64 MiB leaves generous
# headroom while keeping a hostile header from preallocating 4 GiB.
MAX_DATA_LEN = 64 << 20
MAX_ARG_LEN = 16 << 20

TINY_EXTENT_COUNT = 64  # storage/extent_store.go:613-694: 64 shared tiny extents
TINY_EXTENT_MAX_ID = TINY_EXTENT_COUNT  # ids 1..64 are tiny, >=65 normal


def is_tiny_extent(extent_id: int) -> bool:
    return 1 <= extent_id <= TINY_EXTENT_MAX_ID


class ProtoError(Exception):
    pass


_req_counter = 0


def next_req_id() -> int:
    global _req_counter
    _req_counter += 1
    return _req_counter


@dataclass
class Packet:
    opcode: int
    partition_id: int = 0
    extent_id: int = 0
    extent_offset: int = 0
    kernel_offset: int = 0
    data: bytes = b""
    arg: dict = field(default_factory=dict)
    result: int = RES_OK
    remaining_followers: int = 0
    req_id: int = 0
    crc: int = 0

    def __post_init__(self):
        if self.req_id == 0:
            self.req_id = next_req_id()
        if self.data and self.crc == 0:
            self.crc = zlib.crc32(self.data)

    # -- framing ---------------------------------------------------------------

    def encode(self) -> bytes:
        arg_blob = json.dumps(self.arg).encode() if self.arg else b""
        hdr = _HEADER.pack(
            MAGIC, self.opcode, self.result, self.remaining_followers,
            self.crc, len(self.data), len(arg_blob),
            self.partition_id, self.extent_id, self.extent_offset,
            self.kernel_offset, self.req_id,
        )
        return hdr + arg_blob + self.data

    @classmethod
    def decode_header(cls, hdr: bytes) -> tuple["Packet", int, int]:
        (magic, opcode, result, followers, crc, size, arg_len,
         pid, eid, eoff, koff, req_id) = _HEADER.unpack(hdr)
        if magic != MAGIC:
            raise ProtoError(f"bad magic {magic:#x}")
        # bound the u32 length fields BEFORE anyone preallocates: both
        # receive paths (_recv_exact, PacketFramer.arm_stage) size a buffer
        # straight from the header, so an unchecked size=0xFFFFFFFF is a
        # 4 GiB allocation per corrupt/hostile connection
        if size > MAX_DATA_LEN or arg_len > MAX_ARG_LEN:
            raise ProtoError(f"oversized packet: data={size} arg={arg_len}")
        pkt = cls(opcode=opcode, partition_id=pid, extent_id=eid,
                  extent_offset=eoff, kernel_offset=koff, result=result,
                  remaining_followers=followers, req_id=req_id, crc=crc)
        return pkt, arg_len, size

    def verify_crc(self) -> bool:
        return not self.data or zlib.crc32(self.data) == self.crc

    # -- replies ---------------------------------------------------------------

    def reply(self, result: int = RES_OK, data: bytes = b"",
              arg: dict | None = None, extent_id: int | None = None,
              extent_offset: int | None = None) -> "Packet":
        """Build the response packet mirroring ids; write acks may rewrite the
        extent id/offset the datanode assigned (tiny-extent allocation)."""
        return Packet(
            opcode=self.opcode, partition_id=self.partition_id,
            extent_id=self.extent_id if extent_id is None else extent_id,
            extent_offset=self.extent_offset if extent_offset is None else extent_offset,
            kernel_offset=self.kernel_offset, data=data, arg=arg or {},
            result=result, req_id=self.req_id,
        )

    def error(self) -> str:
        return self.arg.get("error", f"result={self.result}")


# -- trace carrier on the packet wire ------------------------------------------
# The binary header is fixed; the trace id and returning track log ride the
# JSON arg blob under reserved keys (the reference packs follower addrs into
# its arg bytes the same way). Requests carry "_trace" (+ the caller's span
# id under "_span", so the server span records its cross-process parent);
# replies carry "_track".

TRACE_ARG_KEY = "_trace"
SPAN_ARG_KEY = "_span"
TRACK_ARG_KEY = "_track"


def trace_inject(pkt: "Packet") -> "Packet":
    """Attach the CURRENT thread span's trace id to an outgoing request."""
    from chubaofs_tpu.blobstore import trace

    span = trace.current_span()
    if span is not None:
        pkt.arg[TRACE_ARG_KEY] = span.trace_id
        pkt.arg[SPAN_ARG_KEY] = span.span_id
    return pkt


def trace_extract(pkt: "Packet", operation: str):
    """Server side: a span continuing the packet's trace (or a fresh root)."""
    from chubaofs_tpu.blobstore import trace

    tid = pkt.arg.get(TRACE_ARG_KEY) if isinstance(pkt.arg, dict) else None
    span = trace.Span(operation, trace_id=tid)
    if tid is not None:
        span.remote_parent = pkt.arg.get(SPAN_ARG_KEY)
    return span


def trace_reply(resp: "Packet", span) -> "Packet":
    """Attach the server span's track log to an outgoing reply."""
    if span is not None and span.track:
        resp.arg[TRACK_ARG_KEY] = span.track_entries()
    return resp


def trace_merge(resp: "Packet") -> None:
    """Client side: fold a reply's track log into the current span."""
    from chubaofs_tpu.blobstore import trace

    span = trace.current_span()
    if span is not None and isinstance(resp.arg, dict):
        span.merge_track(resp.arg.get(TRACK_ARG_KEY))


# -- socket framing ---------------------------------------------------------------
#
# Zero-copy discipline (ISSUE 8): a multi-MB shard payload crosses this layer
# without a single Python-level copy in either direction. Sending hands the
# kernel an iovec of (header, arg, data) memoryviews via sendmsg — never
# `hdr + arg + data` concatenation, which would materialize the payload a
# second time. Receiving preallocates ONE bytearray of the exact size and
# fills it in place with recv_into — the old bytearray-accumulate-then-
# `bytes(buf)` path copied every payload twice (growth reallocs + the final
# freeze). Received payloads stay bytearray: every consumer (crc32, file
# writes, raft codec, json.loads, slice-assign into read buffers) takes any
# buffer object, and the freeze-to-bytes copy bought nothing.


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    """Read exactly n bytes into a preallocated buffer, filled in place."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if r == 0:
            raise ConnectionError("peer closed")
        got += r
    return buf


def packet_iov(pkt: Packet) -> list:
    """The packet as a (header, arg, data) iovec — framing WITHOUT the
    payload concat `encode()` pays. The data element is a memoryview of the
    caller's buffer; nothing is copied."""
    arg_blob = json.dumps(pkt.arg).encode() if pkt.arg else b""
    hdr = _HEADER.pack(
        MAGIC, pkt.opcode, pkt.result, pkt.remaining_followers,
        pkt.crc, len(pkt.data), len(arg_blob),
        pkt.partition_id, pkt.extent_id, pkt.extent_offset,
        pkt.kernel_offset, pkt.req_id,
    )
    iov = [hdr]
    if arg_blob:
        iov.append(arg_blob)
    if pkt.data:
        iov.append(memoryview(pkt.data))
    return iov


def advance_iov(views: list, sent: int) -> list:
    """Drop `sent` bytes off the front of a memoryview iovec and return the
    remainder — THE pointer-advance for every partial-send site (blocking
    sendmsg_all, the evloop's direct send and shard flush all share it, so
    a boundary fix lands everywhere at once)."""
    i = 0
    for v in views:
        if sent < len(v):
            break
        sent -= len(v)
        i += 1
    rest = views[i:]
    if rest and sent:
        rest[0] = rest[0][sent:]
    return [v for v in rest if len(v)]


def sendmsg_all(sock: socket.socket, iov: list) -> None:
    """Drain an iovec through sendmsg, advancing memoryviews across partial
    sends — the writev analog. No buffer is ever joined."""
    views = [memoryview(b) for b in iov]
    while views:
        views = advance_iov(views, sock.sendmsg(views))


def send_packet(sock: socket.socket, pkt: Packet) -> None:
    iov = packet_iov(pkt)
    if hasattr(sock, "sendmsg"):
        sendmsg_all(sock, iov)
    else:  # sendmsg-less socket (test doubles, exotic platforms)
        for buf in iov:
            sock.sendall(buf)


def recv_packet(sock: socket.socket) -> Packet:
    pkt, arg_len, size = Packet.decode_header(_recv_exact(sock, HEADER_SIZE))
    if arg_len:
        pkt.arg = json.loads(_recv_exact(sock, arg_len))
    if size:
        pkt.data = _recv_exact(sock, size)
    return pkt


class PacketFramer:
    """Incremental packet codec — the event loop's per-connection read state
    machine, and the SAME framing recv_packet performs blockingly: header →
    arg blob → data payload, each stage a preallocated buffer the loop fills
    with non-blocking recv_into calls (partial reads resume where they
    stopped). The data-stage buffer BECOMES pkt.data — zero copies on the
    receive path, same as the blocking side.

    Contract (rpc/evloop.py consumes it): `need()` says how many bytes the
    next stage wants; the loop hands back the exact-size filled buffer via
    `feed(buf)`, which returns a completed Packet or None (mid-message).
    Malformed input raises ProtoError — the connection is dropped."""

    def __init__(self):
        self._pkt: Packet | None = None
        self._arg_len = 0
        self._size = 0
        self._stage = "hdr"

    def need(self) -> int:
        if self._stage == "hdr":
            return HEADER_SIZE
        if self._stage == "arg":
            return self._arg_len
        return self._size

    def feed(self, buf: bytearray) -> Packet | None:
        if self._stage == "hdr":
            self._pkt, self._arg_len, self._size = Packet.decode_header(buf)
            self._stage = "arg" if self._arg_len else "data"
        elif self._stage == "arg":
            try:
                self._pkt.arg = json.loads(buf)
            except ValueError as e:
                raise ProtoError(f"bad arg blob: {e}") from None
            self._stage = "data"
        else:
            self._pkt.data = buf
            self._stage = "done"
        if self._stage == "arg" or (self._stage == "data" and self._size):
            return None
        pkt, self._pkt, self._stage = self._pkt, None, "hdr"
        return pkt
