"""Actuator registry — the remediations the autopilot is allowed to run.

Each factory returns `controller.Actuator` objects wrapping one existing
operator knob; none of them invents new mechanism. Two families:

  * knob nudges (reversible) — multiplicative scaling of a live runtime
    value with the old value as the undo token: cache promotion
    threshold (blobstore/cache.py `promote_hits`), blobnode scrub token
    budget (`_scrub_bucket.rate`), QoS parent-bucket rate
    (utils/qos.py FairLimiter parent). The strict-improvement gate rolls
    these back when the triggering alert does not resolve in the settle
    window.
  * sweeps (irreversible) — the master's `rebalance_hot` /
    `rebalance_meta` partition moves. A move cannot be un-moved; the
    gate still records the verdict (`autopilot_rolled_back` with
    reversed=false) so the timeline says whether the sweep helped.

`master_actuators` binds a local Master object (the in-process master
daemon registration); `client_actuators` binds a MasterClient (the
console-fed cfs-capacity `--autopilot` controller, which acts on the
cluster from outside).
"""

from __future__ import annotations

from chubaofs_tpu.autopilot.controller import Actuator, Binding


def knob_nudge(name: str, getter, setter, factor: float,
               floor: float | None = None, ceiling: float | None = None,
               description: str = "") -> Actuator:
    """A reversible multiplicative nudge on one live knob: apply scales
    the current value by `factor` (clamped to [floor, ceiling]) and
    returns the old value; rollback restores it. Int knobs stay ints."""

    def _apply(fp, report):
        old = getter()
        new = old * factor
        if floor is not None:
            new = max(floor, new)
        if ceiling is not None:
            new = min(ceiling, new)
        if isinstance(old, int):
            new = int(round(new))
        setter(new)
        return old

    def _rollback(old):
        setter(old)

    return Actuator(name, apply=_apply, rollback=_rollback,
                    description=description or
                    f"scale by {factor} (undo restores)")


def cache_promote_nudge(cache, factor: float = 0.5) -> Actuator:
    """Cache-miss burn: HALVE the promotion threshold so hot keys reach
    the cache sooner (floor 1 — never disable promotion)."""
    return knob_nudge(
        "nudge_promote",
        lambda: cache.promote_hits,
        lambda v: setattr(cache, "promote_hits", v),
        factor, floor=1,
        description="lower cache promote_hits (promote sooner)")


def scrub_shed(node, factor: float = 0.5) -> Actuator:
    """Repair backlog: shed the CRC-scrub token budget so repair traffic
    gets the spindle. Raises at apply time when the node has no scrub
    bucket armed (surfaces as an autopilot error decision, not silence)."""

    def _get():
        if node._scrub_bucket is None:
            raise RuntimeError("scrub bucket not armed (CFS_SCRUB_RATE=0)")
        return node._scrub_bucket.rate

    def _set(v):
        node._scrub_bucket.rate = v

    return knob_nudge("shed_scrub", _get, _set, factor, floor=1.0,
                      description="shed scrub token budget for repair")


def qos_parent_nudge(plane, factor: float = 1.25) -> Actuator:
    """Tenant throttle-ratio burn: grow the QoS parent (borrow-pool)
    bucket so queued tenants drain — the parent-bucket rebalance."""

    def _get():
        if plane.rate is None or plane.rate.parent is None:
            raise RuntimeError("QoS rate parent bucket not configured")
        return plane.rate.parent.rate

    def _set(v):
        plane.rate.parent.rate = v

    return knob_nudge("qos_rebalance", _get, _set, factor,
                      description="grow QoS parent rate bucket")


def master_actuators(master, factor: float = 1.2,
                     max_moves: int = 2) -> list[Actuator]:
    """The master daemon's in-process sweeps (registered after boot).
    Leader-gated: a follower's apply raises, which the controller
    records as an error decision rather than a silent no-op.
    Irreversible: replica moves have no undo."""

    def _sweep(fn):
        def _apply(fp, report):
            if not getattr(master, "is_leader", True):
                raise RuntimeError("not the raft leader")
            return {"moved": fn(factor=factor, max_moves=max_moves)}

        return _apply

    return [
        Actuator("rebalance_hot", apply=_sweep(master.rebalance_hot),
                 description="shed hottest data replicas to cold nodes"),
        Actuator("rebalance_meta", apply=_sweep(master.rebalance_meta),
                 description="migrate hottest meta partitions"),
    ]


def client_actuators(client, factor: float = 1.2,
                     max_moves: int = 2) -> list[Actuator]:
    """MasterClient-backed sweeps for a console-fed controller (the
    cfs-capacity --autopilot harness): same names, acting over HTTP."""
    return [
        Actuator("rebalance_hot",
                 apply=lambda fp, report: client.rebalance_hot(
                     factor=factor, max_moves=max_moves),
                 description="HTTP /dataNode/rebalanceHot sweep"),
        Actuator("rebalance_meta",
                 apply=lambda fp, report: client.rebalance_meta(
                     factor=factor, max_moves=max_moves),
                 description="HTTP /metaPartition/rebalance sweep"),
    ]


def default_bindings(cooldown_s: float | None = None,
                     settle_s: float | None = None) -> list[Binding]:
    """The stock alert→actuator map (mirrors alerts.default_rules(): one
    set serves every daemon; a binding whose actuator never registers
    shows disarmed in status and decides nothing). Clocks default from
    CFS_AUTOPILOT_COOLDOWN_S / CFS_AUTOPILOT_SETTLE_S."""
    from chubaofs_tpu.autopilot.controller import _env_f

    cd = float(cooldown_s if cooldown_s is not None
               else _env_f("CFS_AUTOPILOT_COOLDOWN_S", 60.0))
    st = float(settle_s if settle_s is not None
               else _env_f("CFS_AUTOPILOT_SETTLE_S", 30.0))
    mk = lambda *a, **kw: Binding(*a, cooldown_s=cd, settle_s=st, **kw)
    return [
        mk("hot-put-rebalance", "slo_failing", "rebalance_hot",
           match_labels=(("slo", "put_p99"),),
           description="PUT p99 burn: shed hot data replicas"),
        mk("hot-get-rebalance", "slo_failing", "rebalance_hot",
           match_labels=(("slo", "get_p99"),),
           description="GET p99 burn: shed hot data replicas"),
        mk("cache-promote", "slo_failing", "nudge_promote",
           match_labels=(("slo", "cache_miss_ratio"),),
           description="cache-miss burn: promote sooner"),
        mk("repair-shed", "repair_backlog", "shed_scrub",
           description="repair backlog: shed scrub token budget"),
        mk("tenant-qos", "slo_failing", "qos_rebalance",
           match_labels=(("slo", "qos_throttle:*"),),
           description="tenant throttle burn: grow QoS parent bucket"),
    ]
