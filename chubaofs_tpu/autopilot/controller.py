"""Closed-loop autopilot — firing alerts drive actuators, auditably.

Every signal and every actuator in this codebase exists separately: burn
alerts (utils/alerts.py), hot-volume rebalance (master), meta
split/rebalance, tier promotion thresholds (blobstore/cache.py), QoS
shaping (utils/qos.py), scrub/repair budgets (blobstore). An operator
still has to read cfs-top and call cfs-cli. This module closes the loop:
a controller subscribes to the alert firing→resolved lifecycle
(`alerts.on_firing` / `alerts.on_resolved`) and maps firing alerts to
actuators through declarative BINDINGS.

Safety is the design center, not a rider:

  * strict-improvement gate — after an actuator runs, the triggering
    alert must RESOLVE within the binding's settle window; if it does
    not, the nudge is rolled back (when the actuator is reversible) and
    the failure is on the timeline either way;
  * per-actuator cooldowns — one nudge per actuator per cooldown window;
  * flap damping — an alert that resolves and re-fires inside the flap
    window backs off EXPONENTIALLY (a flapping signal must not drive an
    oscillating actuator);
  * bounded action budget — at most CFS_AUTOPILOT_BUDGET real actions
    per sliding hour, refusals recorded;
  * dry-run — intended actions are logged (autopilot_executed with
    dry_run=true) without touching the cluster.

Observability IS the product: every decision — considered, damped,
budget-refused, executed, rolled-back — is a typed `autopilot_*` event
carrying the causal alert fingerprint, so `cfs-events --correlate <fp>`
renders the full `alert fired → action taken → alert resolved` causal
chain. Controller state (armed bindings, cooldown clocks, remaining
budget, last N decisions) is served at the `/autopilot` side-door and by
`cfs-cli autopilot status`.

Two feed modes, one decision pipeline:

  * in-process — `attach()` subscribes to this process's alert hooks
    (armed at daemon boot by `activate_from_env()` when CFS_AUTOPILOT is
    set); the master daemon registers its rebalance/split actuators at
    boot;
  * console-fed — `observe_rollup(alerts)` feeds the controller from a
    console `/api/alerts` rollup (the cfs-capacity `--autopilot` mode),
    deduping firing↔resolved transitions by fingerprint itself, with
    MasterClient-backed actuators.

Knobs (all read at activation): CFS_AUTOPILOT (arm), CFS_AUTOPILOT_DRY
(dry-run), CFS_AUTOPILOT_BUDGET (actions/hour, default 6),
CFS_AUTOPILOT_FLAP_S (flap window, default 120), CFS_AUTOPILOT_BACKOFF_S
(base flap back-off, default 60), CFS_AUTOPILOT_COOLDOWN_S /
CFS_AUTOPILOT_SETTLE_S (default binding clocks), CFS_AUTOPILOT_TICK_S
(settle-gate sweep cadence when armed, default 5).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass

from chubaofs_tpu.utils import events
from chubaofs_tpu.utils.locks import SanitizedLock

BUDGET_WINDOW_S = 3600.0  # the sliding budget hour
MAX_BACKOFF_S = 3600.0    # flap back-off cap

# the closed decision vocabulary (bounded metric label, mirrors the
# autopilot_* event taxonomy plus the two non-event outcomes)
DECISIONS = ("considered", "damped", "refused", "executed",
             "rolled_back", "confirmed", "error")


def _env_f(name: str, default: float) -> float:
    try:
        v = float(os.environ.get(name, "") or default)
    except ValueError:
        return default
    return v


@dataclass(frozen=True)
class Binding:
    """One alert-rule → actuator arm. `match_labels` (a tuple of (k, v)
    pairs) restricts the arm to instances whose labels carry that subset
    — e.g. rule="slo_failing", match_labels=(("slo", "put_p99"),)."""

    name: str
    rule: str
    actuator: str
    match_labels: tuple = ()
    cooldown_s: float = 60.0
    settle_s: float = 30.0
    description: str = ""

    def matches(self, report: dict) -> bool:
        if report.get("name") != self.rule:
            return False
        labels = report.get("labels") or {}
        for k, v in self.match_labels:
            got = str(labels.get(k, ""))
            # a trailing * prefix-matches (per-tenant SLO names like
            # qos_throttle:<tenant> are one binding, not one per tenant)
            if v.endswith("*"):
                if not got.startswith(v[:-1]):
                    return False
            elif got != v:
                return False
        return True


@dataclass
class Actuator:
    """A named remediation. `apply(fingerprint, report)` performs the
    nudge and returns an undo token; `rollback(token)` (optional)
    reverses it — knob nudges are reversible, replica moves are not, and
    the strict-improvement gate records which it got."""

    name: str
    apply: object  # callable(fp, report) -> undo token
    rollback: object = None  # callable(token) | None
    description: str = ""


class Autopilot:
    """The decision pipeline + safety gates + decision ring."""

    DECISIONS_KEEP = 64

    def __init__(self, bindings: list[Binding] | None = None,
                 actuators: dict[str, Actuator] | None = None, *,
                 budget_per_hour: int | None = None,
                 flap_window_s: float | None = None,
                 flap_backoff_s: float | None = None,
                 dry_run: bool = False, enabled: bool = True,
                 clock=time.monotonic):
        self.bindings: list[Binding] = list(bindings or [])
        self.actuators: dict[str, Actuator] = dict(actuators or {})
        self.budget_per_hour = int(
            budget_per_hour if budget_per_hour is not None
            else _env_f("CFS_AUTOPILOT_BUDGET", 6))
        self.flap_window_s = float(
            flap_window_s if flap_window_s is not None
            else _env_f("CFS_AUTOPILOT_FLAP_S", 120.0))
        self.flap_backoff_s = float(
            flap_backoff_s if flap_backoff_s is not None
            else _env_f("CFS_AUTOPILOT_BACKOFF_S", 60.0))
        self.dry_run = bool(dry_run)
        self.enabled = bool(enabled)
        self._clock = clock
        self._lock = SanitizedLock(name="autopilot.controller")
        self._decisions: list[dict] = []      # bounded ring, newest last
        self._budget_stamps: list[float] = []  # mono stamps of real actions
        self._cooldown_until: dict[str, float] = {}   # actuator -> mono
        # flap state per fingerprint: resolved_at (mono), flaps (count),
        # blocked_until (mono) — exponential back-off lives here
        self._flap: dict[str, dict] = {}
        # strict-improvement gates: fp -> pending action awaiting resolve
        self._pending: dict[str, dict] = {}
        self._rollup_firing: set[str] = set()
        self._attached = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # the bounded-label contract: decision is a closed vocabulary,
        # a typo'd decision string fails at the metric call
        from chubaofs_tpu.utils.exporter import declare_label_values

        declare_label_values("decision", DECISIONS)
        self._publish_gauges()

    # -- metrics ---------------------------------------------------------------

    def _registry(self):
        from chubaofs_tpu.utils.exporter import registry

        return registry("autopilot")

    def _publish_gauges(self) -> None:
        reg = self._registry()
        reg.gauge("armed").set(1.0 if self.enabled else 0.0)
        reg.gauge("budget_remaining").set(float(self._budget_remaining()))

    def _budget_remaining(self) -> int:
        now = self._clock()
        with self._lock:
            self._budget_stamps = [t for t in self._budget_stamps
                                   if now - t < BUDGET_WINDOW_S]
            return max(0, self.budget_per_hour - len(self._budget_stamps))

    # -- decision ring ---------------------------------------------------------

    def _record(self, decision: str, fp: str, report: dict,
                binding: Binding | None = None, **extra) -> dict:
        rec = {"ts": time.time(), "decision": decision, "fingerprint": fp,
               "rule": report.get("name", ""),
               "binding": binding.name if binding else "",
               "actuator": binding.actuator if binding else ""}
        rec.update(extra)
        with self._lock:
            self._decisions.append(rec)
            if len(self._decisions) > self.DECISIONS_KEEP:
                del self._decisions[: len(self._decisions)
                                    - self.DECISIONS_KEEP]
        self._registry().counter("decisions", {"decision": decision}).add()
        return rec

    def _emit_decision(self, etype: str, decision: str, fp: str,
                       report: dict, binding: Binding | None = None,
                       severity: str = events.SEV_INFO, **extra) -> dict:
        rec = self._record(decision, fp, report, binding, **extra)
        detail = {k: v for k, v in rec.items() if k != "ts"}
        events.emit(etype, severity,
                    entity=binding.name if binding else report.get("name", ""),
                    detail=detail)
        return rec

    # -- lifecycle entry points ------------------------------------------------

    def observe_firing(self, fp: str, report: dict) -> None:
        """The firing-edge entry point (alert hook / rollup feed). Runs
        the full pipeline: match → flap damper → cooldown → budget →
        execute (or dry-run) → arm the strict-improvement gate."""
        if not self.enabled:
            return
        self.tick()  # sweep overdue settle gates before deciding anew
        for binding in self.bindings:
            if binding.matches(report):
                self._decide(binding, fp, report)

    def observe_resolved(self, fp: str, report: dict) -> None:
        """The resolved edge: confirms a pending nudge (strict
        improvement) and starts the flap clock for this fingerprint."""
        now = self._clock()
        with self._lock:
            st = self._flap.setdefault(fp, {"flaps": 0, "blocked_until": 0.0})
            st["resolved_at"] = now
            pending = self._pending.pop(fp, None)
        if pending is not None:
            self._record("confirmed", fp, report, pending["binding"],
                         settle_s=round(now - pending["applied_at"], 3))

    def _decide(self, binding: Binding, fp: str, report: dict) -> None:
        self._emit_decision("autopilot_considered", "considered", fp,
                            report, binding)
        now = self._clock()
        damp: tuple[str, dict] | None = None
        with self._lock:
            st = self._flap.get(fp)
            if st is not None:
                resolved_at = st.get("resolved_at")
                if resolved_at is not None \
                        and now - resolved_at < self.flap_window_s:
                    # firing→resolved→firing inside the window: a flap.
                    # Exponential back-off, capped.
                    st["flaps"] += 1
                    backoff = min(
                        self.flap_backoff_s * (2 ** (st["flaps"] - 1)),
                        MAX_BACKOFF_S)
                    st["blocked_until"] = max(st["blocked_until"],
                                              now + backoff)
                    st.pop("resolved_at", None)
                    damp = ("flap", {"flaps": st["flaps"],
                                     "backoff_s": round(backoff, 3)})
                else:
                    if resolved_at is not None:
                        # a stable resolution ends the flap episode
                        st["flaps"] = 0
                        st.pop("resolved_at", None)
                    if now < st.get("blocked_until", 0.0):
                        damp = ("backoff",
                                {"remaining_s":
                                 round(st["blocked_until"] - now, 3)})
            if damp is None:
                until = self._cooldown_until.get(binding.actuator, 0.0)
                if now < until:
                    damp = ("cooldown",
                            {"remaining_s": round(until - now, 3)})
                elif fp in self._pending:
                    # a nudge for this alert is already settling — one
                    # gate per fingerprint, no stacked actions
                    damp = ("settling", {})
        if damp is not None:
            reason, extra = damp
            sev = events.SEV_WARNING if reason == "flap" else events.SEV_INFO
            self._emit_decision("autopilot_damped", "damped", fp, report,
                                binding, severity=sev, reason=reason,
                                **extra)
            return
        if not self.dry_run and self._budget_remaining() <= 0:
            self._emit_decision("autopilot_refused", "refused", fp, report,
                                binding, severity=events.SEV_WARNING,
                                reason="budget",
                                budget_per_hour=self.budget_per_hour)
            self._publish_gauges()
            return
        self._execute(binding, fp, report)

    def _execute(self, binding: Binding, fp: str, report: dict) -> None:
        """Run (or dry-run) the bound actuator. obslint rule 9 contract:
        the actuator invocation and its autopilot_* emit share this
        function — no silent actions."""
        act = self.actuators.get(binding.actuator)
        now = self._clock()
        if self.dry_run:
            self._emit_decision("autopilot_executed", "executed", fp,
                                report, binding, dry_run=True,
                                available=act is not None)
            return
        if act is None:
            self._emit_decision("autopilot_executed", "error", fp, report,
                                binding, severity=events.SEV_WARNING,
                                error=f"actuator {binding.actuator!r} "
                                      "not registered")
            return
        with self._lock:
            self._cooldown_until[binding.actuator] = now + binding.cooldown_s
            self._budget_stamps.append(now)
        try:
            undo = act.apply(fp, report)
        except Exception as e:
            self._emit_decision("autopilot_executed", "error", fp, report,
                                binding, severity=events.SEV_WARNING,
                                error=str(e))
            self._publish_gauges()
            return
        with self._lock:
            self._pending[fp] = {"binding": binding, "undo": undo,
                                 "applied_at": now,
                                 "deadline": now + binding.settle_s,
                                 "report": dict(report)}
        self._emit_decision("autopilot_executed", "executed", fp, report,
                            binding, dry_run=False,
                            reversible=act.rollback is not None,
                            settle_s=binding.settle_s,
                            budget_remaining=self._budget_remaining())
        self._publish_gauges()

    # -- strict-improvement sweep ----------------------------------------------

    def tick(self) -> int:
        """Roll back pending nudges whose settle window expired with the
        alert still firing (the strict-improvement gate). Returns the
        number of rollbacks. Call-driven (every observe) plus the armed
        periodic thread; obslint rule 9: rollback and its emit share
        this function."""
        now = self._clock()
        with self._lock:
            due = [(fp, p) for fp, p in self._pending.items()
                   if now >= p["deadline"]]
            for fp, _ in due:
                del self._pending[fp]
        for fp, p in due:
            binding = p["binding"]
            act = self.actuators.get(binding.actuator)
            reversed_ok, err = False, ""
            if act is not None and act.rollback is not None:
                try:
                    act.rollback(p["undo"])
                    reversed_ok = True
                except Exception as e:
                    err = str(e)
            with self._lock:
                # a nudge that did not help must not immediately re-run:
                # the failed fingerprint inherits the flap back-off clock
                st = self._flap.setdefault(
                    fp, {"flaps": 0, "blocked_until": 0.0})
                st["blocked_until"] = max(st["blocked_until"],
                                          now + self.flap_backoff_s)
            self._emit_decision(
                "autopilot_rolled_back", "rolled_back", fp, p["report"],
                binding, severity=events.SEV_WARNING, reversed=reversed_ok,
                **({"error": err} if err else {}))
        if due:
            self._publish_gauges()
        return len(due)

    # -- console-fed mode ------------------------------------------------------

    def observe_rollup(self, alerts: list[dict]) -> None:
        """Feed one /api/alerts rollup poll: the controller dedups the
        firing↔resolved edges by fingerprint itself (the console-fed
        capacity-harness mode, where no in-process hook exists)."""
        from chubaofs_tpu.utils.alerts import STATE_FIRING, fingerprint

        now_firing: dict[str, dict] = {}
        for rep in alerts or []:
            if rep.get("state") == STATE_FIRING and not rep.get("silenced"):
                fp = fingerprint(rep.get("name", ""), rep.get("labels"))
                now_firing[fp] = rep
        with self._lock:
            prev = set(self._rollup_firing)
            self._rollup_firing = set(now_firing)
        for fp, rep in now_firing.items():
            if fp not in prev:
                self.observe_firing(fp, rep)
        for fp in prev - set(now_firing):
            name = fp.split("|", 1)[0]
            self.observe_resolved(fp, {"name": name})
        self.tick()

    # -- in-process hook subscription ------------------------------------------

    def attach(self) -> "Autopilot":
        from chubaofs_tpu.utils import alerts

        if not self._attached:
            alerts.on_firing(self.observe_firing)
            alerts.on_resolved(self.observe_resolved)
            self._attached = True
        return self

    def detach(self) -> None:
        from chubaofs_tpu.utils import alerts

        if self._attached:
            alerts.remove_firing_hook(self.observe_firing)
            alerts.remove_resolved_hook(self.observe_resolved)
            self._attached = False

    # -- registration ----------------------------------------------------------

    def register(self, actuator: Actuator,
                 bindings: list[Binding] | None = None) -> None:
        """Late-bind an actuator (daemons register theirs after boot —
        the master adds rebalance/split once its raft group is up)."""
        with self._lock:
            self.actuators[actuator.name] = actuator
            for b in bindings or []:
                if all(x.name != b.name for x in self.bindings):
                    self.bindings.append(b)

    # -- control + report surface ----------------------------------------------

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = bool(enabled)
        self._publish_gauges()

    def set_dry_run(self, dry_run: bool) -> None:
        self.dry_run = bool(dry_run)

    def status(self) -> dict:
        """The /autopilot payload: armed bindings, cooldown clocks,
        remaining budget, pending settle gates, last N decisions."""
        now = self._clock()
        with self._lock:
            cooldowns = {name: round(until - now, 3)
                         for name, until in self._cooldown_until.items()
                         if until > now}
            pending = [{"fingerprint": fp, "binding": p["binding"].name,
                        "actuator": p["binding"].actuator,
                        "settle_remaining_s": round(p["deadline"] - now, 3)}
                       for fp, p in self._pending.items()]
            decisions = [dict(d) for d in self._decisions]
        return {"enabled": self.enabled, "dry_run": self.dry_run,
                "budget": {"per_hour": self.budget_per_hour,
                           "used": self.budget_per_hour
                                   - self._budget_remaining(),
                           "remaining": self._budget_remaining()},
                "bindings": [{"name": b.name, "rule": b.rule,
                              "labels": dict(b.match_labels),
                              "actuator": b.actuator,
                              "armed": b.actuator in self.actuators,
                              "cooldown_s": b.cooldown_s,
                              "settle_s": b.settle_s,
                              "description": b.description}
                             for b in self.bindings],
                "actuators": sorted(self.actuators),
                "cooldowns": cooldowns, "pending": pending,
                "decisions": decisions}

    # -- periodic settle sweep (the metrichist arming discipline) --------------

    @property
    def armed(self) -> bool:
        return self._thread is not None

    def start(self, period_s: float) -> "Autopilot":
        if self._thread is not None:
            return self
        self._stop.clear()

        def _run():
            while not self._stop.wait(period_s):
                try:
                    self.tick()
                except Exception:
                    pass  # one bad sweep must not kill the gate thread

        self._thread = threading.Thread(target=_run, daemon=True,
                                        name="cfs-autopilot")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self.detach()


# -- process-wide default ------------------------------------------------------

_default: Autopilot | None = None
_dlock = threading.Lock()


def enabled_from_env() -> bool:
    return (os.environ.get("CFS_AUTOPILOT", "") or "").lower() \
        in ("1", "true", "on", "yes")


def default_controller() -> Autopilot:
    """The process controller, created on first use (disabled until
    CFS_AUTOPILOT arms it or /autopilot op=enable flips it)."""
    from chubaofs_tpu.autopilot.actuators import default_bindings

    global _default
    with _dlock:
        if _default is None:
            _default = Autopilot(
                bindings=default_bindings(),
                enabled=enabled_from_env(),
                dry_run=(os.environ.get("CFS_AUTOPILOT_DRY", "") or "")
                .lower() in ("1", "true", "on", "yes"))
        return _default


def activate_from_env() -> Autopilot | None:
    """Daemon-boot hook (rpc/server.py): arm the controller iff
    CFS_AUTOPILOT asks for it — unset env means no controller object, no
    hook subscription, no thread (zero overhead, the metrichist
    discipline). Daemons register their actuators afterwards."""
    if not enabled_from_env():
        return _default
    ap = default_controller().attach()
    return ap.start(_env_f("CFS_AUTOPILOT_TICK_S", 5.0))


def deactivate() -> None:
    """Stop + forget the process controller (test isolation)."""
    global _default
    with _dlock:
        ap, _default = _default, None
    if ap is not None:
        ap.stop()


def autopilot_status() -> dict:
    """The /autopilot payload for THIS process; a never-created
    controller reports disarmed without minting one."""
    with _dlock:
        ap = _default
    if ap is None:
        return {"enabled": False, "dry_run": False, "bindings": [],
                "actuators": [], "cooldowns": {}, "pending": [],
                "decisions": [],
                "budget": {"per_hour": 0, "used": 0, "remaining": 0}}
    return ap.status()
