"""Closed-loop autopilot: alerts drive actuators, auditably (ISSUE 20).

See controller.py for the decision pipeline and safety gates,
actuators.py for the remediation registry.
"""

from chubaofs_tpu.autopilot.actuators import (  # noqa: F401
    cache_promote_nudge,
    client_actuators,
    default_bindings,
    knob_nudge,
    master_actuators,
    qos_parent_nudge,
    scrub_shed,
)
from chubaofs_tpu.autopilot.controller import (  # noqa: F401
    DECISIONS,
    Actuator,
    Autopilot,
    Binding,
    activate_from_env,
    autopilot_status,
    deactivate,
    default_controller,
    enabled_from_env,
)
