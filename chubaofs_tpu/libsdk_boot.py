"""Bootstrap the embedded-interpreter side of libcfs.so.

Reference counterpart: libsdk/libsdk.go's newClient — parse the config,
build the SDK stack for one volume, hand back the handle the C ABI
dispatches on. The C++ shim (native/libsdk/libcfs.cc) imports exactly this
module and calls `new_mount(config_json)`.
"""

from __future__ import annotations

import json

from chubaofs_tpu.client.mount import Mount
from chubaofs_tpu.sdk.cluster import RemoteCluster


def new_mount(config_json: str) -> Mount:
    cfg = json.loads(config_json)
    masters = cfg.get("masterAddr") or cfg.get("masterAddrs")
    if isinstance(masters, str):
        masters = [masters]
    if not masters:
        raise ValueError("config needs masterAddr")
    vol = cfg.get("volName")
    if not vol:
        raise ValueError("config needs volName")
    access = cfg.get("accessAddr") or cfg.get("accessAddrs")
    if isinstance(access, str):
        access = [access]
    cluster = RemoteCluster(masters, access_addrs=access)
    fs = cluster.client(vol)
    return Mount(fs, volume=vol, audit_dir=cfg.get("logDir"),
                 client_id=cfg.get("clientId", ""))
