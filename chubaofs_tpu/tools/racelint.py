"""racelint — static lint for lock discipline (the concurrency half of the
static-analysis plane; obslint is the observability half).

The Go reference keeps its daemons honest with `go test -race` and vet; a
Python port gets neither, and this package carries ~50 threading.Lock/RLock/
Condition instances across the raft drain pump, the PUT pipeline window, the
conn pools, the trace sink, and the codec dispatcher. These rules catch the
mistakes that actually bite that kind of code:

1. **Guarded-field escape** (`guarded-field-escape`). Within one class, an
   attribute that is written under `with self._lock:` in one method but
   written bare in another has no discipline at all — the guarded sites pay
   for a contract the bare site silently voids. Writes include plain/aug
   assignment, subscript stores/deletes, and the standard container mutators
   (`append`, `pop`, `update`, ...). `__init__`/`__new__` are construction
   (happens-before publication) and exempt; methods whose name ends in
   `_locked` declare "caller holds the lock" (the reference's `fooLocked`
   convention) and count as guarded.

2. **Threaded global mutation** (`threaded-global-mutation`). Module-level
   mutable state (dict/list/set/deque literals or constructors) mutated
   outside any lock from a method of a class that also spawns threads or
   executors: the class proved it runs concurrently, so its bare writes to
   shared module state are races by construction.

3. **Unjoined thread** (`unjoined-thread`). A `threading.Thread` /
   `ThreadPoolExecutor` created with no reachable `join`/`shutdown`: not
   daemonized, not a `with` block, and no `<target>.join()`/`.shutdown()`
   call anywhere in scope (its CLASS for `self.x`, the enclosing function
   for locals — a same-named handle joined elsewhere in the file does not
   count). Leaked workers outlive their owner, pin its state alive, and
   turn shutdown into a hang.

4. **Check-then-act** (`check-then-act`). `if k in d: del d[k]` (and
   `d.pop(k)`, and `if k not in d: d[k] = ...`) on a `self.*` or
   module-level dict outside a lock: the membership test and the mutation
   are separate bytecodes, and another thread can interleave between them.
   Locals are exempt (unshared by construction).

5. **Thread-per-connection serving** (`thread-per-conn`). A
   `threading.Thread(target=..., args=(conn,...))` spawned per accepted
   connection is the scaling wall ISSUE 8 removed: at hundreds of clients
   the thread stacks and GIL churn dominate before the network does.
   Packet serving rides `rpc/evloop.py` (loop shards + bounded workers);
   the CFS_EVLOOP=0 rollback shims carry the pragma. `rpc/evloop.py` and
   `proto/packet.py` are exempt by path (they ARE the sanctioned layer).

Exceptions carry a `# racelint: <why>` pragma on the flagged line, or a
per-file allowlist entry below — both REQUIRE a written reason. Shared
walk/pragma/CLI plumbing: tools/lintcore.py. Wired into tier-1
(tests/test_racelint.py); the runtime half of the same plan is
utils/locks.py (the CFS_LOCK_SANITIZER lock-order sanitizer).
"""

from __future__ import annotations

import ast

from chubaofs_tpu.tools import lintcore

PRAGMA = "racelint"

# Per-file allowlist: path suffix -> {rule: reason}. An entry suppresses that
# RULE for that file and MUST carry a written reason (it is the file-wide
# sibling of the line pragma). Currently empty: every in-tree exception is
# narrow enough for a `# racelint: <why>` on the flagged line.
ALLOWLIST: dict[str, dict[str, str]] = {}

# container-mutating method names that count as writes for rule 1
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault",
}

# names that make a `with` item a lock guard (threading.Lock/RLock/Condition
# attributes by convention: self._lock, g.pending_lock, _LOCK, self._cond)
def _is_lockish_name(name: str) -> bool:
    low = name.lower()
    return any(t in low for t in ("lock", "cond", "mutex", "mtx"))


def _is_lockish_expr(expr: ast.expr) -> bool:
    if isinstance(expr, ast.Attribute):
        return _is_lockish_name(expr.attr)
    if isinstance(expr, ast.Name):
        return _is_lockish_name(expr.id)
    return False


def _with_is_guard(node: ast.With) -> bool:
    return any(_is_lockish_expr(item.context_expr) for item in node.items)


def _self_attr(expr: ast.expr) -> str | None:
    """'x' when expr is `self.x`, else None."""
    if (isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"):
        return expr.attr
    return None


def _mutable_literal(value: ast.expr) -> bool:
    """Dict/list/set literal, comprehension, or bare dict()/list()/set()/
    deque()/defaultdict() constructor — module state a thread can mutate."""
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                          ast.ListComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call):
        fn = value.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        return name in ("dict", "list", "set", "deque", "defaultdict",
                        "OrderedDict", "Counter")
    return False


def _thread_call_kind(node: ast.Call) -> str | None:
    """'thread' / 'executor' when node constructs one, else None."""
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else "")
    if name == "Thread":
        return "thread"
    if name == "ThreadPoolExecutor":
        return "executor"
    return None


class _Write:
    __slots__ = ("attr", "lineno", "guarded")

    def __init__(self, attr: str, lineno: int, guarded: bool):
        self.attr = attr
        self.lineno = lineno
        self.guarded = guarded


def _scan_writes(body: list[ast.stmt], depth: int, out: list[_Write],
                 global_muts: list[tuple[str, int, bool]],
                 module_globals: set[str]) -> None:
    """Walk statements tracking lock depth; record self-attribute writes and
    module-global mutations with their guardedness."""

    def record_target(tgt: ast.expr, lineno: int) -> None:
        attr = _self_attr(tgt)
        if attr is not None and not _is_lockish_name(attr) \
                and not attr.startswith("__"):
            out.append(_Write(attr, lineno, depth > 0))
        if isinstance(tgt, ast.Subscript):
            base = tgt.value
            attr = _self_attr(base)
            if attr is not None and not _is_lockish_name(attr):
                out.append(_Write(attr, lineno, depth > 0))
            if isinstance(base, ast.Name) and base.id in module_globals:
                global_muts.append((base.id, lineno, depth > 0))

    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # nested defs run later, on their caller's terms
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = depth + 1 if _with_is_guard(stmt) else depth
            _scan_writes(stmt.body, inner, out, global_muts, module_globals)
            continue
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                record_target(tgt, stmt.lineno)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            if stmt.value is not None or isinstance(stmt, ast.AugAssign):
                record_target(stmt.target, stmt.lineno)
                if isinstance(stmt, ast.AugAssign) \
                        and isinstance(stmt.target, ast.Name) \
                        and stmt.target.id in module_globals:
                    global_muts.append((stmt.target.id, stmt.lineno, depth > 0))
        elif isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                record_target(tgt, stmt.lineno)
        # recurse into compound statements (if/for/while/try bodies)
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:  # With/AsyncWith never reach here (handled above)
                _scan_writes(sub, depth, out, global_muts, module_globals)
        for handler in getattr(stmt, "handlers", ()) or ():
            _scan_writes(handler.body, depth, out, global_muts, module_globals)
        # expression statements: container mutator calls on self.x / globals
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            fn = stmt.value.func
            if isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS:
                attr = _self_attr(fn.value)
                if attr is not None and not _is_lockish_name(attr):
                    out.append(_Write(attr, stmt.lineno, depth > 0))
                if isinstance(fn.value, ast.Name) \
                        and fn.value.id in module_globals:
                    global_muts.append((fn.value.id, stmt.lineno, depth > 0))


def _module_mutable_globals(tree: ast.Module) -> set[str]:
    out: set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and _mutable_literal(stmt.value):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name) and not _is_lockish_name(tgt.id):
                    out.add(tgt.id)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                and _mutable_literal(stmt.value) \
                and isinstance(stmt.target, ast.Name) \
                and not _is_lockish_name(stmt.target.id):
            out.add(stmt.target.id)
    return out


def _class_spawns_threads(cls: ast.ClassDef) -> bool:
    for node in ast.walk(cls):
        if isinstance(node, ast.Call) and _thread_call_kind(node):
            return True
    return False


_CTOR_SEEDS = ("__init__", "__new__", "__del__", "__post_init__")


def _construction_only_methods(cls: ast.ClassDef) -> set[str]:
    """Methods whose every intra-class call site is inside __init__/__new__
    (transitively): they run before the object is published, so their bare
    writes are construction, not races. Methods with NO intra-class callers
    are public API and never qualify."""
    methods = {m.name: m for m in cls.body
               if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}
    callers: dict[str, set[str]] = {name: set() for name in methods}
    for name, m in methods.items():
        for node in ast.walk(m):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                callee = _self_attr(node.func)
                if callee in callers:
                    callers[callee].add(name)
    result: set[str] = set()
    changed = True
    while changed:
        changed = False
        for name in methods:
            if name in result or name in _CTOR_SEEDS:
                continue
            cs = callers[name]
            if cs and all(c in _CTOR_SEEDS or c in result for c in cs):
                result.add(name)
                changed = True
    return result


# -- rule 3 helpers ------------------------------------------------------------


def _call_has_true_kw(call: ast.Call, kw_name: str) -> bool:
    for kw in call.keywords:
        if kw.arg == kw_name and isinstance(kw.value, ast.Constant) \
                and kw.value.value is True:
            return True
    return False


def _joinish_targets(tree: ast.AST) -> tuple[set[str], set[str]]:
    """(self attrs, local names) that have .join()/.shutdown() called on
    them anywhere under `tree`."""
    attrs: set[str] = set()
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("join", "shutdown"):
            base = node.func.value
            attr = _self_attr(base)
            if attr is not None:
                attrs.add(attr)
            elif isinstance(base, ast.Name):
                names.add(base.id)
    return attrs, names


def _with_context_calls(tree: ast.AST) -> set[int]:
    """Line numbers of calls used directly as `with <call>(...)` items —
    context-managed executors shut down on exit."""
    out: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    out.add(id(item.context_expr))
    return out


# -- the pass ------------------------------------------------------------------


def lint_source(src: str, relpath: str) -> list[str]:
    """Lint one file's source; returns human-readable findings tagged with
    their rule id."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{relpath}: syntax error: {e}"]
    src_lines = src.splitlines()
    allow = {}
    for sfx, rules in ALLOWLIST.items():
        if lintcore.path_matches(relpath, (sfx,)):
            allow.update(rules)
    findings: list[str] = []

    def flag(rule: str, lineno: int, msg: str) -> None:
        if rule in allow:
            return
        if lintcore.has_pragma(src_lines, lineno, PRAGMA):
            return
        findings.append(f"{relpath}:{lineno}: [{rule}] {msg}")

    module_globals = _module_mutable_globals(tree)

    # -- rules 1 + 2: per-class write-discipline inference --------------------
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        writes: list[_Write] = []
        global_muts: list[tuple[str, int, bool]] = []
        ctor_only = _construction_only_methods(cls)
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if meth.name in _CTOR_SEEDS or meth.name in ctor_only:
                continue  # construction/teardown happens-before publication
            # `*_locked` methods document "caller holds the lock": their
            # writes are guarded at every call site by contract
            depth = 1 if meth.name.endswith("_locked") else 0
            _scan_writes(meth.body, depth, writes, global_muts, module_globals)
        guarded = {w.attr for w in writes if w.guarded}
        for w in writes:
            if not w.guarded and w.attr in guarded:
                flag("guarded-field-escape", w.lineno,
                     f"self.{w.attr} is written under a lock elsewhere in "
                     f"{cls.name} but bare here — either every write holds "
                     "the lock or none meaningfully does; hold the lock, or "
                     "rename the method *_locked if the caller already "
                     "does")
        if global_muts and _class_spawns_threads(cls):
            for name, lineno, is_guarded in global_muts:
                if not is_guarded:
                    flag("threaded-global-mutation", lineno,
                         f"module-level `{name}` mutated without a lock from "
                         f"{cls.name}, which spawns threads/executors — "
                         "shared module state needs a module lock (or move "
                         "the state onto the instance)")

    # -- rule 3: thread/executor creation without reachable join/shutdown -----
    _scan_unjoined(tree, flag)

    # -- rule 4: check-then-act on shared dicts outside a lock ----------------
    _scan_check_then_act(tree, module_globals, flag)

    # -- rule 5: thread-per-connection serving --------------------------------
    _scan_thread_per_conn(tree, relpath, flag)
    return findings


# files that ARE the sanctioned serving layer (rule 5)
_EVLOOP_PATHS = lintcore.PACKET_LAYER_PATHS

# arg names that mark a Thread target as per-connection serving
_CONNISH = ("conn", "sock", "client", "peer")


def _scan_thread_per_conn(tree: ast.AST, relpath: str, flag) -> None:
    """Rule 5: `threading.Thread(target=..., args=(conn,...))` — one thread
    per accepted connection. The evloop core replaced this; only the
    CFS_EVLOOP=0 shims (pragma'd) and evloop/packet themselves may spawn
    per-connection service threads."""
    if lintcore.path_matches(relpath, _EVLOOP_PATHS):
        return
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and _thread_call_kind(node) == "thread"):
            continue
        for kw in node.keywords:
            if kw.arg != "args" or not isinstance(kw.value, ast.Tuple):
                continue
            names = [e.id.lower() for e in kw.value.elts
                     if isinstance(e, ast.Name)]
            if any(any(t in n for t in _CONNISH) for n in names):
                flag("thread-per-conn", node.lineno,
                     "thread-per-connection serving — a full OS thread per "
                     "accepted conn is the scale wall the evloop removed "
                     "(ISSUE 8); register the socket on rpc/evloop.py's "
                     "loop shards instead, or pragma the CFS_EVLOOP=0 shim "
                     "with its reason")
                break


def _assign_target_of(tree: ast.AST, call: ast.Call) -> ast.expr | None:
    """The single assignment target whose value IS `call`, if any."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and node.value is call \
                and len(node.targets) == 1:
            return node.targets[0]
    return None


def _scan_unjoined(tree: ast.Module, flag) -> None:
    """Rule 3, SCOPED: a `self.x` handle counts as joined only if ITS class
    joins/shuts it down; a local only if its enclosing function does. A
    same-named handle joined elsewhere in the file must not whitelist this
    one — that would silently re-open the exact leak class this rule caught
    in Access."""
    ctx_calls = _with_context_calls(tree)
    joins_cache: dict[int, tuple[set[str], set[str]]] = {}

    def joins_of(scope: ast.AST) -> tuple[set[str], set[str]]:
        got = joins_cache.get(id(scope))
        if got is None:
            got = joins_cache[id(scope)] = _joinish_targets(scope)
        return got

    def handle(call: ast.Call, cls: ast.ClassDef | None,
               func: ast.AST) -> None:
        kind = _thread_call_kind(call)
        if id(call) in ctx_calls:
            return  # `with ThreadPoolExecutor(...) as pool:` joins on exit
        if kind == "thread" and _call_has_true_kw(call, "daemon"):
            return  # daemonized: fire-and-forget by declaration
        tgt = _assign_target_of(func, call)
        if tgt is not None:
            attr = _self_attr(tgt)
            if attr is not None and cls is not None \
                    and attr in joins_of(cls)[0]:
                return
            if isinstance(tgt, ast.Name) and tgt.id in joins_of(func)[1]:
                return
        flag("unjoined-thread", call.lineno,
             ("ThreadPoolExecutor" if kind == "executor" else
              "threading.Thread") + " created with no reachable "
             "shutdown/join — leaked workers outlive their owner and turn "
             "shutdown into a hang; daemonize it, `with` it, or keep a "
             "handle you join/shutdown")

    def visit(node: ast.AST, cls, func) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.Call) and _thread_call_kind(child):
                handle(child, cls, func)
            ncls, nfunc = cls, func
            if isinstance(child, ast.ClassDef):
                ncls = child
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nfunc = child
            visit(child, ncls, nfunc)

    visit(tree, None, tree)


def _shared_base(expr: ast.expr, module_globals: set[str]) -> str | None:
    """'self.x' / module-global name when expr is one, else None (locals are
    unshared by construction)."""
    attr = _self_attr(expr)
    if attr is not None:
        return f"self.{attr}"
    if isinstance(expr, ast.Name) and expr.id in module_globals:
        return expr.id
    return None


def _same_shared(a: ast.expr, b: ast.expr, module_globals: set[str]) -> bool:
    sa, sb = _shared_base(a, module_globals), _shared_base(b, module_globals)
    return sa is not None and sa == sb


def _scan_check_then_act(tree: ast.AST, module_globals: set[str],
                         flag) -> None:
    """Find `if k in d:` / `if k not in d:` followed by a mutation of the
    SAME shared d in the branch body, outside any lock `with`."""

    def scan(body: list[ast.stmt], depth: int) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # `*_locked` means "caller holds the lock" — same contract
                # rule 1 honors
                scan(stmt.body, 1 if stmt.name.endswith("_locked") else 0)
                continue
            if isinstance(stmt, ast.ClassDef):
                scan(stmt.body, 0)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                scan(stmt.body, depth + 1 if _with_is_guard(stmt) else depth)
                continue
            if isinstance(stmt, ast.If) and depth == 0:
                hit = _check_then_act_hit(stmt, module_globals)
                if hit:
                    flag("check-then-act", stmt.lineno, hit)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    scan(sub, depth)
            for handler in getattr(stmt, "handlers", ()) or ():
                scan(handler.body, depth)

    scan(tree.body if isinstance(tree, ast.Module) else [], 0)


def _check_then_act_hit(stmt: ast.If, module_globals: set[str]) -> str | None:
    test = stmt.test
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.ops[0], (ast.In, ast.NotIn))):
        return None
    container = test.comparators[0]
    shared = _shared_base(container, module_globals)
    if shared is None:
        return None
    negated = isinstance(test.ops[0], ast.NotIn)
    for inner in ast.walk(stmt):
        if negated:
            # `if k not in d: d[k] = ...` — a racing writer's value is lost
            if isinstance(inner, ast.Assign):
                for tgt in inner.targets:
                    if isinstance(tgt, ast.Subscript) \
                            and _same_shared(tgt.value, container,
                                             module_globals):
                        return (f"`if k not in {shared}: {shared}[k] = ...` "
                                "outside a lock — two racers both miss the "
                                "check and the loser's insert is silently "
                                "overwritten; use setdefault under the "
                                "container's lock")
        else:
            # `if k in d: del d[k]` / `d.pop(k)` — the del can KeyError
            if isinstance(inner, ast.Delete):
                for tgt in inner.targets:
                    if isinstance(tgt, ast.Subscript) \
                            and _same_shared(tgt.value, container,
                                             module_globals):
                        return (f"`if k in {shared}: del {shared}[k]` "
                                "outside a lock — a racing deleter wins "
                                "between check and act and this del raises "
                                "KeyError; use pop(k, None) or hold the "
                                "lock")
            if isinstance(inner, ast.Call) \
                    and isinstance(inner.func, ast.Attribute) \
                    and inner.func.attr in ("pop", "remove") \
                    and len(inner.args) == 1 \
                    and _same_shared(inner.func.value, container,
                                     module_globals):
                return (f"`if k in {shared}: {shared}."
                        f"{inner.func.attr}(k)` outside a lock — the "
                        "membership test and the mutation interleave with "
                        "other threads; use pop(k, None)/discard under the "
                        "container's lock")
    return None


def run(root: str | None = None) -> list[str]:
    """Lint every .py file under the package; returns all findings."""
    return lintcore.run_package(lint_source, root)


def main(argv=None) -> int:
    return lintcore.lint_main(
        "racelint",
        "lint lock discipline: guarded-field escapes, threaded global "
        "mutation, unjoined threads, check-then-act dict races",
        run, argv)


if __name__ == "__main__":
    import sys

    sys.exit(main())
