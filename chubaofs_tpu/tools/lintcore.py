"""lintcore — the shared mechanics of the repo's static-analysis passes.

`obslint` (observability invariants) and `racelint` (lock discipline) are
both small AST passes with identical plumbing: walk every .py file under the
package, parse it, apply per-rule checks, and suppress documented exceptions
either via a line pragma (`# <tool>: <why>`) or a per-file allowlist entry.
This module IS that plumbing, extracted so the two linters cannot drift:

  * `run_package(lint_source)` — the os.walk + parse + collect loop every
    linter shares (skips __pycache__, sorts filenames so findings are
    deterministic across filesystems);
  * `has_pragma(src_lines, lineno, tag)` — the pragma contract: the flagged
    LINE carries `# <tag>: <why>` with a NON-EMPTY reason. A bare `# tag:`
    does not suppress — every exception must say why it is one, or the next
    reader (and the next linter run) can't audit it;
  * `path_matches(relpath, suffixes)` — the per-file allowlist primitive
    (suffix match, so linting an installed package and linting a checkout
    agree);
  * `lint_main(...)` — the shared CLI shape (`cfs-obslint` / `cfs-racelint`):
    findings to stderr, a count line, exit 1 on any finding, `<name>: clean`
    on success.

Both linters are wired into tier-1 (tests/test_obslint.py,
tests/test_racelint.py), so a rule regression — or plumbing drift — fails
the build the day it lands.
"""

from __future__ import annotations

import os
import sys
from collections.abc import Callable, Iterator

# files that ARE the sanctioned packet-serving layer — exempt from the
# serving-model rules (racelint thread-per-conn, obslint hand-framed
# sendall). ONE definition so the linters can't drift apart.
PACKET_LAYER_PATHS = ("rpc/evloop.py", "rpc/httpevloop.py",
                      "proto/packet.py")


def package_root() -> str:
    """Directory of the installed chubaofs_tpu package (the default lint
    target)."""
    import chubaofs_tpu

    return os.path.dirname(os.path.abspath(chubaofs_tpu.__file__))


def iter_py_files(root: str) -> Iterator[tuple[str, str]]:
    """Yield (abspath, relpath) for every .py under root, deterministic
    order, __pycache__ pruned."""
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                path = os.path.join(dirpath, fn)
                yield path, os.path.relpath(path, root)


def line_at(src_lines: list[str], lineno: int) -> str:
    """The 1-indexed source line, or "" out of range (synthetic AST nodes)."""
    return src_lines[lineno - 1] if 0 < lineno <= len(src_lines) else ""


def has_pragma(src_lines: list[str], lineno: int, tag: str) -> bool:
    """True when the flagged line carries `# <tag>: <non-empty why>`.

    The reason is REQUIRED: a pragma is a claim that a human judged this
    exception safe, and the judgment must be written down where the lint
    points."""
    line = line_at(src_lines, lineno)
    marker = tag + ":"
    i = line.find(marker)
    if i < 0:
        return False
    return bool(line[i + len(marker):].strip())


def path_matches(relpath: str, suffixes) -> bool:
    """Per-file allowlist primitive: does relpath end with any entry?"""
    rel = relpath.replace(os.sep, "/")
    return any(rel.endswith(sfx) for sfx in suffixes)


def run_package(lint_source: Callable[[str, str], list[str]],
                root: str | None = None) -> list[str]:
    """Run one linter's lint_source over every file under root (default:
    the installed package); returns every finding."""
    if root is None:
        root = package_root()
    findings: list[str] = []
    for path, rel in iter_py_files(root):
        with open(path, encoding="utf-8") as f:
            findings.extend(lint_source(f.read(), rel))
    return findings


def lint_main(name: str, description: str,
              run: Callable[[str | None], list[str]], argv=None) -> int:
    """The shared CLI: findings to stderr, count, exit 1 when dirty."""
    import argparse

    p = argparse.ArgumentParser(prog=f"cfs-{name}", description=description)
    p.add_argument("root", nargs="?", default=None,
                   help="directory to lint (default: the installed package)")
    args = p.parse_args(argv)
    findings = run(args.root)
    for f in findings:
        print(f, file=sys.stderr)
    if findings:
        print(f"{name}: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    print(f"{name}: clean")
    return 0
