"""fdstore — unix-socket file-descriptor store for client hot-upgrade.

Reference counterpart: fdstore/fdstore.go (392 LoC): the FUSE client hands
its open descriptors (the /dev/fuse fd and friends) to a tiny daemon over a
unix socket before exec'ing its replacement, and the new process collects
them back — a mount survives a client upgrade without remounting. Kept: the
same put/get-by-key surface, fds ride SCM_RIGHTS ancillary data, one store
daemon per host. The protocol is line-oriented: `PUT <key> <n>` + n fds,
`GET <key>` -> `OK <n>` + n fds, `DEL <key>`, `LIST`.
"""

from __future__ import annotations

import array
import os
import socket
import threading

MAX_FDS = 32


def _send_fds(sock: socket.socket, msg: bytes, fds: list[int]) -> None:
    ancillary = [(socket.SOL_SOCKET, socket.SCM_RIGHTS,
                  array.array("i", fds).tobytes())] if fds else []
    sock.sendmsg([msg], ancillary)


def _recv_fds(sock: socket.socket, max_fds: int = MAX_FDS) -> tuple[bytes, list[int]]:
    fds = array.array("i")
    msg, ancdata, _flags, _addr = sock.recvmsg(
        4096, socket.CMSG_LEN(max_fds * fds.itemsize))
    for level, type_, data in ancdata:
        if level == socket.SOL_SOCKET and type_ == socket.SCM_RIGHTS:
            data = data[: len(data) - (len(data) % fds.itemsize)]
            fds.frombytes(data)
    return msg, list(fds)


class FdStore:
    """The store daemon: holds named fd bundles across client restarts."""

    def __init__(self, sock_path: str):
        self.sock_path = sock_path
        try:
            os.unlink(sock_path)
        except FileNotFoundError:
            pass
        self.listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.listener.bind(sock_path)
        self.listener.listen(8)
        self._store: dict[str, list[int]] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept, daemon=True)
        self._thread.start()

    def _accept(self):
        while not self._stop.is_set():
            try:
                conn, _ = self.listener.accept()
            except OSError:
                return
            threading.Thread(  # racelint: local unix-socket upgrade daemon — a handful of conns, and SCM_RIGHTS ancillary fds don't frame through the evloop
                target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket):
        try:
            while True:
                msg, fds = _recv_fds(conn)
                if not msg:
                    return
                parts = msg.decode().split()
                cmd = parts[0] if parts else ""
                if cmd == "PUT" and len(parts) == 3:
                    key, n = parts[1], int(parts[2])
                    for surplus in fds[n:]:  # count mismatch must not leak fds
                        os.close(surplus)
                    with self._lock:
                        for old in self._store.pop(key, []):
                            os.close(old)
                        self._store[key] = fds[:n]
                    _send_fds(conn, b"OK 0", [])
                elif cmd == "GET" and len(parts) == 2:
                    with self._lock:
                        held = self._store.pop(parts[1], None)
                    if held is None:
                        _send_fds(conn, b"ERR not-found", [])
                    else:
                        _send_fds(conn, b"OK %d" % len(held), held)
                        for fd in held:  # ownership transferred to the caller
                            os.close(fd)
                elif cmd == "DEL" and len(parts) == 2:
                    with self._lock:
                        for fd in self._store.pop(parts[1], []):
                            os.close(fd)
                    _send_fds(conn, b"OK 0", [])
                elif cmd == "LIST":
                    with self._lock:
                        keys = " ".join(sorted(self._store)) or "-"
                    _send_fds(conn, b"OK " + keys.encode(), [])
                else:
                    _send_fds(conn, b"ERR bad-command", [])
        except OSError:
            pass
        finally:
            conn.close()

    def close(self):
        self._stop.set()
        self.listener.close()
        try:
            os.unlink(self.sock_path)
        except FileNotFoundError:
            pass
        with self._lock:
            for fds in self._store.values():
                for fd in fds:
                    os.close(fd)
            self._store.clear()


class FdStoreClient:
    def __init__(self, sock_path: str):
        self.sock_path = sock_path

    def _dial(self) -> socket.socket:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(self.sock_path)
        return s

    def put(self, key: str, fds: list[int]) -> None:
        with self._dial() as s:
            _send_fds(s, f"PUT {key} {len(fds)}".encode(), fds)
            msg, _ = _recv_fds(s)
            if not msg.startswith(b"OK"):
                raise OSError(msg.decode())

    def get(self, key: str) -> list[int]:
        with self._dial() as s:
            _send_fds(s, f"GET {key}".encode(), [])
            msg, fds = _recv_fds(s)
            if not msg.startswith(b"OK"):
                raise KeyError(key)
            return fds

    def delete(self, key: str) -> None:
        with self._dial() as s:
            _send_fds(s, f"DEL {key}".encode(), [])
            _recv_fds(s)

    def list(self) -> list[str]:
        with self._dial() as s:
            _send_fds(s, b"LIST", [])
            msg, _ = _recv_fds(s)
            body = msg.decode().split(" ", 1)[1]
            return [] if body == "-" else body.split()
