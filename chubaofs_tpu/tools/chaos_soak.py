"""cfs-chaos-soak — seeded chaos soak against an in-process MiniCluster.

The acceptance harness for the chaos subsystem: for each fault plan it runs
PUT -> fault -> degraded GET -> heal -> converge and fails loudly on data
loss, unbounded tail latency, or a cluster that will not converge. With
--verify-repro each plan runs TWICE and the injection event logs must be
byte-identical — the determinism contract that makes a chaos failure
debuggable by replaying its seed.

    cfs-chaos-soak --seed 7                  # the 3 acceptance plans
    cfs-chaos-soak --plan link_drop --rounds 8 --verify-repro
    cfs-chaos-soak --kill-blobnode --seed 7  # kill-a-blobnode rebuild soak
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

ACCEPTANCE_PLANS = ["node_wedge", "link_drop", "shard_bitrot"]
ALL_PLANS = ACCEPTANCE_PLANS + ["slow_disk", "crash_restart"]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="cfs-chaos-soak", description=__doc__)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--plan", action="append", choices=ALL_PLANS, default=[],
                   help="fault plan (repeatable; default: the 3 acceptance "
                        "plans)")
    p.add_argument("--rounds", type=int, default=6)
    p.add_argument("--puts-per-round", type=int, default=2)
    p.add_argument("--nodes", type=int, default=9)
    p.add_argument("--disks-per-node", type=int, default=2)
    p.add_argument("--root", default=None,
                   help="state dir (default: a fresh temp dir per plan)")
    p.add_argument("--verify-repro", action="store_true",
                   help="run each plan twice; event logs must be identical")
    p.add_argument("--kill-blobnode", action="store_true",
                   help="run the kill-a-blobnode rebuild scenario (instead "
                        "of the fault plans unless --plan is also given): "
                        "kills one node under live PUT load and FAILS if "
                        "rebuild throughput is zero, any repaired stripe "
                        "miscompares, or a WORKING task is stranded")
    p.add_argument("--meta-split", action="store_true",
                   help="run the metadata scale-out chaos soak (ISSUE 15): "
                        "crash-restart a metanode daemon mid-split and "
                        "mid-migration under live create load; fails on any "
                        "acked-file loss, a double-owned inode, an unhealed "
                        "membership, or a missing split/migrate event "
                        "timeline")
    p.add_argument("--cache", action="store_true",
                   help="run the cache-plane correctness soak (ISSUE 12): "
                        "zipfian GETs + overwrites + deletes through the "
                        "tiered read cache with failpoint-DELAYED "
                        "invalidation; fails on any stale or corrupt byte "
                        "(crc ledger) or a deleted blob still readable")
    p.add_argument("--mode", default=None,
                   help="pin every PUT of the kill scenario to one CodeMode "
                        "by name (e.g. RG6P6 to soak the beta-fetch repair "
                        "plane, EC12P4 for the RS baseline); default: the "
                        "cluster's default mode")
    p.add_argument("--hb-timeout", type=float, default=0.75,
                   help="heartbeat-silence window for the kill scenario's "
                        "dead-disk detection (seconds)")
    p.add_argument("--sanitize", action="store_true",
                   help="arm the lock-order sanitizer (CFS_LOCK_SANITIZER=1) "
                        "for the whole soak; any lock inversion observed "
                        "under fault load fails the run")
    p.add_argument("--json", action="store_true", help="machine-readable out")
    args = p.parse_args(argv)

    if args.sanitize:
        # before run_soak builds any cluster: locks check the env when
        # CONSTRUCTED, so this must precede every component import-and-build
        os.environ["CFS_LOCK_SANITIZER"] = "1"

    from chubaofs_tpu.chaos.soak import (
        SoakFailure, run_cache_soak, run_kill_soak, run_meta_split_soak,
        run_soak)

    plans = args.plan or (
        [] if (args.kill_blobnode or args.cache or args.meta_split)
        else ACCEPTANCE_PLANS)
    results = []
    ok = True
    if args.meta_split:
        root = (os.path.join(args.root, "meta-split") if args.root
                else tempfile.mkdtemp(prefix="chaos-meta-"))
        try:
            res = run_meta_split_soak(root, seed=args.seed)
        except SoakFailure as e:
            ok = False
            res = {"plan": "meta_split", "seed": args.seed, "ok": False,
                   "error": str(e),
                   "bundle": getattr(e, "bundle", None)}
        results.append(res)
    if args.cache:
        root = (os.path.join(args.root, "cache-soak") if args.root
                else tempfile.mkdtemp(prefix="chaos-cache-"))
        try:
            res = run_cache_soak(root, seed=args.seed, rounds=args.rounds)
        except SoakFailure as e:
            ok = False
            res = {"plan": "cache", "seed": args.seed, "ok": False,
                   "error": str(e),
                   "bundle": getattr(e, "bundle", None)}
        results.append(res)
    if args.kill_blobnode:
        root = (os.path.join(args.root, "kill-blobnode") if args.root
                else tempfile.mkdtemp(prefix="chaos-kill-"))
        try:
            res = run_kill_soak(root, seed=args.seed, n_nodes=args.nodes,
                                disks_per_node=args.disks_per_node,
                                hb_timeout=args.hb_timeout, mode=args.mode)
        except SoakFailure as e:
            ok = False
            res = {"plan": "kill_blobnode", "seed": args.seed, "ok": False,
                   "error": str(e),
                   "bundle": getattr(e, "bundle", None)}
        results.append(res)
    for plan in plans:
        runs = 2 if args.verify_repro else 1
        logs = []
        for i in range(runs):
            if args.root:
                root = os.path.join(args.root, f"{plan}-{i}")
            else:
                root = tempfile.mkdtemp(prefix=f"chaos-{plan}-")
            try:
                res = run_soak(root, plan, seed=args.seed,
                               rounds=args.rounds,
                               puts_per_round=args.puts_per_round,
                               n_nodes=args.nodes,
                               disks_per_node=args.disks_per_node)
            except SoakFailure as e:
                ok = False
                res = {"plan": plan, "seed": args.seed, "ok": False,
                       "error": str(e),
                       "bundle": getattr(e, "bundle", None)}
            logs.append(res.get("events"))
            results.append(res)
            if not res.get("ok"):
                break
        if args.verify_repro and len(logs) == 2 and logs[0] != logs[1]:
            ok = False
            results.append({"plan": plan, "ok": False,
                            "error": "event logs diverged across identical "
                                     "seeded runs"})
    sanitizer = None
    if args.sanitize:
        from chubaofs_tpu.utils import locks

        sanitizer = locks.report()
        if sanitizer["inversions"]:
            ok = False
    if args.json:
        out = {"ok": ok, "results": results}
        if sanitizer is not None:
            out["sanitizer"] = sanitizer
        print(json.dumps(out, indent=2))
    else:
        for r in results:
            status = "OK " if r.get("ok") else "FAIL"
            if not r.get("ok"):
                extra = r.get("error", "")
            elif r.get("plan") == "meta_split":
                extra = (f"parts={r.get('partitions')} "
                         f"acked={r.get('creates_acked')} "
                         f"failed={r.get('creates_failed')} "
                         f"inodes={r.get('inodes_census')} "
                         f"moved={r.get('migrate_moved')} "
                         f"kills={[k['phase'] for k in r.get('kills', [])]}")
            elif r.get("plan") == "kill_blobnode":
                extra = ((f"mode={r['code_mode']} " if r.get("code_mode")
                          else "")
                         + f"killed={r['killed_node']} "
                         f"detect={r['detect_s']}s "
                         f"rebuilt={r['rebuilt_shards']} shards "
                         f"({r['rebuild_shards_per_s']}/s) "
                         + (f"beta={r['beta_shards']} "
                            if r.get("beta_shards") else "")
                         + f"overlap={r['repair_overlap_ratio']} "
                         f"bytes/shard={r['bytes_per_repaired_shard']}")
            else:
                extra = (f"puts={r.get('puts')} "
                         f"rejected={r.get('puts_rejected')}"
                         f" gets={r.get('gets')}"
                         f" max_get={r.get('max_get_s', 0):.2f}s")
            print(f"[{status}] plan={r['plan']} seed={r.get('seed')} {extra}")
            if r.get("bundle"):
                print(f"         incident bundle: {r['bundle']} "
                      f"(cfs-doctor inspect)")
            for ev in r.get("events") or []:
                print(f"         t={ev['t']} {ev['event']} {ev['fault']}"
                      + "".join(f" {k}={v}" for k, v in ev.items()
                                if k not in ("t", "event", "fault")))
            # the event-plane acceptance evidence: the causal timeline the
            # soak asserted on, plus the alert lifecycle it observed
            for te in r.get("timeline") or []:
                print(f"         timeline +{te['t']}s {te['type']} "
                      f"{te['entity']}"
                      + (f" trace={te['trace_id'][:12]}"
                         if te.get("trace_id") else ""))
            if "alerts_fired" in r:
                print(f"         alerts fired={r['alerts_fired']} "
                      f"still-firing={r.get('alerts_firing', [])}")
        if sanitizer is not None:
            n = len(sanitizer["inversions"])
            print(f"[{'OK ' if n == 0 else 'FAIL'}] lock-sanitizer "
                  f"inversions={n} hold_outliers="
                  f"{len(sanitizer['hold_outliers'])} "
                  f"locks={sanitizer['locks_tracked']} "
                  f"edges={sanitizer['edges']}")
            for rec in sanitizer["inversions"]:
                print(f"         {rec['first']} -> {rec['then']} at "
                      f"{rec['acquire_site']} (reverse at "
                      f"{rec['reverse_site']})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
