"""autofs — automount map helper.

Reference counterpart: autofs/ (403 LoC: the `cfs-autofs` mount helper that
automount invokes with a key + options string to mount a CubeFS volume on
demand). Kept: the same option grammar (`-fstype=chubaofs,master=...,vol=...`)
and the map-entry parsing; instead of exec'ing a kernel-FUSE mount (out of
scope here), it emits the client config JSON the mount daemon consumes — the
piece automount integration actually needs from us.
"""

from __future__ import annotations

import json


def parse_options(opts: str) -> dict:
    """'-fstype=chubaofs,master=h1:p;h2:p,vol=media,ro' -> config dict."""
    cfg: dict = {}
    for field in opts.lstrip("-").split(","):
        if not field:
            continue
        if "=" in field:
            k, v = field.split("=", 1)
        else:
            k, v = field, "true"
        if k == "master":
            cfg["masterAddr"] = v.split(";")
        elif k == "vol":
            cfg["volName"] = v
        elif k == "access":
            cfg["accessAddr"] = v.split(";")
        elif k == "fstype":
            cfg["fstype"] = v
        else:
            cfg.setdefault("options", {})[k] = v
    return cfg


def map_entry_to_config(key: str, opts: str) -> dict:
    cfg = parse_options(opts)
    if cfg.get("fstype") not in ("chubaofs", "cfs", None):
        raise ValueError(f"unsupported fstype {cfg.get('fstype')!r}")
    cfg.pop("fstype", None)
    cfg.setdefault("volName", key)
    if "masterAddr" not in cfg:
        raise ValueError("map options need master=host:port[;host:port]")
    cfg["mountPoint"] = f"/{key}"
    return cfg


def main(argv=None) -> int:
    import argparse
    import sys

    p = argparse.ArgumentParser(prog="cfs-autofs",
                                description="automount map helper")
    p.add_argument("key", help="automount key (volume)")
    p.add_argument("options", help="map options, e.g. "
                   "-fstype=chubaofs,master=h:p,vol=v")
    args = p.parse_args(argv)
    try:
        print(json.dumps(map_entry_to_config(args.key, args.options), indent=2))
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
