"""cfs-events — the merged cluster event timeline + alert view.

The forensics companion to cfs-top: where the dashboard shows the cluster's
state NOW, this shows what CHANGED — every daemon's event journal (disk
transitions, repair leases, tier migrations, raft elections, backpressure
flips, SLO flips, chaos injections, alert lifecycle) merged into one
wall-clock-ordered timeline via the console's `/api/events` rollup
(cursor-paged; `--addr` polls daemons' `/events` side-doors directly).

    cfs-events --console 127.0.0.1:8500 --since 600
    cfs-events --console C --type disk_status,task_finished --follow
    cfs-events --console C --alerts
    cfs-events --console C --correlate 8f3a...   # events ⋈ trace spans
    cfs-events --console C --correlate 'slo_failing|slo=put_p99'

`--correlate <trace-id>` joins the timeline against the trace sink: events
carrying that trace id and the trace's spans (console `/api/trace`, or each
daemon's `/traces`) interleave into one causally-ordered view — the
injected-fault → detection → repair-lease → rebuild-finished chain the
chaos kill soak asserts on, readable by a human.

`--correlate <alert-fingerprint>` instead joins the alert lifecycle against
the autopilot's decision log: the alert_firing edge, every `autopilot_*`
decision stamped with that causal fingerprint, and the alert_resolved edge,
each line carrying its wall-time delta from the firing edge — the auditable
cause→action→resolution chain (an argument that matches no alert falls
back to the trace join, so one flag serves both).

`--follow` keeps polling with the rollup cursor, printing only new events
(tail -f for the cluster). Unreachable targets print as warnings, never
silently vanish.
"""

from __future__ import annotations

import json
import sys
import time
import urllib.parse

SEVERITY_MARK = {"info": " ", "warning": "W", "critical": "C"}


# -- fetching ------------------------------------------------------------------


def _get_json(addr: str, path: str, timeout: float = 5.0) -> dict:
    from chubaofs_tpu.tools.cfsstat import scrape

    return json.loads(scrape(addr, path, timeout=timeout))


def _fanout_json(addrs: list[str], path_of, timeout: float) -> list[tuple]:
    """[(addr, json-or-None)] fetched CONCURRENTLY — dead daemons cost one
    timeout, not one per corpse (the console rollup discipline; this is
    the ONE fan-out both the console /api/* rollups and the CLI's direct
    --addr mode ride, so the two surfaces cannot drift)."""
    from concurrent.futures import ThreadPoolExecutor

    def one(addr: str):
        try:
            return _get_json(addr, path_of(addr), timeout=timeout)
        except Exception:
            return None

    with ThreadPoolExecutor(max_workers=min(8, len(addrs) or 1)) as pool:
        return list(zip(addrs, pool.map(one, addrs)))


def fetch_events(console: str | None, addrs: list[str],
                 cursor: dict | None = None, n: int = 500,
                 types: str = "", severity: str = "",
                 timeout: float = 5.0) -> tuple[list[dict], dict, list[str]]:
    """One timeline page: (events tagged with target, next cursor map,
    unreachable targets). Console mode rides /api/events; --addr mode (also
    the implementation BEHIND /api/events) polls each target's /events —
    newest page when no cursor is held for it, exact oldest-first
    pagination once one is."""
    cursor = dict(cursor or {})
    extra = ""
    if types:
        extra += f"&type={urllib.parse.quote(types)}"
    if severity:
        extra += f"&severity={urllib.parse.quote(severity)}"
    if console:
        q = f"/api/events?n={n}{extra}"
        if cursor:
            q += f"&cursor={urllib.parse.quote(json.dumps(cursor))}"
        out = _get_json(console, q, timeout=timeout)
        return (out.get("events", []), out.get("cursor", cursor),
                out.get("unreachable", []))

    def path_of(addr: str) -> str:
        since = f"since={cursor[addr]}&" if addr in cursor else ""
        return f"/events?{since}n={n}{extra}"

    merged: list[dict] = []
    missed: list[str] = []
    for addr, out in _fanout_json(addrs, path_of, timeout):
        if out is None:
            missed.append(addr)  # cursor stays put: nothing is skipped
            continue
        cursor[addr] = int(out.get("cursor", cursor.get(addr, 0)))
        merged.extend({**rec, "target": addr}
                      for rec in out.get("events", ()))
    merged.sort(key=lambda e: e.get("ts", 0.0))
    return merged, cursor, missed


def fetch_alerts(console: str | None, addrs: list[str],
                 timeout: float = 5.0) -> dict:
    """The merged alert view (also the implementation behind /api/alerts):
    per-target rows + the cluster firing total, corpses marked."""
    if console:
        return _get_json(console, "/api/alerts", timeout=timeout)
    rows, missed = [], []
    total = 0
    for addr, out in _fanout_json(addrs, lambda a: "/alerts", timeout):
        if out is None or "alerts" not in out:
            missed.append(addr)
            rows.append({"target": addr, "unreachable": True, "alerts": [],
                         "firing": 0})
            continue
        rows.append({"target": addr, "alerts": out.get("alerts", []),
                     "firing": out.get("firing", 0)})
        total += int(out.get("firing", 0))
    return {"targets": rows, "firing": total, "unreachable": missed}


def fetch_spans(console: str | None, addrs: list[str],
                trace_id: str, timeout: float = 5.0) -> list[dict]:
    tid = urllib.parse.quote(trace_id)
    if console:
        out = _get_json(console, f"/api/trace?id={tid}", timeout=timeout)
        return out.get("spans", [])
    spans: dict[str, dict] = {}
    for addr in addrs:
        try:
            out = _get_json(addr, f"/traces?id={tid}", timeout=timeout)
        except Exception:
            continue
        for rec in out.get("spans", ()):
            if rec.get("span_id"):
                spans.setdefault(rec["span_id"], rec)
    return sorted(spans.values(), key=lambda r: r.get("start", 0.0))


# -- rendering -----------------------------------------------------------------


def fmt_event(e: dict) -> str:
    ts = time.strftime("%H:%M:%S", time.localtime(e.get("ts", 0.0)))
    ms = int((e.get("ts", 0.0) % 1) * 1000)
    who = e.get("role") or e.get("target") or "-"
    detail = " ".join(f"{k}={v}" for k, v in (e.get("detail") or {}).items())
    tid = f" trace={e['trace_id'][:12]}" if e.get("trace_id") else ""
    return (f"{ts}.{ms:03d} [{SEVERITY_MARK.get(e.get('severity'), '?')}] "
            f"{who:<12} {e.get('type', '?'):<18} {e.get('entity', ''):<18} "
            f"{detail}{tid}")


def render_alerts(roll: dict) -> str:
    lines = [f"firing: {roll.get('firing', 0)}"]
    for row in roll.get("targets", []):
        tag = " UNREACHABLE" if row.get("unreachable") else ""
        lines.append(f"{row['target']}{tag}:")
        for a in row.get("alerts", []):
            labels = "".join(f" {k}={v}"
                             for k, v in (a.get("labels") or {}).items())
            since = time.strftime("%H:%M:%S",
                                  time.localtime(a.get("since") or 0))
            sil = " (silenced)" if a.get("silenced") else ""
            lines.append(f"  [{a.get('state', '?'):>8}] {a['name']}{labels} "
                         f"value={a.get('value')} since={since}{sil}")
        if not row.get("alerts"):
            lines.append("  (no alerts)")
    for addr in roll.get("unreachable", []):
        lines.append(f"! {addr}: unreachable")
    return "\n".join(lines)


def event_fingerprint(e: dict) -> str | None:
    """The alert fingerprint an event belongs to, or None: autopilot_*
    decisions carry it verbatim in detail.fingerprint (the causal stamp);
    alert_firing/alert_resolved reconstruct it from entity + labels —
    the same fingerprint() the alert manager dedupes by."""
    from chubaofs_tpu.utils.alerts import fingerprint

    d = e.get("detail") or {}
    if str(e.get("type", "")).startswith("autopilot_"):
        return str(d.get("fingerprint", "")) or None
    if e.get("type") in ("alert_firing", "alert_resolved"):
        return fingerprint(e.get("entity", ""), d.get("labels"))
    return None


def correlate_alert_chain(events: list[dict], fp: str) -> list[dict]:
    """The cause→action→resolution join (ISSUE 20): every alert_firing /
    autopilot_* / alert_resolved event belonging to one alert fingerprint,
    wall-ordered, each stamped with the delta since the chain's most
    recent firing edge — so `fired +0.0s → executed +2.1s → resolved
    +9.8s` reads straight down. Empty when the fingerprint matched no
    alert lifecycle (the caller falls back to the trace-span join)."""
    chain = [e for e in events if event_fingerprint(e) == fp]
    chain.sort(key=lambda e: e.get("ts", 0.0))
    items: list[dict] = []
    t_fire: float | None = None
    for e in chain:
        if e.get("type") == "alert_firing":
            t_fire = e.get("ts", 0.0)
        dt = None if t_fire is None \
            else round(e.get("ts", 0.0) - t_fire, 3)
        kind = "alert" if str(e.get("type", "")).startswith("alert_") \
            else "action"
        mark = "cause    " if e.get("type") == "alert_firing" \
            else (f"+{dt:.3f}s" if dt is not None else "?        ")
        items.append({"t": e.get("ts", 0.0), "kind": kind, "dt": dt,
                      "record": e, "line": f"{mark:>10}  {fmt_event(e)}"})
    return items


def correlate(events: list[dict], spans: list[dict],
              trace_id: str) -> list[dict]:
    """The join: events carrying the trace id + the trace's spans, merged
    into one wall-ordered item list ({'t', 'kind', 'line'})."""
    items: list[dict] = []
    for e in events:
        if e.get("trace_id") != trace_id:
            continue
        items.append({"t": e.get("ts", 0.0), "kind": "event",
                      "record": e, "line": fmt_event(e)})
    for s in spans:
        start = s.get("start", 0.0)
        dur_ms = s.get("dur_us", 0) / 1e3
        ts = time.strftime("%H:%M:%S", time.localtime(start))
        items.append({
            "t": start, "kind": "span", "record": s,
            "line": f"{ts}.{int((start % 1) * 1000):03d} [span] "
                    f"{s.get('op', '?'):<32} {dur_ms:.2f}ms"})
    items.sort(key=lambda i: i["t"])
    return items


# -- offline bundle mode (ISSUE 18) --------------------------------------------
#
# Postmortems outlive clusters: --bundle points every view this CLI renders
# at a collected flight-recorder bundle (one daemon's dir or a console-
# assembled incident dir) instead of live side-doors.


def bundle_events(bundle: dict, types: str = "",
                  severity: str = "") -> list[dict]:
    tset = {t for t in types.split(",") if t}
    sset = {s for s in severity.split(",") if s}
    evs = []
    for payload in bundle["targets"].values():
        for e in (payload.get("events") or {}).get("events", []):
            if tset and e.get("type") not in tset:
                continue
            if sset and e.get("severity") not in sset:
                continue
            evs.append(e)
    evs.sort(key=lambda e: e.get("ts", 0.0))
    return evs


def bundle_alerts(bundle: dict) -> dict:
    """The frozen alert view in the merged-rollup shape render_alerts
    expects: each target's triggering alert (bundles freeze the CAUSE, not
    the whole /alerts table)."""
    rows, firing = [], 0
    for tname, payload in sorted(bundle["targets"].items()):
        a = payload.get("alert") or {}
        alist = [a] if a.get("name") else []
        firing += sum(1 for x in alist if x.get("state") == "firing")
        rows.append({"target": tname, "alerts": alist,
                     "firing": sum(1 for x in alist
                                   if x.get("state") == "firing")})
    inc = bundle.get("incident") or {}
    return {"targets": rows, "firing": firing,
            "unreachable": inc.get("unreachable", [])}


def bundle_spans(bundle: dict, trace_id: str) -> list[dict]:
    spans: dict[str, dict] = {}
    for payload in bundle["targets"].values():
        for rec in (payload.get("traces") or {}).get("records", []):
            if rec.get("trace_id") == trace_id and rec.get("span_id"):
                spans.setdefault(rec["span_id"], rec)
    return sorted(spans.values(), key=lambda r: r.get("start", 0.0))


# -- CLI -----------------------------------------------------------------------


def main(argv=None, out=None) -> int:
    import argparse

    out = out or sys.stdout
    p = argparse.ArgumentParser(
        prog="cfs-events",
        description="merged cluster event timeline + alerts")
    p.add_argument("--console", default=None,
                   help="console address (uses /api/events + /api/alerts)")
    p.add_argument("--addr", action="append", default=[],
                   help="poll a daemon directly (repeatable; skips console)")
    p.add_argument("--since", type=float, default=0.0,
                   help="only events newer than SINCE seconds ago")
    p.add_argument("--type", default="",
                   help="comma-separated event types to keep")
    p.add_argument("--severity", default="",
                   help="comma-separated severities to keep "
                        "(info,warning,critical)")
    p.add_argument("--n", type=int, default=500,
                   help="page size per target")
    p.add_argument("--follow", action="store_true",
                   help="keep polling and print only new events (^C stops)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="--follow poll period (s)")
    p.add_argument("--alerts", action="store_true",
                   help="show the merged alert view instead of the timeline")
    p.add_argument("--correlate", default="", metavar="TRACE_ID|ALERT_FP",
                   help="join events against this trace's spans, or — given "
                        "an alert fingerprint — print its cause→action→"
                        "resolution chain with wall-time deltas")
    p.add_argument("--bundle", default="",
                   help="read from a collected flight-recorder bundle dir "
                        "instead of live side-doors (postmortem mode)")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    if not args.console and not args.addr and not args.bundle:
        p.error("give --console, --addr, or --bundle")

    bundle = None
    if args.bundle:
        if args.follow:
            p.error("--follow needs a live cluster, not --bundle")
        from chubaofs_tpu.tools.cfsdoctor import read_bundle

        try:
            bundle = read_bundle(args.bundle)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1

    if args.alerts:
        roll = (bundle_alerts(bundle) if bundle is not None
                else fetch_alerts(args.console, args.addr))
        print(json.dumps(roll, indent=2) if args.json
              else render_alerts(roll), file=out)
        return 0

    if bundle is not None:
        events, cursor, missed = (
            bundle_events(bundle, types=args.type, severity=args.severity),
            0, (bundle.get("incident") or {}).get("unreachable", []))
    else:
        events, cursor, missed = fetch_events(
            args.console, args.addr, n=args.n, types=args.type,
            severity=args.severity)
    if args.since > 0:
        # event records carry WALL stamps (the cross-daemon merge key), so
        # the --since floor is wall arithmetic by protocol
        floor = time.time() - args.since  # wallclock: event ts are cross-process wall stamps
        events = [e for e in events if e.get("ts", 0.0) >= floor]

    if args.correlate:
        # an alert fingerprint takes precedence over a trace id: when the
        # argument names an alert lifecycle in the window, render the
        # cause→action→resolution chain (ISSUE 20); otherwise it is a
        # trace id and the events ⋈ spans join applies
        chain = correlate_alert_chain(events, args.correlate)
        if chain:
            if args.json:
                print(json.dumps({"fingerprint": args.correlate,
                                  "items": chain},
                                 default=str, indent=2), file=out)
            else:
                acts = sum(1 for i in chain if i["kind"] == "action")
                resolved = any(
                    i["record"].get("type") == "alert_resolved"
                    for i in chain)
                print(f"alert {args.correlate}: {len(chain)} items "
                      f"({acts} autopilot action(s), "
                      f"{'resolved' if resolved else 'still firing'})",
                      file=out)
                for item in chain:
                    print(item["line"], file=out)
            return 0
        spans = (bundle_spans(bundle, args.correlate)
                 if bundle is not None
                 else fetch_spans(args.console, args.addr, args.correlate))
        items = correlate(events, spans, args.correlate)
        if args.json:
            print(json.dumps({"trace_id": args.correlate, "items": items},
                             default=str, indent=2), file=out)
        else:
            print(f"trace {args.correlate}: {len(items)} items "
                  f"({sum(1 for i in items if i['kind'] == 'event')} events, "
                  f"{sum(1 for i in items if i['kind'] == 'span')} spans)",
                  file=out)
            for item in items:
                print(item["line"], file=out)
        return 0

    def show(evs: list[dict], missed_now: list[str]):
        if args.json:
            print(json.dumps({"events": evs, "unreachable": missed_now},
                             indent=2), file=out)
        else:
            for e in evs:
                print(fmt_event(e), file=out)
            for addr in missed_now:
                print(f"! {addr}: unreachable", file=out)

    show(events, missed)
    if not args.follow:
        return 0
    try:
        while True:
            time.sleep(max(0.1, args.interval))
            events, cursor, missed = fetch_events(
                args.console, args.addr, cursor=cursor, n=args.n,
                types=args.type, severity=args.severity)
            if events or missed:
                show(events, missed)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
