"""One-command local cluster — the `docker/run_docker.sh -r` analog.

The reference brings up 3 masters + 4 metanodes + 4 datanodes + objectnode +
client with docker compose (reference docker/docker-compose.yml:369-412,
run_docker.sh:39). Here the same topology launches as local daemon
subprocesses (the testing harness's ProcCluster promoted to an operator
entry): one command, ephemeral ports, a JSON line with every address, and a
clean teardown on SIGINT/SIGTERM.

    cfs-localcluster --root /tmp/cfs --blobstore --objectnode

Intended for development and soak testing; production deployments run the
per-role daemons (`chubaofs-tpu -c role.json`) under real supervision.
"""

from __future__ import annotations

import argparse
import json
import sys


def launch(args) -> "ProcCluster":
    from chubaofs_tpu.testing.harness import ProcCluster

    return ProcCluster(
        args.root,
        masters=args.masters,
        metanodes=args.metanodes,
        datanodes=args.datanodes,
        blobstore=args.blobstore or args.objectnode,
        objectnode=args.objectnode,
        # config, not env: cmd.py prefers cfg['jaxPlatform'] and ProcCluster
        # defaults it to cpu, so an env-only request would be silently lost
        jax_platform=args.jax_platform or None,
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="cfs-localcluster",
        description="spin up a full local chubaofs-tpu cluster (dev/test)")
    p.add_argument("--root", required=True, help="state directory")
    p.add_argument("--masters", type=int, default=3)
    p.add_argument("--metanodes", type=int, default=3)
    p.add_argument("--datanodes", type=int, default=3)
    p.add_argument("--blobstore", action="store_true",
                   help="also run the EC blobstore (cold tier)")
    p.add_argument("--objectnode", action="store_true",
                   help="also run the S3 gateway (implies --blobstore backing)")
    p.add_argument("--jax-platform", default="",
                   help="force the daemons' JAX platform (e.g. cpu)")
    p.add_argument("--volume", default="",
                   help="create this volume once nodes register")
    args = p.parse_args(argv)

    from chubaofs_tpu.utils.shutdown import await_shutdown, shutdown_event

    # handlers FIRST: a supervisor that signals the instant it sees the JSON
    # line must hit the graceful path, not the default handler
    stop = shutdown_event()
    cluster = launch(args)  # constructor already waits for node registration
    try:
        if args.volume:
            cluster.client_master().create_volume(args.volume, cold=False)
        print(json.dumps({
            "master_addrs": cluster.master_addrs,
            "access_addr": cluster.access_addr,
            "s3_addr": cluster.s3_addr,
            "root": cluster.root,
        }), flush=True)
        await_shutdown(stop)
        return 0
    finally:
        cluster.close()


if __name__ == "__main__":
    sys.exit(main())
