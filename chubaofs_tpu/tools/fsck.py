"""fsck — offline inode/dentry consistency checker.

Reference counterpart: fsck/ (1,431 LoC: the `cfs-fsck check` / `clean`
commands that cross-walk inode and dentry dumps looking for orphans and
dangling entries). Kept: the same defect taxonomy —

  * dangling dentry: names an inode that no partition holds;
  * orphan inode: held by a partition but reachable by no dentry (and not
    already queued on the freelist);
  * nlink drift: a file inode's link count differs from its dentry count;
  * dir cycle / unreachable subtree: a directory whose walk never reaches
    the root.

`check` reports; `clean` repairs what's safe: dangling dentries are removed,
orphan inodes are unlinked+evicted so the freelist purges their data.
Runs over a MetaWrapper (live cluster or in-proc), so the same tool works
against daemons via RemoteCluster.
"""

from __future__ import annotations

import stat as stat_mod
from dataclasses import dataclass, field

from chubaofs_tpu.meta.metanode import OpError
from chubaofs_tpu.meta.partition import ROOT_INO


@dataclass
class FsckReport:
    inode_count: int = 0
    dentry_count: int = 0
    dangling_dentries: list[tuple[int, str, int]] = field(default_factory=list)
    orphan_inodes: list[int] = field(default_factory=list)
    nlink_drift: list[tuple[int, int, int]] = field(default_factory=list)  # ino, expect, got
    unreachable_dirs: list[int] = field(default_factory=list)
    cleaned: int = 0

    @property
    def clean(self) -> bool:
        return not (self.dangling_dentries or self.orphan_inodes
                    or self.nlink_drift or self.unreachable_dirs)

    def summary(self) -> str:
        lines = [
            f"inodes           : {self.inode_count}",
            f"dentries         : {self.dentry_count}",
            f"dangling dentries: {len(self.dangling_dentries)}",
            f"orphan inodes    : {len(self.orphan_inodes)}",
            f"nlink drift      : {len(self.nlink_drift)}",
            f"unreachable dirs : {len(self.unreachable_dirs)}",
        ]
        if self.cleaned:
            lines.append(f"cleaned          : {self.cleaned}")
        lines.append("status           : " + ("CLEAN" if self.clean else "DIRTY"))
        return "\n".join(lines)


class Fsck:
    ORPHAN_GRACE = 60.0  # seconds an unreferenced inode may be mid-creation

    def __init__(self, meta, orphan_grace: float | None = None):
        """meta: a MetaWrapper for the volume under check."""
        self.meta = meta
        if orphan_grace is not None:
            self.ORPHAN_GRACE = orphan_grace

    # -- collection ------------------------------------------------------------

    def _collect(self):
        """Full namespace dump via per-partition leader reads."""
        inodes: dict[int, object] = {}
        dentries: list = []
        for mp in self.meta._view().meta_partitions:
            # walk the partition's inode range via readdir of known dirs is
            # not enough (orphans have no dentry); ask the SM directly
            sm_inodes = self.meta._on_partition(
                mp, lambda n, _mp=mp: self._dump_partition(n, _mp.partition_id))
            inodes.update(sm_inodes["inodes"])
            dentries += sm_inodes["dentries"]
        return inodes, dentries

    @staticmethod
    def _dump_partition(node, pid: int):
        """Dump one partition — MetaNode and RemoteMetaNode share the
        dump_namespace surface."""
        dump = node.dump_namespace(pid)
        return {"inodes": {i.ino: i for i in dump["inodes"]},
                "dentries": dump["dentries"]}

    # -- check -----------------------------------------------------------------

    def check(self) -> FsckReport:
        inodes, dentries = self._collect()
        rep = FsckReport(inode_count=len(inodes), dentry_count=len(dentries))

        by_ino: dict[int, int] = {}
        children: dict[int, list] = {}
        for d in dentries:
            by_ino[d.ino] = by_ino.get(d.ino, 0) + 1
            children.setdefault(d.parent, []).append(d)
            if d.ino not in inodes:
                rep.dangling_dentries.append((d.parent, d.name, d.ino))

        import time

        now = time.time()
        for ino, inode in inodes.items():
            if ino == ROOT_INO:
                continue
            refs = by_ino.get(ino, 0)
            if refs == 0:
                # a live client creates the inode BEFORE its dentry, and the
                # per-partition dumps aren't atomic — young inodes are likely
                # mid-creation, not orphans (the reference fsck runs offline;
                # online we need the grace window)
                if now - inode.ctime >= self.ORPHAN_GRACE:
                    rep.orphan_inodes.append(ino)
            elif not inode.is_dir and inode.nlink != refs:
                rep.nlink_drift.append((ino, refs, inode.nlink))

        # reachability: BFS from root over dentries
        reachable = {ROOT_INO}
        frontier = [ROOT_INO]
        while frontier:
            nxt = []
            for parent in frontier:
                for d in children.get(parent, []):
                    if d.ino not in reachable:
                        reachable.add(d.ino)
                        if stat_mod.S_ISDIR(d.mode):
                            nxt.append(d.ino)
            frontier = nxt
        for ino, inode in inodes.items():
            if inode.is_dir and ino not in reachable and ino != ROOT_INO:
                rep.unreachable_dirs.append(ino)
        return rep

    # -- clean -----------------------------------------------------------------

    def clean(self) -> FsckReport:
        rep = self.check()
        for parent, name, _ino in rep.dangling_dentries:
            try:
                self.meta.delete_dentry(parent, name)
                rep.cleaned += 1
            except OpError:
                pass
        for ino in rep.orphan_inodes:
            try:
                self.meta.unlink_inode(ino)
                self.meta.evict_inode(ino)
                rep.cleaned += 1
            except OpError:
                pass
        return rep


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="cfs-fsck",
                                description="namespace consistency checker")
    p.add_argument("--addr", action="append", required=True,
                   help="master address (repeatable)")
    p.add_argument("--volume", required=True)
    p.add_argument("mode", choices=["check", "clean"])
    args = p.parse_args(argv)

    from chubaofs_tpu.sdk.cluster import RemoteCluster

    fs = RemoteCluster(args.addr).client(args.volume)
    fsck = Fsck(fs.meta)
    rep = fsck.clean() if args.mode == "clean" else fsck.check()
    print(rep.summary())
    return 0 if rep.clean or args.mode == "clean" else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
