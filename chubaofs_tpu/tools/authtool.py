"""authtool — key and ticket utility for the authnode.

Reference counterpart: authtool/ (522 LoC: generates auth keys, crafts
ticket requests, decodes tickets for debugging). Subcommands:

  genkey                     print a fresh 32-byte base64 key
  createkey ID ROLE          register a key at the authnode (HTTP)
  ticket CLIENT SERVICE      fetch a ticket for CLIENT to talk to SERVICE
  decode TICKET KEY          decrypt+dump a ticket with the service key
"""

from __future__ import annotations

import argparse
import base64
import json
import secrets
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="cfs-authtool")
    p.add_argument("--addr", help="authnode HTTP address host:port")
    p.add_argument("--admin-secret", default="", help="authnode admin secret")
    sub = p.add_subparsers(dest="cmd", required=True)

    sub.add_parser("genkey")

    ck = sub.add_parser("createkey")
    ck.add_argument("id")
    ck.add_argument("role", choices=["client", "service"])
    ck.add_argument("--caps", default="", help="comma-separated capabilities")

    tk = sub.add_parser("ticket")
    tk.add_argument("client")
    tk.add_argument("service")
    tk.add_argument("--key", required=True, help="client key (base64)")

    dc = sub.add_parser("decode")
    dc.add_argument("ticket")
    dc.add_argument("key", help="service key (base64)")
    dc.add_argument("--service", required=True)

    args = p.parse_args(argv)

    if args.cmd == "genkey":
        print(base64.b64encode(secrets.token_bytes(32)).decode())
        return 0

    if args.cmd == "decode":
        from chubaofs_tpu.authnode.server import verify_ticket

        info = verify_ticket(args.service, base64.b64decode(args.key),
                             args.ticket)
        print(json.dumps(info, indent=2, default=str))
        return 0

    if not args.addr:
        print("need --addr for authnode commands", file=sys.stderr)
        return 2
    from chubaofs_tpu.rpc.client import RPCClient

    if args.cmd == "createkey":
        # /admin/* rides the shared-secret path-HMAC middleware
        rpc = RPCClient([args.addr],
                        auth_secret=args.admin_secret.encode() or None)
        caps = [c for c in args.caps.split(",") if c]
        out = rpc.post("/admin/createkey",
                       {"id": args.id, "role": args.role, "caps": caps})
        print(json.dumps(out, indent=2))
        return 0

    if args.cmd == "ticket":
        import time

        from chubaofs_tpu.utils import cryptoutil

        rpc = RPCClient([args.addr])
        ts = time.time()
        key = base64.b64decode(args.key)
        msg = f"{args.client}:{args.service}:{ts}".encode()
        verifier = base64.b64encode(
            cryptoutil.hmac_sha256(key, msg)).decode()
        out = rpc.post("/client/getticket", {
            "client_id": args.client, "service_id": args.service,
            "verifier": verifier, "ts": ts})
        # the reply is sealed with the client key; open it like sdk/auth does
        plain = cryptoutil.open_sealed(
            key, base64.b64decode(out["sealed"]), aad=args.client.encode())
        print(json.dumps(json.loads(plain.decode()), indent=2))
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
