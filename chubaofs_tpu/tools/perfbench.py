"""Hot-path perf harness — the mdtest/fio analog over real daemon clusters.

Reference counterpart: the published evaluation suite
(/root/reference/docs/source/evaluation/ — mdtest file create/stat/removal
ops/s, fio streaming MB/s, tiny-file TPS; BASELINE.md carries the numbers
from a 10-node 32-core cluster on 10 Gb/s networking). This harness measures
the SAME axes against a ProcCluster of real subprocess daemons, so every op
crosses the client/metanode/datanode process boundaries the way the
reference's benchmarks cross machines.

Single-host caveat (PERF.md records the scaling argument next to these
numbers): everything here shares one host's cores, so absolute figures are
per-node floors, not cluster aggregates. The reference's cluster numbers
scale out with node count because metadata partitions and data partitions
shard across machines — the same sharding this repo implements — so the
honest comparison is ops/s-per-metanode and MB/s-per-datanode.

Usage:
    python -m chubaofs_tpu.tools.perfbench [--clients N] [--files N]
        [--stream-mb N] [--root DIR]

Prints exactly ONE JSON line: {"metric": "mdtest_create_ops", ...,
"configs": {...}}. Diagnostics go to stderr.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def bench_metadata(cluster, volume: str, n_files: int, n_clients: int) -> dict:
    """mdtest analog: create / stat / remove ops/s, 1 and N clients.

    Each client works in its own directory (mdtest -u), so creates contend
    on the shared metanode partitions, not on a single directory lock."""
    from chubaofs_tpu.sdk.cluster import RemoteCluster

    out = {}
    for clients in sorted({1, n_clients}):
        fss = [RemoteCluster(cluster.master_addrs).client(volume)
               for _ in range(clients)]
        per = n_files // clients
        for fs, c in zip(fss, range(clients)):
            fs.mkdirs(f"/md{clients}/c{c}")

        def phase(verb):
            def client_run(args):
                fs, c = args
                base = f"/md{clients}/c{c}"
                for i in range(per):
                    verb(fs, f"{base}/f{i}")
            with ThreadPoolExecutor(clients) as pool:
                list(pool.map(client_run, zip(fss, range(clients))))

        dt = _timed(lambda: phase(lambda fs, p: fs.create(p)))
        out[f"create_ops_{clients}c"] = round(per * clients / dt, 1)
        dt = _timed(lambda: phase(lambda fs, p: fs.stat(p)))
        out[f"stat_ops_{clients}c"] = round(per * clients / dt, 1)
        dt = _timed(lambda: phase(lambda fs, p: fs.unlink(p)))
        out[f"remove_ops_{clients}c"] = round(per * clients / dt, 1)
        log(f"  mdtest {clients} client(s): "
            f"create={out[f'create_ops_{clients}c']} "
            f"stat={out[f'stat_ops_{clients}c']} "
            f"remove={out[f'remove_ops_{clients}c']} ops/s")
    return out


def bench_stream(cluster, volume: str, total_mb: int) -> dict:
    """fio analog: sequential write then read MB/s through the chain-repl
    path (one streaming client, 1 MiB IOs, 3-replica write amplification)."""
    from chubaofs_tpu.sdk.cluster import RemoteCluster

    fs = RemoteCluster(cluster.master_addrs).client(volume)
    chunk = b"\xa5" * (1 << 20)
    ino = fs.create("/stream.bin")
    t0 = time.perf_counter()
    for i in range(total_mb):
        fs.write_at(ino, i << 20, chunk)
    wdt = time.perf_counter() - t0
    t0 = time.perf_counter()
    got = 0
    for i in range(total_mb):
        got += len(fs.read_at(ino, i << 20, 1 << 20))
    rdt = time.perf_counter() - t0
    assert got == total_mb << 20
    out = {"seq_write_mbps": round(total_mb / wdt, 1),
           "seq_read_mbps": round(total_mb / rdt, 1)}
    log(f"  stream: write={out['seq_write_mbps']} read={out['seq_read_mbps']} MB/s")
    return out


def bench_smallfile(cluster, volume: str, n_files: int, size: int = 4096) -> dict:
    """Tiny-file TPS (create+write+read of 4 KiB files — the tiny-extent
    path; ref evaluation tiny.md)."""
    from chubaofs_tpu.sdk.cluster import RemoteCluster

    fs = RemoteCluster(cluster.master_addrs).client(volume)
    fs.mkdirs("/small")
    payload = b"s" * size
    t0 = time.perf_counter()
    for i in range(n_files):
        ino = fs.create(f"/small/f{i}")
        fs.write_at(ino, 0, payload)
    wdt = time.perf_counter() - t0
    t0 = time.perf_counter()
    for i in range(n_files):
        assert len(fs.read_file(f"/small/f{i}")) == size
    rdt = time.perf_counter() - t0
    out = {"smallfile_write_tps": round(n_files / wdt, 1),
           "smallfile_read_tps": round(n_files / rdt, 1)}
    log(f"  smallfile: write={out['smallfile_write_tps']} "
        f"read={out['smallfile_read_tps']} TPS")
    return out


def bench_meta_scale(root: str, volume: str = "metascale", dirs: int = 16,
                     seed_files: int = 24, files_per_phase: int = 12,
                     metanodes: int = 9, phases: tuple = (1, 3, 4),
                     wire_ms: float = 40.0,
                     workers_per_partition: int = 4) -> dict:
    """Metadata scale-out proof (ISSUE 15): aggregate create ops/s as ONE
    volume grows from 1 to >=4 meta partitions spread over >=2 metanode
    processes, via mid-range LOAD splits at the median live inode.

    Cluster shape: `metanodes` metanode daemons (9, so the measured phases'
    3-replica partition groups land on DISJOINT node triples — a node
    hosting two groups serializes their commit rounds through its single
    raft drain-pump thread, and with only 3 metanodes every node
    participates in every commit, so partitioning could spread nothing),
    and a deterministic `wire_ms` delay at the raft.drain
    failpoint in every daemon — the WAL-fsync + replication RTT every real
    deployment pays per commit round (bench_put_pipeline's `_wire`
    rationale: in-process commits cost ~0 wall, so without it there is
    nothing for partition parallelism to overlap on a shared CI host).

    Methodology: WEAK scaling — client concurrency grows with the partition
    count (`workers_per_partition` x partitions), the mdtest scale-out
    convention: a metadata plane that splits exists to serve MORE
    concurrent clients, and holding the client herd fixed would only
    re-measure per-client latency. The workload is the directory-heavy
    tenant of arxiv 1709.05365: `dirs` directories created INTERLEAVED
    with seed files so the dir inos spread across the inode range (a
    median split then leaves directories on BOTH sides), parents resolved
    once (the mdtest cached-handle shape). Between phases
    /metaPartition/split grows the layout — the same machinery
    CFS_META_SPLIT_OPS drives from heartbeat loads, triggered explicitly
    so phase boundaries are deterministic: splitting the TAIL chains a
    cursor split (dead lower half, headroom-capped hot half, fresh tail),
    splitting a mid partition adds one. Dirs on allocating partitions keep
    the combined single-commit path; dirs on dead ranges pay dentry-local
    + tail-inode two-op commits. Each phase warms one untimed create per
    dir first (fills the client's full-partition cache so ERANGE probe
    rounds stay out of the window).

    Correctness gates (the tier-1 smoke): every phase reaches its exact
    partition count, ranges stay contiguous/disjoint, no duplicate ino is
    ever handed out, every create lands exactly once (per-dir readdir
    census), and the final layout has raft leaders on >=2 distinct
    metanodes. The scaling numbers ride the BENCH json (PERF.md policy:
    no perf floors in tier-1 on co-tenant CI hosts)."""
    import stat as stat_mod

    from chubaofs_tpu.meta.service import RemoteMetaNode
    from chubaofs_tpu.sdk.cluster import RemoteCluster
    from chubaofs_tpu.testing.harness import ProcCluster

    cluster = ProcCluster(
        root, masters=1, metanodes=metanodes, datanodes=0,
        env={"CFS_FAILPOINTS": f"raft.drain=delay({wire_ms / 1000.0})"}
        if wire_ms > 0 else None)
    try:
        return _meta_scale_phases(cluster, volume, dirs, seed_files,
                                  files_per_phase, phases,
                                  workers_per_partition,
                                  RemoteCluster, RemoteMetaNode, stat_mod)
    finally:
        cluster.close()


def _meta_scale_phases(cluster, volume, dirs, seed_files, files_per_phase,
                       phases, workers_per_partition,
                       RemoteCluster, RemoteMetaNode, stat_mod) -> dict:
    mc = cluster.client_master()
    mc.create_volume(volume, cold=True)
    setup_fs = RemoteCluster(cluster.master_addrs).client(volume)
    expected: dict[int, set] = {d: set() for d in range(dirs)}
    dir_inos: list[int] = []
    for d in range(dirs):
        dir_inos.append(setup_fs.mkdirs(f"/d{d}"))
        for i in range(seed_files):
            setup_fs.create(f"/d{d}/seed{i}")
            expected[d].add(f"seed{i}")

    max_workers = workers_per_partition * phases[-1]
    fss = []
    for _ in range(max_workers):
        fs = RemoteCluster(cluster.master_addrs).client(volume)
        # the measurement window outlives the default view TTL; routing
        # refreshes are error-driven (EWRONGPART) during the window, so a
        # mid-window TTL refresh would only clear the full-partition cache
        # and re-pay ERANGE probe rounds
        fs.meta.VIEW_TTL = 300.0
        fss.append(fs)
    out: dict = {}

    def mps():
        return sorted(mc.meta_partitions(volume), key=lambda m: m["start"])

    def split_to(target: int):
        """Split toward `target` partitions, always splitting the partition
        holding the MOST measured directories (tie: the highest range —
        later partitions are the ones with allocation headroom, and
        splitting those keeps the combined-create path alive)."""
        while len(mps()) < target:
            def dirs_in(m):
                end = m["end"] if m["end"] > 0 else (1 << 63)
                return sum(1 for ino in dir_inos if m["start"] <= ino < end)

            cands = sorted(mps(), key=lambda m: (-dirs_in(m), -m["start"]))
            for m in cands:
                new_pid = mc.split_meta_partition(
                    volume, m["partition_id"])["new_pid"]
                if new_pid:
                    break
            else:
                raise RuntimeError("no partition would split "
                                   f"(view: {mps()})")

    def create_one(fs, parent: int, name: str) -> int:
        """One create with the parent handle CACHED (no per-create path
        resolution): the combined single-commit fast path when the parent's
        partition allocates, else the two-op flow — FsClient._create_node's
        exact contract, minus the resolve."""
        mode = stat_mod.S_IFREG | 0o644
        inode = fs.meta.create_file(parent, name, mode, quota_ids=[])
        if inode is None:
            inode = fs.meta.create_inode(mode)
            fs.meta.create_dentry(parent, name, inode.ino, inode.mode)
        return inode.ino

    def measure(tag: str, parts: int) -> float:
        workers = workers_per_partition * parts
        # warm-up: one untimed create per dir per client herd — routes
        # refresh, ERANGE probes land in _full_pids, raft leaders settle
        for d in range(dirs):
            create_one(fss[d % workers], dir_inos[d], f"{tag}_warm")
            expected[d].add(f"{tag}_warm")
        inos: list[list[int]] = [[] for _ in range(workers)]

        def worker(w: int):
            fs = fss[w]
            for d in range(w, dirs, workers):
                parent = dir_inos[d]
                for i in range(files_per_phase):
                    inos[w].append(create_one(fs, parent, f"{tag}_{i}"))
                    expected[d].add(f"{tag}_{i}")

        t0 = time.perf_counter()
        with ThreadPoolExecutor(workers) as pool:
            list(pool.map(worker, range(workers)))
        dt = time.perf_counter() - t0
        made = dirs * files_per_phase
        flat = [i for per in inos for i in per]
        assert len(flat) == made and len(set(flat)) == len(flat), \
            "duplicate or missing ino"
        rate = made / dt
        log(f"  meta-scale {parts}p x{workers}w: {made} creates in "
            f"{dt:.2f}s = {rate:.1f} ops/s")
        return rate

    for parts in phases:
        split_to(parts)
        view = mps()
        assert len(view) == parts, (parts, view)
        # contiguous + disjoint ranges: no ino owned by zero/two partitions
        for a, b in zip(view, view[1:]):
            assert a["end"] == b["start"], f"range gap/overlap: {view}"
        out[f"meta_create_ops_{parts}p"] = round(measure(f"p{parts}", parts), 1)

    # census: every create landed exactly once, across every boundary
    census_fs = RemoteCluster(cluster.master_addrs).client(volume)
    for d in range(dirs):
        names = census_fs.readdir(f"/d{d}")
        assert len(names) == len(set(names)), f"dup dentries in /d{d}"
        missing = expected[d] - set(names)
        extra = set(names) - expected[d]
        assert not missing and not extra, \
            f"/d{d}: missing={sorted(missing)[:4]} extra={sorted(extra)[:4]}"

    # leader spread: the final layout's raft leaders live on >=2 metanodes
    leaders: dict[int, int] = {}
    for n in mc.get_cluster()["nodes"]:
        if n["kind"] != "meta" or not n["addr"]:
            continue
        h = RemoteMetaNode(n["addr"])
        try:
            for pid, is_lead in h.partition_leaders().items():
                if is_lead:
                    leaders[pid] = n["node_id"]
        finally:
            h.close()
    view_pids = {m["partition_id"] for m in mps()}
    lead_nodes = {leaders[pid] for pid in view_pids if pid in leaders}
    out["meta_leader_nodes"] = len(lead_nodes)
    assert len(lead_nodes) >= 2, \
        f"partitions not spread: leaders {leaders} for {sorted(view_pids)}"
    lo, hi = phases[0], phases[-1]
    out["meta_scale_speedup"] = round(
        out[f"meta_create_ops_{hi}p"]
        / max(0.001, out[f"meta_create_ops_{lo}p"]), 2)
    log(f"  meta-scale: {lo}p -> {hi}p aggregate create speedup "
        f"x{out['meta_scale_speedup']}, leaders on "
        f"{out['meta_leader_nodes']} metanodes")
    return out


def bench_raft_commit(wal_root: str, n_ops: int = 600) -> dict:
    """Raft-commit microbench: single-group commits/s at 1/8/64 concurrent
    proposers — the exact axis the round-5 metadata gap was diagnosed on
    (VERDICT: the reference drains up to 64 pending proposals into one
    replication round, raft.go:283-311; this measures our group commit the
    same way). A real 3-node MultiRaft over InProcNet with per-group WALs;
    every proposer loops propose -> wait-for-apply, so any coalescing comes
    ONLY from the consensus layer's pending-queue drain, not the harness."""
    from chubaofs_tpu.raft import InProcNet, MultiRaft, NotLeaderError, StateMachine
    from chubaofs_tpu.raft.server import TickLoop, run_until

    class _CountSM(StateMachine):
        def __init__(self):
            self.applied = 0

        def apply(self, data, index):
            self.applied += 1
            return index

        def snapshot(self):
            return b""

        def restore(self, data):
            pass

    net = InProcNet()
    nodes = {i: MultiRaft(i, net, wal_dir=os.path.join(wal_root, f"n{i}"))
             for i in (1, 2, 3)}
    for n in nodes.values():
        n.create_group(1, [1, 2, 3], _CountSM())
    assert run_until(net, lambda: any(n.is_leader(1) for n in nodes.values()))
    lead = next(n for n in nodes.values() if n.is_leader(1))
    loop = TickLoop(list(nodes.values()))
    loop.start()
    out = {}
    try:
        for clients in (0, 1, 8, 64):
            # clients=0 is the UNBATCHED control: max_batch=1 defeats group
            # commit (one log-append + WAL flush + fan-out per proposal, the
            # pre-batching behavior) under a single proposer — the baseline
            # the 64-proposer batched rate is judged against
            unbatched = clients == 0
            if unbatched:
                clients, lead.groups[1].core.max_batch = 1, 1
            else:
                lead.groups[1].core.max_batch = 64
            per = max(1, n_ops // clients)

            def proposer(c):
                for i in range(per):
                    for _ in range(3):  # stable net: retries are paranoia
                        try:
                            lead.propose(1, ("op", c, i)).result(timeout=30)
                            break
                        except NotLeaderError:
                            time.sleep(0.05)

            def one_pass() -> float:
                t0 = time.perf_counter()
                with ThreadPoolExecutor(clients) as pool:
                    list(pool.map(proposer, range(clients)))
                return per * clients / (time.perf_counter() - t0)

            lead.drain_stats_reset()
            # best-of-2: this is a 2-vCPU shared dev host; a co-tenant burst
            # in either pass must not masquerade as a batching regression
            rate = max(one_pass(), one_pass())
            key = "raft_commit_ops_1p_unbatched" if unbatched \
                else f"raft_commit_ops_{clients}p"
            out[key] = round(rate, 1)
            st = lead.drain_stats_snapshot()  # consistent multi-field read
            avg_b = st["entries"] / max(1, st["rounds"])
            if not unbatched:
                out[f"raft_commit_batch_{clients}p"] = round(avg_b, 1)
            log(f"  raft-commit {clients} proposer(s)"
                f"{' UNBATCHED' if unbatched else ''}: {out[key]} commits/s "
                f"(avg drained batch {avg_b:.1f}, max {st['max_batch']})")

        # the batch-aware submit path itself: 64 proposals in flight as
        # 8 clients x 8-deep propose_batch windows — what a batching caller
        # (combined-op SDK flows, freelist sweeps) actually exercises
        from concurrent.futures import wait as fut_wait

        per = max(1, n_ops // 64)

        def batch_proposer(c):
            for i in range(per):
                for _ in range(3):
                    try:
                        futs = lead.propose_batch(
                            1, [("op", c, i, j) for j in range(8)])
                        fut_wait(futs, timeout=30)
                        break
                    except NotLeaderError:
                        time.sleep(0.05)

        def batch_pass() -> float:
            t0 = time.perf_counter()
            with ThreadPoolExecutor(8) as pool:
                list(pool.map(batch_proposer, range(8)))
            return per * 8 * 8 / (time.perf_counter() - t0)

        out["raft_commit_ops_8x8"] = round(max(batch_pass(), batch_pass()), 1)
        log(f"  raft-commit 8 clients x 8-deep propose_batch: "
            f"{out['raft_commit_ops_8x8']} commits/s")
    finally:
        loop.stop()
    return out


def bench_put_pipeline(root: str, blob_kb: int = 64, n_puts: int = 8,
                       blob_counts: tuple = (1, 4, 16),
                       n_nodes: int = 6, wire_ms: float = 10.0) -> dict:
    """Blobstore data-path pipeline A/B (ISSUE 4): PUT (and a widest-object
    GET) throughput through a real AccessGateway HTTP hop, on two axes in
    ONE run — pipeline on/off (windowed encode->write overlap vs the
    serialized per-blob path) x pooled/unpooled RPC (keep-alive connection
    pool vs connect-per-request). Multi-blob objects are forced by shrinking
    max_blob_size, so the 16-blob config exercises the full window without
    64 MiB objects.

    Two latency regimes are emitted side by side: the raw in-process numbers
    (blobnodes are objects in this process — the shard hop costs ~0 wire
    time, so overlap can only exploit CPU/file-IO parallelism), and `_wire`
    configs with a deterministic `wire_ms` per-shard delay injected at the
    access.write_shard / access.read_shard chaos failpoints — the
    deployment shape, where the gateway->blobnode hop is a network RTT and
    hiding it behind the codec is the whole point of the pipeline (the
    reference's own numbers ride a 10 Gb/s fabric, BASELINE.md). Also emits
    the pipelined/serial speedups, the realized overlap ratio from the
    access registry, and the rpc pool hit rate over the pooled phase."""
    from chubaofs_tpu.blobstore.cluster import MiniCluster
    from chubaofs_tpu.blobstore.gateway import AccessClient, AccessGateway
    from chubaofs_tpu.utils import exporter

    c = MiniCluster(os.path.join(root, "blob"), n_nodes=n_nodes,
                    disks_per_node=2)
    c.access.max_blob_size = blob_kb * 1024
    gw = AccessGateway(c.access)
    rpc_reg = exporter.registry("rpc")

    def pool_ctrs() -> tuple[float, float]:
        return (rpc_reg.counter("pool_reuse").value,
                rpc_reg.counter("pool_miss").value)

    out: dict = {}
    rng_data = {nb: os.urandom(nb * blob_kb * 1024) for nb in blob_counts}
    clients = {False: AccessClient([gw.addr], pooled=False),
               True: AccessClient([gw.addr], pooled=True)}
    variants = [(pooled, window)
                for pooled in (False, True) for window in (0, 3)]
    pool_hits = pool_misses = 0.0
    try:
        # warm every path once (vuid creation, codec jit shapes, pool fill)
        for pooled, window in variants:
            c.access.pipeline_window = window
            loc = clients[pooled].put(rng_data[max(blob_counts)])
            assert clients[pooled].get(loc) == rng_data[max(blob_counts)]
        def one_variant(pooled: bool, window: int, suffix: str):
            nonlocal pool_hits, pool_misses
            client = clients[pooled]
            c.access.pipeline_window = window
            variant = (f"{'pipe' if window else 'serial'}_"
                       f"{'pooled' if pooled else 'nopool'}")
            if pooled:
                reuse0, miss0 = pool_ctrs()
            for nb in blob_counts:
                data = rng_data[nb]
                # per-op timing, min-of-puts (timeit discipline): a co-
                # tenant burst inflates SOME puts on this shared host;
                # the fastest op is what the path can actually do
                best = 1e9
                for _ in range(n_puts):
                    t0 = time.perf_counter()
                    loc = client.put(data)
                    best = min(best, time.perf_counter() - t0)
                key = f"put_{nb}b_{variant}{suffix}_mbps"
                rate = round(len(data) / best / 2**20, 1)
                out[key] = max(out.get(key, 0.0), rate)
                assert client.get(loc) == data
            # GET readahead A/B on the widest object only
            nb = max(blob_counts)
            loc = client.put(rng_data[nb])
            best = 1e9
            for _ in range(n_puts):
                t0 = time.perf_counter()
                client.get(loc)
                best = min(best, time.perf_counter() - t0)
            gkey = f"get_{nb}b_{variant}{suffix}_mbps"
            out[gkey] = max(out.get(gkey, 0.0), round(
                len(rng_data[nb]) / best / 2**20, 1))
            if pooled:
                reuse1, miss1 = pool_ctrs()
                pool_hits += reuse1 - reuse0
                pool_misses += miss1 - miss0

        # 2 interleaved passes per regime, best-of per config: a co-tenant
        # burst on this shared host must not masquerade as (or mask) a
        # pipeline effect
        from chubaofs_tpu import chaos

        suffixes = ("",) if wire_ms <= 0 else ("", "_wire")
        for suffix in suffixes:
            if suffix:
                # deterministic emulated gateway->blobnode RTT on every
                # shard read/write — the deployment's latency shape
                chaos.arm("access.write_shard", f"delay({wire_ms / 1000.0})")
                chaos.arm("access.read_shard", f"delay({wire_ms / 1000.0})")
            try:
                for _ in range(2):
                    for pooled, window in variants:
                        one_variant(pooled, window, suffix)
            finally:
                if suffix:
                    chaos.disarm("access.write_shard")
                    chaos.disarm("access.read_shard")
        for k in sorted(out):
            log(f"  put-pipeline {k} = {out[k]}")
        out["rpc_pool_hit_rate"] = round(
            pool_hits / max(1.0, pool_hits + pool_misses), 3)
        nb = max(blob_counts)
        for suffix in suffixes:
            out[f"put_pipeline_speedup{suffix}"] = round(
                out[f"put_{nb}b_pipe_pooled{suffix}_mbps"]
                / max(0.001, out[f"put_{nb}b_serial_nopool{suffix}_mbps"]), 2)
        ov = exporter.registry("access").summary("put_overlap_ratio").snapshot()
        out["put_overlap_ratio_avg"] = round(
            ov["sum"] / ov["count"], 2) if ov["count"] else 0.0
        log(f"  put-pipeline speedup({nb}b) x{out['put_pipeline_speedup']} raw"
            + (f" / x{out['put_pipeline_speedup_wire']} wire" if wire_ms > 0
               else "")
            + f", overlap {out['put_overlap_ratio_avg']}, "
              f"pool hit rate {out['rpc_pool_hit_rate']}")
    finally:
        gw.stop()
        c.close()
    return out


def bench_repair(root: str, n_nodes: int = 6, disks_per_node: int = 2,
                 stripes: int = 16, blob_kb: int = 256,
                 wire_ms: float = 2.0, window: int = 4) -> dict:
    """Repair-plane A/B (ISSUE 7): stripes/s rebuilt off a broken disk,
    serial control (repair_window=0) vs the windowed download↔decode
    pipeline, under a deterministic `wire_ms` per-shard-read delay — the
    deployment's gateway->blobnode RTT, same rationale as
    bench_put_pipeline's _wire regime (in-process reads cost ~0, so without
    it there is nothing for the pipeline to hide). The broken source is a
    KILLED NODE (engine closed and unrouted), not a merely-flagged disk, so
    every rebuilt row really is reconstructed from survivors through the
    batched device decode — a flagged-but-alive disk would let the migrate
    degenerate to a copy and the decode leg would measure nothing. Each
    phase runs on a fresh cluster with identical payloads; every repaired
    object must read back byte-identical (a miscompare raises). Also emits
    the realized download/decode overlap ratio (from the repair spans, via
    the scheduler's cfs_scheduler_repair_overlap_ratio summary) and
    bytes-downloaded-per-repaired-shard."""
    from chubaofs_tpu import chaos
    from chubaofs_tpu.blobstore.cluster import MiniCluster
    from chubaofs_tpu.blobstore.clustermgr import DISK_BROKEN
    from chubaofs_tpu.utils import exporter

    reg = exporter.registry("scheduler")
    payloads = [os.urandom(blob_kb * 1024) for _ in range(stripes)]

    def phase(label: str, win: int) -> tuple[int, float]:
        c = MiniCluster(os.path.join(root, label), n_nodes=n_nodes,
                        disks_per_node=disks_per_node)
        try:
            c.worker.set_repair_window(win)  # resizes the stripe pool too
            locs = [c.access.put(p) for p in payloads]
            # kill the most-loaded node: its disks' repair tasks then cover
            # the widest reconstruct set this little cluster can produce
            load = {n: 0 for n in c.nodes}
            for d in c.cm.disks.values():
                load[d.node_id] = load.get(d.node_id, 0) + d.chunk_count
            victim = max(load, key=load.get)
            c.nodes.pop(victim).close()
            for d in c.cm.disks.values():
                if d.node_id == victim:
                    c.cm.set_disk_status(d.disk_id, DISK_BROKEN)
            shards0 = reg.counter("repaired_shards").value
            if wire_ms > 0:
                chaos.arm("blobnode.get_shard", f"delay({wire_ms / 1000.0})")
            t0 = time.perf_counter()
            try:
                c.scheduler.check_disks()
                while c.worker.run_once():
                    pass
                dt = time.perf_counter() - t0
            finally:
                if wire_ms > 0:
                    chaos.disarm("blobnode.get_shard")
            rebuilt = int(reg.counter("repaired_shards").value - shards0)
            for loc, p in zip(locs, payloads):
                assert c.access.get(loc) == p, \
                    f"repaired stripe miscompares ({label})"
            return rebuilt, dt
        finally:
            c.close()

    out: dict = {}
    bytes0 = reg.counter("repair_bytes_downloaded").value
    rebuilt_s, dt_s = phase("serial", 0)
    # pass the writer's bucket spec: a bucket-less reader minting the family
    # first would make the scheduler's later observe() fail loudly
    ov0 = reg.summary("repair_overlap_ratio",
                      buckets=exporter.RATIO_BUCKETS).snapshot()
    rebuilt_p, dt_p = phase("pipelined", window)
    ov1 = reg.summary("repair_overlap_ratio",
                      buckets=exporter.RATIO_BUCKETS).snapshot()
    dl_bytes = reg.counter("repair_bytes_downloaded").value - bytes0
    out["repair_rows_serial"] = rebuilt_s
    out["repair_rows_pipelined"] = rebuilt_p
    out["repair_stripes_s_serial"] = round(rebuilt_s / max(1e-9, dt_s), 1)
    out["repair_stripes_s_pipelined"] = round(rebuilt_p / max(1e-9, dt_p), 1)
    out["repair_speedup"] = round(
        out["repair_stripes_s_pipelined"]
        / max(0.001, out["repair_stripes_s_serial"]), 2)
    n_obs = ov1["count"] - ov0["count"]
    out["repair_overlap_ratio"] = round(
        (ov1["sum"] - ov0["sum"]) / n_obs, 3) if n_obs else 0.0
    total_rows = max(1, rebuilt_s + rebuilt_p)
    out["repair_bytes_per_shard"] = round(dl_bytes / total_rows, 1)
    log(f"  repair: serial {out['repair_stripes_s_serial']}/s vs pipelined "
        f"{out['repair_stripes_s_pipelined']}/s "
        f"(x{out['repair_speedup']}), overlap "
        f"{out['repair_overlap_ratio']}, "
        f"{out['repair_bytes_per_shard']} bytes/shard")
    return out


def bench_repair_codes(root: str, n_nodes: int = 17, stripes: int = 12,
                       blob_kb: int = 120, wire_ms: float = 2.0,
                       window: int = 4) -> dict:
    """Repair-traffic A/B (ISSUE 19): identical blob bytes rebuilt off a
    killed node under the product-matrix regenerating code RG6P6 (β-fetch:
    d=10 helpers each ship a GF-combined shard/5 slice, 2 shard-equivalents
    per row) vs classic RS EC12P4 (k=12 full shards per row). One disk per
    node so the kill loses exactly ONE unit per stripe — the single-loss
    regime the β path exists for; a two-disk node would alias two stripe
    positions onto the victim and silently turn the RG phase into its own
    multi-loss fallback. Same wire regime and byte-identical read-back
    rules as bench_repair. Hedged bytes are excluded from the numerator by
    the scheduler's need-aware accounting, so bytes-per-repaired-shard is
    pure required traffic. Emits per-mode bytes/shard, download
    amplification (bytes downloaded / bytes rebuilt — shard sizes differ
    across modes, amplification doesn't), stripes/s, overlap ratio, and
    the headline reduction the acceptance gate rides (>=25%; the geometry
    predicts ~67% on bytes/shard, ~83% on amplification)."""
    from chubaofs_tpu import chaos
    from chubaofs_tpu.blobstore.cluster import MiniCluster
    from chubaofs_tpu.blobstore.clustermgr import DISK_BROKEN
    from chubaofs_tpu.codec.codemode import CodeMode, get_tactic
    from chubaofs_tpu.utils import exporter

    reg = exporter.registry("scheduler")

    def phase(label: str, mode: CodeMode, payloads: list[bytes]) -> dict:
        c = MiniCluster(os.path.join(root, label), n_nodes=n_nodes,
                        disks_per_node=1)
        try:
            c.worker.set_repair_window(window)
            locs = [c.access.put(p, code_mode=mode) for p in payloads]
            load = {n: 0 for n in c.nodes}
            for d in c.cm.disks.values():
                load[d.node_id] = load.get(d.node_id, 0) + d.chunk_count
            victim = max(load, key=load.get)
            c.nodes.pop(victim).close()
            for d in c.cm.disks.values():
                if d.node_id == victim:
                    c.cm.set_disk_status(d.disk_id, DISK_BROKEN)
            shards0 = reg.counter("repaired_shards").value
            bytes0 = reg.counter("repair_bytes_downloaded").value
            beta0 = reg.counter("repair_beta_shards").value
            ov0 = reg.summary("repair_overlap_ratio",
                              buckets=exporter.RATIO_BUCKETS).snapshot()
            if wire_ms > 0:
                chaos.arm("blobnode.get_shard", f"delay({wire_ms / 1000.0})")
            t0 = time.perf_counter()
            try:
                c.scheduler.check_disks()
                while c.worker.run_once():
                    pass
                dt = time.perf_counter() - t0
            finally:
                if wire_ms > 0:
                    chaos.disarm("blobnode.get_shard")
            rebuilt = int(reg.counter("repaired_shards").value - shards0)
            dl = int(reg.counter("repair_bytes_downloaded").value - bytes0)
            ov1 = reg.summary("repair_overlap_ratio",
                              buckets=exporter.RATIO_BUCKETS).snapshot()
            for loc, p in zip(locs, payloads):
                assert c.access.get(loc) == p, \
                    f"repaired stripe miscompares ({label})"
            shard_len = get_tactic(mode).shard_size(blob_kb * 1024)
            n_obs = ov1["count"] - ov0["count"]
            return {
                "rows": rebuilt,
                "stripes_s": round(rebuilt / max(1e-9, dt), 1),
                "bytes_per_shard": round(dl / max(1, rebuilt), 1),
                "amp": round(dl / max(1, rebuilt * shard_len), 2),
                "overlap": round((ov1["sum"] - ov0["sum"]) / n_obs, 3)
                if n_obs else 0.0,
                "beta_rows": int(reg.counter("repair_beta_shards").value
                                 - beta0),
            }
        finally:
            c.close()

    payloads = [os.urandom(blob_kb * 1024) for _ in range(stripes)]
    # discarded warmup repair: in a full run the RS decode paths arrive
    # pre-warmed by bench_repair while the PM kernel/bit-matrix lowering
    # would JIT inside the RG timed region, skewing stripes/s ~3x cold
    phase("warmup", CodeMode.RG6P6, payloads[:2])
    rg = phase("rg6p6", CodeMode.RG6P6, payloads)
    rs = phase("ec12p4", CodeMode.EC12P4, payloads)
    out = {
        "repair_codes_rows_rg": rg["rows"],
        "repair_codes_rows_rs": rs["rows"],
        "repair_codes_beta_rows": rg["beta_rows"],
        "repair_codes_bytes_per_shard_rg": rg["bytes_per_shard"],
        "repair_codes_bytes_per_shard_rs": rs["bytes_per_shard"],
        "repair_codes_amp_rg": rg["amp"],
        "repair_codes_amp_rs": rs["amp"],
        "repair_codes_reduction": round(
            1.0 - rg["bytes_per_shard"] / max(1.0, rs["bytes_per_shard"]), 3),
        "repair_codes_amp_reduction": round(
            1.0 - rg["amp"] / max(0.001, rs["amp"]), 3),
        "repair_codes_stripes_s_rg": rg["stripes_s"],
        "repair_codes_stripes_s_rs": rs["stripes_s"],
        "repair_codes_overlap_rg": rg["overlap"],
        "repair_codes_overlap_rs": rs["overlap"],
    }
    log(f"  repair-codes: RG6P6 {rg['bytes_per_shard']} B/shard "
        f"(amp x{rg['amp']}) vs EC12P4 {rs['bytes_per_shard']} B/shard "
        f"(amp x{rs['amp']}) -> -{out['repair_codes_reduction'] * 100:.0f}% "
        f"bytes, {rg['stripes_s']}/s vs {rs['stripes_s']}/s, "
        f"overlap {rg['overlap']}/{rs['overlap']}")
    return out


def _conc_driver(addr: str, n_socks: int, ops: int, payload: int) -> None:
    """Subprocess body for bench_concurrency's load generator. Runs OUT of
    the server's process: an in-process driver shares the server's GIL, and
    at 256+ clients the load generation drowns out the serving-model
    difference the A/B exists to measure. Protocol with the parent: connect
    + warm every socket, print READY, block for GO on stdin, run the timed
    loop, print one JSON line of per-request latencies (ms)."""
    import socket as _socket
    import threading

    from chubaofs_tpu.proto.packet import (
        OP_WRITE, Packet, recv_packet, send_packet)

    host, port = addr.rsplit(":", 1)
    req = Packet(OP_WRITE, partition_id=1, extent_id=65,
                 data=b"\xa7" * payload)
    socks = []
    for _ in range(n_socks):
        s = _socket.create_connection((host, int(port)))
        s.settimeout(60)
        s.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        send_packet(s, req)  # warm: conn registration, framer state
        recv_packet(s)
        socks.append(s)
    print("READY", flush=True)
    sys.stdin.readline()  # GO
    n_threads = max(1, min(8, n_socks))
    chunks = [socks[t::n_threads] for t in range(n_threads)]
    lats: list[list[float]] = [[] for _ in range(n_threads)]

    def run(t: int) -> None:
        mine, out = chunks[t], lats[t]
        t0s = [0.0] * len(mine)
        for _ in range(ops):
            for i, s in enumerate(mine):  # one in-flight request per socket
                t0s[i] = time.perf_counter()
                send_packet(s, req)
            for i, s in enumerate(mine):
                recv_packet(s)
                out.append(time.perf_counter() - t0s[i])

    threads = [threading.Thread(target=run, args=(t,), daemon=True)
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for s in socks:
        s.close()
    print(json.dumps([round(x * 1000.0, 3) for chunk in lats
                      for x in chunk]), flush=True)


_CONC_DRIVER_CMD = (
    "import sys\n"
    "from chubaofs_tpu.tools.perfbench import _conc_driver\n"
    "_conc_driver(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]),"
    " int(sys.argv[4]))\n")


def bench_concurrency(clients_axis: tuple = (64, 256, 1024),
                      ops_per_client: int = 20, payload: int = 4096) -> dict:
    """High fan-in packet-serving A/B (ISSUE 8): ops/s and p99 latency at
    64/256/1024 concurrent packet connections, event-loop serving vs the
    CFS_EVLOOP=0 thread-per-connection baseline, against a real ReplServer
    whose dispatch does representative per-op work (CRC verify + small
    reply). The client harness is identical in both phases — up to 4
    subprocess drivers (own GIL each, see _conc_driver) with 8 threads
    apiece, one in-flight request per socket — so the only variable is the
    serving model. Per-request latency is measured send→reply per socket;
    p99 over every request of the phase, so fan-in queueing (the thing
    thread stacks and GIL churn inflate) lands in the number."""
    from chubaofs_tpu.data.repl import ReplServer
    from chubaofs_tpu.proto.packet import Packet, RES_OK

    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    def dispatch(pkt: Packet) -> Packet:
        # representative op cost: payload CRC + a small ack (the datanode
        # write path's shape without the disk)
        ok = pkt.verify_crc()
        return pkt.reply(RES_OK if ok else 1, data=bytes(pkt.data[:32]))

    def phase(mode: str, n_clients: int) -> tuple[float, float]:
        prev_env = os.environ.get("CFS_EVLOOP")
        os.environ["CFS_EVLOOP"] = "1" if mode == "evloop" else "0"
        srv = None
        procs: list[subprocess.Popen] = []
        try:
            srv = ReplServer("127.0.0.1:0", dispatch)
            srv.start()
            n_procs = max(1, min(4, n_clients // 16))
            per = n_clients // n_procs
            env = dict(os.environ)
            env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
            procs = [
                subprocess.Popen(
                    [sys.executable, "-c", _CONC_DRIVER_CMD, srv.addr,
                     str(per), str(ops_per_client), str(payload)],
                    stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                    env=env, text=True)
                for _ in range(n_procs)
            ]
            for p in procs:  # all sockets connected + warmed before the clock
                if p.stdout.readline().strip() != "READY":
                    raise RuntimeError(
                        f"concurrency driver died during warm-up "
                        f"({mode}, {n_clients}c)")
            t0 = time.perf_counter()
            for p in procs:
                p.stdin.write("GO\n")
                p.stdin.flush()
            all_lats: list[float] = []
            for p in procs:
                line = p.stdout.readline()
                if not line.strip():
                    raise RuntimeError(
                        f"concurrency driver died mid-run "
                        f"({mode}, {n_clients}c)")
                all_lats.extend(json.loads(line))
            dt = time.perf_counter() - t0
            for p in procs:
                p.wait(timeout=30)
            if len(all_lats) != n_procs * per * ops_per_client:
                raise RuntimeError(
                    f"concurrency driver dropped requests "
                    f"({mode}, {n_clients}c): {len(all_lats)}")
            all_lats.sort()
            p99 = all_lats[min(len(all_lats) - 1, int(0.99 * len(all_lats)))]
            return len(all_lats) / dt, p99
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            if srv is not None:
                srv.stop()
            if prev_env is None:
                os.environ.pop("CFS_EVLOOP", None)
            else:
                os.environ["CFS_EVLOOP"] = prev_env

    out: dict = {}
    for n in clients_axis:
        for mode in ("threads", "evloop"):
            ops, p99 = phase(mode, n)
            out[f"conc_ops_{n}c_{mode}"] = round(ops, 1)
            out[f"conc_p99_ms_{n}c_{mode}"] = round(p99, 2)
            log(f"  concurrency {n}c {mode}: {out[f'conc_ops_{n}c_{mode}']} "
                f"ops/s, p99 {out[f'conc_p99_ms_{n}c_{mode}']} ms")
        out[f"conc_speedup_{n}c"] = round(
            out[f"conc_ops_{n}c_evloop"]
            / max(0.001, out[f"conc_ops_{n}c_threads"]), 2)
        out[f"conc_p99_ratio_{n}c"] = round(
            out[f"conc_p99_ms_{n}c_evloop"]
            / max(0.001, out[f"conc_p99_ms_{n}c_threads"]), 2)
    return out


def _gw_driver(addr: str, url: str, n_socks: int, ops: int,
               tolerate: int = 0) -> None:
    """Subprocess body for the gateway benches' load generator: keep-alive
    S3 GETs of one presigned URL over `n_socks` http.client connections,
    one in-flight request per connection — OUT of the server's process for
    the same reason as _conc_driver (an in-process driver measures the load
    generator, not the serving model). Pure stdlib: the URL is presigned by
    the parent, so the driver needs no signing code. `tolerate=1` accepts
    throttle statuses (429/503) and reports per-status counts (the QoS
    fairness bench's noisy tenant); otherwise any non-200 aborts the run.
    Protocol: connect + warm every socket, print READY, block for GO, run,
    print one JSON line {"lats": [...ms...], "statuses": {code: n}}."""
    import http.client as _hc
    import threading

    host, port = addr.rsplit(":", 1)

    def connect():
        c = _hc.HTTPConnection(host, int(port), timeout=60)  # obslint: bench driver — one keep-alive conn PER simulated client IS the workload; pooling would defeat the A/B
        c.connect()
        return c

    conns = [connect() for _ in range(n_socks)]
    for c in conns:  # warm: conn registration, framer state, a real GET
        c.request("GET", url, headers={"Host": addr})
        r = c.getresponse()
        r.read()
    print("READY", flush=True)
    sys.stdin.readline()  # GO
    n_threads = max(1, min(8, n_socks))
    chunks = [conns[t::n_threads] for t in range(n_threads)]
    lats: list[list[float]] = [[] for _ in range(n_threads)]
    statuses: list[dict] = [{} for _ in range(n_threads)]

    def run(t: int) -> None:
        mine, out, st = chunks[t], lats[t], statuses[t]
        for _ in range(ops):
            for i, c in enumerate(mine):
                t0 = time.perf_counter()
                try:
                    c.request("GET", url, headers={"Host": addr})
                    r = c.getresponse()
                    r.read()
                    status = r.status
                except Exception:
                    status = -1
                    mine[i] = connect()  # server closed a throttled conn
                out.append(time.perf_counter() - t0)
                st[status] = st.get(status, 0) + 1
                if status != 200 and not tolerate:
                    raise RuntimeError(f"gateway driver got HTTP {status}")

    threads = [threading.Thread(target=run, args=(t,), daemon=True)
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for c in conns:
        c.close()
    agg: dict = {}
    for st in statuses:
        for k, v in st.items():
            agg[str(k)] = agg.get(str(k), 0) + v
    print(json.dumps({"lats": [round(x * 1e3, 3) for ch in lats for x in ch],
                      "statuses": agg}), flush=True)


_GW_DRIVER_CMD = (
    "import sys\n"
    "from chubaofs_tpu.tools.perfbench import _gw_driver\n"
    "_gw_driver(sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),"
    " int(sys.argv[5]))\n")


def _paced_driver(addr: str, url: str, rate: float, duration: float,
                  warm_s: float = 1.0) -> None:
    """Subprocess body for the fairness bench's VICTIM: one keep-alive
    connection, open-loop paced at `rate` req/s for `duration` seconds —
    the tenant whose p99 the noisy neighbor must not wreck. The first
    `warm_s` seconds still COUNT toward goodput (statuses) but are
    excluded from the latency sample: phase start is when both drivers'
    connection storms land and the server's lazy worker pool spawns, a
    one-time transient that would otherwise own a small sample's p99.
    Prints the same JSON line shape as _gw_driver."""
    import http.client as _hc

    host, port = addr.rsplit(":", 1)
    c = _hc.HTTPConnection(host, int(port), timeout=60)  # obslint: bench driver — one keep-alive conn PER simulated client IS the workload; pooling would defeat the A/B
    c.request("GET", url, headers={"Host": addr})
    c.getresponse().read()
    print("READY", flush=True)
    sys.stdin.readline()
    lats: list[float] = []
    statuses: dict = {}
    t0 = time.perf_counter()
    n = 0
    while True:
        sched = t0 + n / rate
        now = time.perf_counter()
        if sched - now > 0:
            time.sleep(sched - now)
        if time.perf_counter() - t0 >= duration:
            break
        t1 = time.perf_counter()
        try:
            c.request("GET", url, headers={"Host": addr})
            r = c.getresponse()
            r.read()
            status = r.status
        except Exception:
            status = -1
            c = _hc.HTTPConnection(host, int(port), timeout=60)  # obslint: bench driver — one keep-alive conn PER simulated client IS the workload; pooling would defeat the A/B
        if t1 - t0 >= warm_s:
            lats.append(time.perf_counter() - t1)
        statuses[str(status)] = statuses.get(str(status), 0) + 1
        n += 1
    print(json.dumps({"lats": [round(x * 1e3, 3) for x in lats],
                      "statuses": statuses}), flush=True)


_PACED_DRIVER_CMD = (
    "import sys\n"
    "from chubaofs_tpu.tools.perfbench import _paced_driver\n"
    "_paced_driver(sys.argv[1], sys.argv[2], float(sys.argv[3]),"
    " float(sys.argv[4]))\n")


def _spawn_driver(cmd: str, argv: list) -> subprocess.Popen:
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen([sys.executable, "-c", cmd] + [str(a) for a in argv],
                            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                            env=env, text=True)


def _drive(procs: list, label: str) -> list[dict]:
    """READY/GO handshake + result collection for a set of driver procs."""
    for p in procs:
        if p.stdout.readline().strip() != "READY":
            raise RuntimeError(f"{label} driver died during warm-up")
    for p in procs:
        p.stdin.write("GO\n")
        p.stdin.flush()
    outs = []
    for p in procs:
        line = p.stdout.readline()
        if not line.strip():
            raise RuntimeError(f"{label} driver died mid-run")
        outs.append(json.loads(line))
    for p in procs:
        p.wait(timeout=30)
    return outs


def _p99(lats: list[float]) -> float:
    lats = sorted(lats)
    return lats[min(len(lats) - 1, int(0.99 * len(lats)))] if lats else 0.0


class _S3Fixture:
    """One FsCluster + ObjectNode the gateway benches serve: a bucket, a
    small object, presign() for driver URLs, serve()/stop() to bring an
    RPCServer up under the CURRENT CFS_EVLOOP_HTTP mode."""

    AK, SK = "benchak", "benchsk"

    def __init__(self, root: str, payload: int = 2048, qos=None):
        from chubaofs_tpu.deploy import FsCluster
        from chubaofs_tpu.objectnode.server import ObjectNode

        self.cluster = FsCluster(root, n_nodes=3, blob_nodes=6, data_nodes=0)
        self.node = ObjectNode(
            self.cluster, users={self.AK: {"secret_key": self.SK,
                                           "uid": "bench"}}, qos=qos)
        self.users = {self.AK: self.SK}
        self.srv = None
        self._payload = payload

    def serve(self):
        from chubaofs_tpu.rpc.server import RPCServer

        self.srv = RPCServer(self.node.router, metrics=False,
                             module="objectnode").start()
        return self.srv.addr

    def put_object(self, bucket: str = "bench", key: str = "obj",
                   ak: str | None = None, sk: str | None = None) -> None:
        import http.client as _hc

        from chubaofs_tpu.objectnode import auth as s3auth

        ak, sk = ak or self.AK, sk or self.SK
        host, port = self.srv.addr.rsplit(":", 1)
        for method, path, body in ((("PUT", f"/{bucket}", b"")),
                                   ("PUT", f"/{bucket}/{key}",
                                    b"\xa5" * self._payload)):
            hdrs = s3auth.sign_v4(method, path, "", {"host": self.srv.addr},
                                  ak, sk, payload=body)
            c = _hc.HTTPConnection(host, int(port))  # obslint: bench driver — one keep-alive conn PER simulated client IS the workload; pooling would defeat the A/B
            c.request(method, path, body=body, headers=hdrs)
            r = c.getresponse()
            r.read()
            c.close()
            if r.status != 200:
                raise RuntimeError(f"fixture {method} {path} -> {r.status}")

    def presign(self, bucket: str = "bench", key: str = "obj",
                ak: str | None = None, sk: str | None = None) -> str:
        from chubaofs_tpu.objectnode import auth as s3auth

        path = f"/{bucket}/{key}"
        q = s3auth.presign_v4("GET", path, self.srv.addr, ak or self.AK,
                              sk or self.SK)
        return f"{path}?{q}"

    def stop_server(self):
        if self.srv is not None:
            self.srv.stop()
            self.srv = None

    def close(self):
        self.stop_server()
        self.cluster.close()


def bench_gateway(root: str, clients_axis: tuple = (64, 256, 1024),
                  ops_per_client: int = 10, payload: int = 2048) -> dict:
    """Gateway serving-model A/B (ISSUE 14): ops/s and p99 at 64/256/1024
    keep-alive S3 client connections doing presigned GETs against a REAL
    ObjectNode over a real FsCluster — evloop HTTP core vs the
    CFS_EVLOOP_HTTP=0 ThreadingHTTPServer baseline, the bench_concurrency
    shape ported to the HTTP plane. Drivers are subprocesses (own GIL);
    the server is rebuilt per phase under the phase's serving mode; every
    request must be HTTP 200. The headline number is FLATNESS: evloop
    throughput at 1024c vs its own 64c value, where the threaded control
    degrades under 1024 parked handler threads."""
    fix = _S3Fixture(os.path.join(root, "gwbench"), payload=payload)
    out: dict = {}
    try:
        def phase(mode: str, n_clients: int) -> tuple[float, float]:
            prev = os.environ.get("CFS_EVLOOP_HTTP")
            os.environ["CFS_EVLOOP_HTTP"] = "1" if mode == "evloop" else "0"
            procs: list[subprocess.Popen] = []
            try:
                addr = fix.serve()
                if not out:  # first phase creates the bucket + object
                    fix.put_object()
                url = fix.presign()
                n_procs = max(1, min(4, n_clients // 16))
                per = n_clients // n_procs
                procs = [_spawn_driver(
                    _GW_DRIVER_CMD, [addr, url, per, ops_per_client, 0])
                    for _ in range(n_procs)]
                t0 = time.perf_counter()
                outs = _drive(procs, f"gateway {mode} {n_clients}c")
                dt = time.perf_counter() - t0
                lats = [x for o in outs for x in o["lats"]]
                bad = {k: v for o in outs for k, v in o["statuses"].items()
                       if k != "200"}
                if bad or len(lats) != n_procs * per * ops_per_client:
                    raise RuntimeError(
                        f"gateway driver anomalies ({mode}, {n_clients}c): "
                        f"bad={bad} n={len(lats)}")
                return len(lats) / dt, _p99(lats)
            finally:
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                fix.stop_server()
                if prev is None:
                    os.environ.pop("CFS_EVLOOP_HTTP", None)
                else:
                    os.environ["CFS_EVLOOP_HTTP"] = prev

        for n in clients_axis:
            for mode in ("threads", "evloop"):
                ops, p99 = phase(mode, n)
                out[f"gw_ops_{n}c_{mode}"] = round(ops, 1)
                out[f"gw_p99_ms_{n}c_{mode}"] = round(p99, 2)
                log(f"  gateway {n}c {mode}: {out[f'gw_ops_{n}c_{mode}']} "
                    f"ops/s, p99 {out[f'gw_p99_ms_{n}c_{mode}']} ms")
            out[f"gw_speedup_{n}c"] = round(
                out[f"gw_ops_{n}c_evloop"]
                / max(0.001, out[f"gw_ops_{n}c_threads"]), 2)
        lo, hi = clients_axis[0], clients_axis[-1]
        out["gw_flatness_evloop"] = round(
            out[f"gw_ops_{hi}c_evloop"]
            / max(0.001, out[f"gw_ops_{lo}c_evloop"]), 2)
        out["gw_flatness_threads"] = round(
            out[f"gw_ops_{hi}c_threads"]
            / max(0.001, out[f"gw_ops_{lo}c_threads"]), 2)
        log(f"  gateway flatness {lo}c->{hi}c: evloop "
            f"{out['gw_flatness_evloop']}x vs threads "
            f"{out['gw_flatness_threads']}x")
    finally:
        fix.close()
    return out


def bench_qos_fairness(root: str, parent_rps: float = 50.0,
                       victim_rps: float = 15.0, duration: float = 4.0,
                       noisy_socks: int = 24) -> dict:
    """Multi-tenant fairness A/B (ISSUE 14): a victim tenant paced at
    victim_rps measures its GET p99 SOLO, then again while a noisy tenant
    offers ~10x the victim's load through `noisy_socks` tight-loop
    connections — with the QoS plane armed (shared parent at parent_rps,
    deficit-fair dequeue, bounded queue wait). The noisy tenant must be
    CAPPED (throttle counters nonzero, 429/503 in its status mix) while
    the victim's p99 stays within a small factor of its solo baseline and
    its goodput holds."""
    from chubaofs_tpu.utils.qos import QosPlane

    ak_n, sk_n = "noisyak", "noisysk"
    # a saturated tenant's fair-queue waiters PARK a dispatch worker for up
    # to queue_ms each; the pool must be sized above the shaped concurrency
    # or the victim waits for a WORKER, not for tokens (the reserve bucket
    # can only protect admission, not a starved pool). Set BEFORE the plane
    # is built: FairLimiter bounds its waiter herd to half this pool.
    prev_workers = os.environ.get("CFS_EVLOOP_WORKERS")
    os.environ["CFS_EVLOOP_WORKERS"] = str(max(64, noisy_socks * 2))
    qos = QosPlane(("noisyak", "benchak"), rps=parent_rps,
                   tenant_min_rps=victim_rps * 2, queue_ms=50.0,
                   queue_len=16)
    fix = _S3Fixture(os.path.join(root, "qosbench"), payload=2048, qos=qos)
    fix.node.users[ak_n] = {"secret_key": sk_n, "uid": "noisy"}
    out: dict = {}
    try:
        addr = fix.serve()
        fix.put_object()  # victim's bucket (benchak owns it)
        # noisy tenant gets its own bucket/object so ACLs stay out of the way
        fix.put_object(bucket="noisy", key="obj", ak=ak_n, sk=sk_n)
        v_url = fix.presign()
        n_url = fix.presign(bucket="noisy", key="obj", ak=ak_n, sk=sk_n)

        def victim_phase(with_noise: bool) -> tuple[float, float, dict]:
            procs = [_spawn_driver(_PACED_DRIVER_CMD,
                                   [addr, v_url, victim_rps, duration])]
            if with_noise:
                procs.append(_spawn_driver(
                    _GW_DRIVER_CMD,
                    [addr, n_url, noisy_socks,
                     max(4, int(victim_rps * 10 * duration / noisy_socks)),
                     1]))
            try:
                outs = _drive(procs, "fairness")
            finally:
                for p in procs:
                    if p.poll() is None:
                        p.kill()
            vic = outs[0]
            noisy = outs[1]["statuses"] if with_noise else {}
            ok = vic["statuses"].get("200", 0)
            goodput = ok / max(duration, 1e-9)
            return _p99(vic["lats"]), goodput, noisy

        p99_solo, goodput_solo, _ = victim_phase(False)
        p99_mixed, goodput_mixed, noisy_st = victim_phase(True)
        thr = sum(v for k, v in noisy_st.items() if k in ("429", "503", "-1"))
        served = noisy_st.get("200", 0)
        out.update({
            "qos_victim_p99_solo_ms": round(p99_solo, 2),
            "qos_victim_p99_mixed_ms": round(p99_mixed, 2),
            "qos_victim_p99_ratio": round(p99_mixed / max(p99_solo, 1e-9), 2),
            "qos_victim_goodput_solo": round(goodput_solo, 1),
            "qos_victim_goodput_mixed": round(goodput_mixed, 1),
            "qos_victim_goodput_ratio": round(
                goodput_mixed / max(goodput_solo, 1e-9), 2),
            "qos_noisy_served": served,
            "qos_noisy_throttled": thr,
        })
        log(f"  qos fairness: victim p99 {out['qos_victim_p99_solo_ms']} -> "
            f"{out['qos_victim_p99_mixed_ms']} ms "
            f"(x{out['qos_victim_p99_ratio']}), goodput ratio "
            f"{out['qos_victim_goodput_ratio']}, noisy served {served} / "
            f"throttled {thr}")
    finally:
        if prev_workers is None:
            os.environ.pop("CFS_EVLOOP_WORKERS", None)
        else:
            os.environ["CFS_EVLOOP_WORKERS"] = prev_workers
        fix.close()
        qos.close()
    return out


def bench_capacity(root: str, duration: float = 3.5, rate: float = 20.0,
                   seed: int = 7, interval: float = 0.4,
                   tenants: int = 3) -> dict:
    """Capacity-harness smoke (ISSUE 11): the cfs-capacity generator /
    collector / gate loop at seconds scale over an IN-PROCESS FsCluster
    (whose access/codec registries this process's /health evaluates),
    fronted by a real RPCServer + console so the collector exercises the
    same `/api/health` + `/api/metrics` rollup path as a daemon cluster.

    Two phases on the same seed: a CLEAN run (the gate must evaluate to a
    non-None, non-failing verdict and archive >=3 JSONL frames) and a CHAOS
    run (a sustained `blobnode.put_shard` delay under a tightened PUT p99
    objective must flip the verdict to failing, naming put_p99) — the
    regression pair that keeps the gate honest in both directions."""
    from chubaofs_tpu import chaos
    from chubaofs_tpu.console.server import Console
    from chubaofs_tpu.deploy import FsCluster
    from chubaofs_tpu.rpc.router import Router
    from chubaofs_tpu.rpc.server import RPCServer
    from chubaofs_tpu.tools.capacity import (
        Collector, LocalDriver, Workload, plan_ops)
    from chubaofs_tpu.utils import metrichist

    out: dict = {}
    c = FsCluster(os.path.join(root, "cap"), n_nodes=3, blob_nodes=6,
                  data_nodes=0)
    srv = RPCServer(Router(), module="capacity").start()
    console = Console([srv.addr])

    def phase(report: str) -> tuple[dict, dict]:
        plan = plan_ops(seed, tenants, duration, rate, 1.2,
                        keys_per_tenant=32, ramp="diurnal")
        wl = Workload(LocalDriver(c, "capvol"), plan, seed=seed, workers=4)
        col = Collector(report, console=console.addr, interval=interval)
        col.start()
        try:
            ledger = wl.run()
            time.sleep(2 * interval)  # the tail burn windows land
        finally:
            col.stop()
            wl.close()
        return col.verdict(), ledger

    prev_slo = os.environ.get("CFS_SLO_PUT_P99_MS")
    try:
        c.create_volume("capvol", cold=True)
        c.blobstore.access.put(b"warm" * 256)  # jit outside the window
        verdict, ledger = phase(os.path.join(root, "capacity-clean.jsonl"))
        out["cap_frames_clean"] = verdict["frames"]
        out["cap_verdict_clean"] = verdict["verdict"]
        out["cap_ops_ok"] = ledger["ops_ok"]
        out["cap_ops_planned"] = ledger["ops_planned"]
        out["cap_corruptions"] = len(ledger["corruptions"])
        out["cap_max_late_s"] = ledger["max_late_s"]
        log(f"  capacity clean: verdict={verdict['verdict']} "
            f"frames={verdict['frames']} ops_ok={ledger['ops_ok']}"
            f"/{ledger['ops_planned']}")
        # chaos phase: sustained shard-write latency + a 20ms objective
        os.environ["CFS_SLO_PUT_P99_MS"] = "20"
        chaos.arm("blobnode.put_shard", "delay(0.03)")
        try:
            verdict2, _ = phase(os.path.join(root, "capacity-chaos.jsonl"))
        finally:
            chaos.disarm("blobnode.put_shard")
        out["cap_verdict_chaos"] = verdict2["verdict"]
        out["cap_chaos_flipped"] = sorted(
            {n for names in verdict2["flipped"].values() for n in names})
        log(f"  capacity chaos: verdict={verdict2['verdict']} "
            f"flipped={out['cap_chaos_flipped']}")
    finally:
        if prev_slo is None:
            os.environ.pop("CFS_SLO_PUT_P99_MS", None)
        else:
            os.environ["CFS_SLO_PUT_P99_MS"] = prev_slo
        console.stop()
        srv.stop()
        c.close()
        # the chaos phase salted the default history ring with slow-put
        # snapshots; drop it so later /health consumers start clean
        metrichist.deactivate()
    return out


def bench_rebalance_spread(root: str, duration: float = 6.0,
                           rate: float = 30.0, seed: int = 7,
                           datanodes: int = 5) -> dict:
    """Spread-reduction-under-skew A/B (ROADMAP item 9 leftover): the
    `cfs-capacity --ab-rebalance` scenario as a tracked BENCH number. The
    same seeded zipf-hot plan (s=3.0 under a spike ramp — one scorching
    volume head) runs over two daemon clusters, hot-volume rebalance sweep
    off then on; the number is the per-datanode op-spread CV the sweep
    buys back. Flight recorders stay disarmed (CFS_FLIGHT=0) so the A/B
    measures the data plane, not capture overhead."""
    import argparse

    from chubaofs_tpu.tools.capacity import run_capacity

    args = argparse.Namespace(
        seed=seed, tenants=3, zipf_s=3.0, ramp="spike", duration=duration,
        rate=rate, keys=32, workers=6, interval=0.5, masters=1,
        metanodes=3, datanodes=datanodes, failpoints="",
        daemon_env=["CFS_FLIGHT=0"], cache_mb=0, s3=False,
        rebalance_secs=1.0, autopilot=False, scenario="none")
    out: dict = {}
    res_off = run_capacity(args, rebalance=False,
                           root=os.path.join(root, "off"),
                           out_path=os.path.join(root, "cap-off.jsonl"))
    res_on = run_capacity(args, rebalance=True,
                          root=os.path.join(root, "on"),
                          out_path=os.path.join(root, "cap-on.jsonl"))
    cv_off = res_off["spread"]["cv"]
    cv_on = res_on["spread"]["cv"]
    out["cap_ab_spread_cv_off"] = cv_off
    out["cap_ab_spread_cv_on"] = cv_on
    out["cap_ab_spread_reduction"] = (round((cv_off - cv_on) / cv_off, 3)
                                      if cv_off > 0 else 0.0)
    out["cap_ab_verdict_off"] = res_off["verdict"]
    out["cap_ab_verdict_on"] = res_on["verdict"]
    log(f"  ab-rebalance: spread cv {cv_off} -> {cv_on} "
        f"(reduction {out['cap_ab_spread_reduction']})")
    return out


def bench_cache_zipf(root: str, objects: int = 32, obj_kb: int = 64,
                     gets: int = 240, zipf_s: float = 1.1,
                     wire_ms: float = 2.0, cache_mb: int = 64,
                     seed: int = 7) -> dict:
    """Cache-plane A/B (ISSUE 12): the zipfian GET workload the tiered
    read cache exists for, EC cold path vs frequency-admitted cache tier.

    Two phases over identical payloads and the SAME seeded zipfian access
    sequence (s≈1.1 — the skew regime of arxiv 1709.05365's object traces):
    a BASELINE MiniCluster with no cache (every GET pays the full shard
    gather), and a CACHE-tier cluster (one warm pass, then the measured
    pass). A deterministic `wire_ms` per-shard-read delay stands in for the
    gateway->blobnode RTT, same rationale as bench_repair: in-process reads
    cost ~0, and the cache's win IS skipping N wire round-trips per GET.
    Every GET is crc-verified against its payload — a cache serving stale
    or torn bytes fails the bench, not just the numbers. Reports per-GET
    p50/p99 for both arms, the realized hit ratio, and the p99 speedup."""
    import random
    import zlib

    from chubaofs_tpu import chaos
    from chubaofs_tpu.blobstore.cache import BlobCache
    from chubaofs_tpu.blobstore.cluster import MiniCluster
    from chubaofs_tpu.utils import exporter

    rng = random.Random(seed)
    payloads = [os.urandom(obj_kb * 1024) for _ in range(objects)]
    crcs = [zlib.crc32(p) for p in payloads]
    weights = [1.0 / (r + 1) ** zipf_s for r in range(objects)]
    seq = rng.choices(range(objects), weights=weights, k=gets)
    reg = exporter.registry("cache")

    def phase(label: str, cache) -> dict:
        c = MiniCluster(os.path.join(root, label), n_nodes=6, cache=cache)
        try:
            locs = [c.access.put(p) for p in payloads]
            c.access.get(locs[0])  # jit/warm the GET path outside the window
            if cache is not None:
                for i in seq:  # warm pass: the zipfian head fills the cache
                    c.access.get(locs[i])
            if wire_ms > 0:
                chaos.arm("blobnode.get_shard", f"delay({wire_ms / 1000.0})")
            lat: list[float] = []
            try:
                for i in seq:
                    t0 = time.perf_counter()
                    data = c.access.get(locs[i])
                    lat.append(time.perf_counter() - t0)
                    if zlib.crc32(data) != crcs[i]:
                        raise AssertionError(
                            f"cache bench crc miscompare on object {i}")
            finally:
                if wire_ms > 0:
                    chaos.disarm("blobnode.get_shard")
            lat.sort()
            return {"p50": lat[len(lat) // 2] * 1e3,
                    "p99": lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1e3}
        finally:
            c.close()

    out: dict = {}
    # the baseline arm must really be cache-less: MiniCluster(cache=None)
    # falls back to BlobCache.from_env, so a deployment-exported
    # CFS_CACHE_MB would silently arm the "EC" arm and flatten the A/B
    prev_mb = os.environ.pop("CFS_CACHE_MB", None)
    try:
        base = phase("ec", None)
    finally:
        if prev_mb is not None:
            os.environ["CFS_CACHE_MB"] = prev_mb
    lk0 = reg.counter("lookups").value
    h0 = reg.counter("hits").value
    cache = BlobCache(os.path.join(root, "cachedir"), mem_mb=cache_mb)
    cached = phase("cached", cache)
    lookups = reg.counter("lookups").value - lk0
    hits = reg.counter("hits").value - h0
    # warm pass included: the ratio spans fill + steady state, which is the
    # honest number (a steady-state-only ratio would hide admission churn)
    out["cache_zipf_hit_ratio"] = round(hits / lookups, 3) if lookups else 0.0
    out["cache_zipf_p50_ms_ec"] = round(base["p50"], 3)
    out["cache_zipf_p99_ms_ec"] = round(base["p99"], 3)
    out["cache_zipf_p50_ms_cached"] = round(cached["p50"], 3)
    out["cache_zipf_p99_ms_cached"] = round(cached["p99"], 3)
    out["cache_zipf_speedup_p99"] = round(
        base["p99"] / cached["p99"], 2) if cached["p99"] > 0 else 0.0
    log(f"  cache zipf: hit_ratio={out['cache_zipf_hit_ratio']} "
        f"p99 {out['cache_zipf_p99_ms_ec']}ms (EC) -> "
        f"{out['cache_zipf_p99_ms_cached']}ms (cached), "
        f"{out['cache_zipf_speedup_p99']}x")
    return out


def bench_ranged(root: str, blob_mb: int = 4,
                 range_kbs: tuple = (4, 64, 256, 1024),
                 gets_per: int = 4, cache_mb: int = 16,
                 seed: int = 11) -> dict:
    """Partial-stripe ranged reads (ISSUE 17): bytes-read scales with the
    RANGE, not the blob.

    One blob_mb blob (4 MiB -> a single EC12P4 stripe under the 1-AZ
    policy) served three ways per range size, with the
    cfs_access_read_bytes{kind} counter deltas turned into per-arm ratios:

      * healthy/uncached — in-window sub-shard reads only; the floor is
        shards_read/stripe_bytes < 1/4 for any <=256 KiB range
        (acceptance: the old path gathered the whole stripe every time);
      * degraded — one in-window data shard lost: range-scoped survivor
        gather + row-sliced decode, so shards_read is N x window, never
        N x shard, and decoded bytes are window-sized;
      * cached — block-granular BlobCache: the repeat pass must be all
        hits with ZERO backend shard bytes.

    Every ranged GET (healthy AND degraded) is byte-compared against the
    whole-object slice — a miscompare raises, the same correctness-first
    contract as bench_cache_zipf's crc gate. Tier-1 floors ride
    tests/test_perfbench.py at smoke size."""
    import random

    from chubaofs_tpu.blobstore.cache import BlobCache
    from chubaofs_tpu.blobstore.cluster import MiniCluster
    from chubaofs_tpu.codec.codemode import get_tactic
    from chubaofs_tpu.utils import exporter

    rng = random.Random(seed)
    reg = exporter.registry("access")

    def ctr(kind: str) -> float:
        return reg.counter("read_bytes", {"kind": kind}).value

    data = os.urandom(blob_mb << 20)
    out: dict = {}
    # EC12P4 needs 16 units; 9 nodes x 2 disks covers it. cache=None must
    # really mean cache-less (MiniCluster falls back to from_env otherwise)
    prev_mb = os.environ.pop("CFS_CACHE_MB", None)
    try:
        c = MiniCluster(os.path.join(root, "mc"), n_nodes=9,
                        disks_per_node=2, cache=None)
        try:
            loc = c.access.put(data)
            c.access.get(loc, 0, 4096)  # jit/warm outside the counters
            blob = loc.blobs[0]
            t = get_tactic(loc.code_mode)
            shard_len = t.shard_size(blob.size)
            stripe_bytes = t.N * shard_len  # the old whole-gather cost
            out["ranged_stripe_bytes"] = stripe_bytes
            for rkb in range_kbs:
                rlen = min(rkb * 1024, len(data))
                offs = [rng.randrange(0, len(data) - rlen + 1)
                        for _ in range(gets_per)]
                s0, q0 = ctr("shards_read"), ctr("requested")
                for off in offs:
                    if c.access.get(loc, off, rlen) != data[off:off + rlen]:
                        raise AssertionError(
                            f"healthy ranged miscompare at {off}+{rlen}")
                req = ctr("requested") - q0
                out[f"ranged_amp_{rkb}k"] = round(
                    (ctr("shards_read") - s0) / req, 3) if req else 0.0
                out[f"ranged_stripe_frac_{rkb}k"] = round(
                    (ctr("shards_read") - s0) / gets_per / stripe_bytes, 4)
            # degraded arm: lose a data shard, read windows INSIDE it so
            # every GET exercises the range-scoped decode
            vol = c.cm.get_volume(blob.vid)
            unit = vol.units[1]
            c.nodes[unit.node_id].lose_shard(unit.vuid, blob.bid)
            rlen = min(range_kbs[0] * 1024, shard_len // 2)
            offs = [shard_len + rng.randrange(0, shard_len - rlen)
                    for _ in range(gets_per)]
            s0, q0, d0 = (ctr("shards_read"), ctr("requested"),
                          ctr("decoded"))
            for off in offs:
                if c.access.get(loc, off, rlen) != data[off:off + rlen]:
                    raise AssertionError(
                        f"degraded ranged miscompare at {off}+{rlen}")
            req = ctr("requested") - q0
            out["ranged_amp_degraded"] = round(
                (ctr("shards_read") - s0) / req, 3) if req else 0.0
            out["ranged_decoded_frac_degraded"] = round(
                (ctr("decoded") - d0) / gets_per / stripe_bytes, 4)
        finally:
            c.close()
        # cached arm: block-granular fills — a repeat of the same ranges
        # is all hits, zero backend shard bytes
        cache = BlobCache(os.path.join(root, "cachedir"), mem_mb=cache_mb)
        c2 = MiniCluster(os.path.join(root, "mc2"), n_nodes=9,
                         disks_per_node=2, cache=cache)
        try:
            loc = c2.access.put(data)
            rlen = min(64 * 1024, len(data))
            offs = [rng.randrange(0, len(data) - rlen + 1)
                    for _ in range(gets_per)]
            for off in offs:  # fill pass
                if c2.access.get(loc, off, rlen) != data[off:off + rlen]:
                    raise AssertionError("cached fill-pass miscompare")
            creg = exporter.registry("cache")
            h0 = creg.counter("hits").value
            s0 = ctr("shards_read")
            for off in offs:  # repeat pass
                if c2.access.get(loc, off, rlen) != data[off:off + rlen]:
                    raise AssertionError("cached hit-pass miscompare")
            out["ranged_cached_hits"] = int(creg.counter("hits").value - h0)
            out["ranged_cached_backend_bytes"] = int(ctr("shards_read") - s0)
        finally:
            c2.close()
    finally:
        if prev_mb is not None:
            os.environ["CFS_CACHE_MB"] = prev_mb
    frac_keys = [k for k in out if k.startswith("ranged_stripe_frac_")]
    log(f"  ranged: stripe_frac per range "
        f"{ {k.split('_')[-1]: out[k] for k in frac_keys} } "
        f"degraded_amp={out['ranged_amp_degraded']} "
        f"cached_backend_bytes={out['ranged_cached_backend_bytes']}")
    return out


def bench_events(root: str, n_events: int = 10_000, puts: int = 6,
                 blob_kb: int = 64) -> dict:
    """Events-overhead smoke (ISSUE 13): the plane's two cost contracts.

    (1) Emission is cheap enough to never matter at transition rates:
    emitting `n_events` journal records (ring + rotating JSONL + counter)
    is timed wall-clock; the tier-1 floor keeps it under a generous budget.

    (2) THE HOT PATH EMITS NOTHING: a MiniCluster PUT/GET burst — the
    busiest per-op traffic in the repo — must produce ZERO events, because
    the plane records transitions, never ops. A nonzero count here is a
    correctness failure (someone wired emit() into a data path), so the
    bench raises instead of just reporting."""
    from chubaofs_tpu.blobstore.cluster import MiniCluster
    from chubaofs_tpu.utils import events

    journal = events.configure(logdir=os.path.join(root, "events"))
    t0 = time.perf_counter()
    for i in range(n_events):
        events.emit("bench_tick", detail={"i": i})
    emit_s = time.perf_counter() - t0
    out = {"events_emit_10k_s": round(emit_s * (10_000 / n_events), 4),
           "events_emit_us_avg": round(emit_s / n_events * 1e6, 2)}

    c = MiniCluster(os.path.join(root, "evcluster"), n_nodes=6)
    try:
        payload = os.urandom(blob_kb * 1024)
        warm = c.access.put(payload)  # jit/vuid creation outside the count
        assert c.access.get(warm) == payload
        seq0 = journal.last_seq()
        locs = [c.access.put(payload) for _ in range(puts)]
        for loc in locs:
            assert c.access.get(loc) == payload
        hot = journal.last_seq() - seq0
        out["events_hot_path"] = hot
        if hot:
            evs, _ = journal.query(since=seq0, n=20)
            raise AssertionError(
                f"hot-path PUT/GET burst emitted {hot} events (the plane "
                f"records transitions, never per-op traffic): "
                f"{[e['type'] for e in evs]}")
    finally:
        c.close()
    log(f"  events: emit {out['events_emit_us_avg']}us/event "
        f"({out['events_emit_10k_s']}s / 10k), hot-path events "
        f"{out['events_hot_path']}")
    return out


def bench_flightrec(root: str, puts: int = 8, blob_kb: int = 64) -> dict:
    """Flight-recorder disarm floor (ISSUE 18): zero cost until armed AND
    firing.

    The recorder is threadless and hook-driven — with CFS_FLIGHT unset
    activate_from_env() touches nothing, so a PUT/GET burst must see
    (a) no flight/recorder thread anywhere in the process, (b) zero
    bundles on disk, and (c) the armed-but-quiescent arm of the A/B
    within noise of the disarmed arm: arming only registers an alert
    hook, which costs nothing until an alert transition actually fires.
    Thread or bundle leakage is a correctness failure, so the bench
    raises rather than just reporting a number."""
    import threading

    from chubaofs_tpu.blobstore.cluster import MiniCluster
    from chubaofs_tpu.utils import flightrec

    flight_dir = os.path.join(root, "flight")
    prev = {k: os.environ.pop(k, None)
            for k in ("CFS_FLIGHT", "CFS_FLIGHT_DIR")}
    out: dict = {}
    try:
        flightrec.deactivate()
        c = MiniCluster(os.path.join(root, "frcluster"), n_nodes=6)
        try:
            payload = os.urandom(blob_kb * 1024)
            warm = c.access.put(payload)  # jit/vuid creation off the clock
            assert c.access.get(warm) == payload

            def burst_med_ms() -> float:
                lat = []
                for _ in range(puts):
                    t0 = time.perf_counter()
                    loc = c.access.put(payload)
                    if c.access.get(loc) != payload:
                        raise AssertionError("flightrec burst miscompare")
                    lat.append(time.perf_counter() - t0)
                lat.sort()
                return round(lat[len(lat) // 2] * 1000, 2)

            out["flightrec_disarmed_med_ms"] = burst_med_ms()
            stray = [t.name for t in threading.enumerate()
                     if "flight" in t.name.lower()
                     or "recorder" in t.name.lower()]
            if stray:
                raise AssertionError(
                    f"disarmed flight recorder owns threads {stray} — the "
                    f"design is threadless; nothing may spin when "
                    f"CFS_FLIGHT is unset")
            if os.path.isdir(flight_dir) and os.listdir(flight_dir):
                raise AssertionError(
                    f"disarmed burst wrote bundles: {os.listdir(flight_dir)}")

            # armed-but-quiescent arm: the hook is registered, no alert
            # fires, so the hot path must be indistinguishable
            os.environ["CFS_FLIGHT"] = "1"
            os.environ["CFS_FLIGHT_DIR"] = flight_dir
            flightrec.activate_from_env()
            out["flightrec_armed_med_ms"] = burst_med_ms()
            bundles = (os.listdir(flight_dir)
                       if os.path.isdir(flight_dir) else [])
            out["flightrec_quiescent_bundles"] = len(bundles)
            if bundles:
                raise AssertionError(
                    f"armed-but-quiescent burst wrote bundles {bundles} — "
                    f"capture must only follow an alert transition or an "
                    f"explicit trigger")
        finally:
            c.close()
    finally:
        flightrec.deactivate()
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    log(f"  flightrec: burst med disarmed "
        f"{out['flightrec_disarmed_med_ms']}ms vs armed-quiescent "
        f"{out['flightrec_armed_med_ms']}ms, bundles "
        f"{out['flightrec_quiescent_bundles']}, recorder threads 0")
    return out


def run(root: str, n_files: int = 600, n_clients: int = 4,
        stream_mb: int = 64, metanodes: int = 3, datanodes: int = 3) -> dict:
    from chubaofs_tpu.testing.harness import ProcCluster

    cfg: dict = {}
    log("event plane (emission overhead + hot-path zero-events)...")
    cfg.update(bench_events(os.path.join(root, "eventsbench")))
    log("flight recorder (disarmed zero-overhead floor)...")
    cfg.update(bench_flightrec(os.path.join(root, "flightbench")))
    log("raft commit (group-commit microbench)...")
    cfg.update(bench_raft_commit(os.path.join(root, "raftbench"), n_ops=n_files))
    log("blobstore data-path pipeline (PUT overlap + pooled RPC A/B)...")
    cfg.update(bench_put_pipeline(os.path.join(root, "blobbench"),
                                  n_puts=max(3, min(8, n_files // 100))))
    log("repair plane (windowed rebuild vs serial control)...")
    cfg.update(bench_repair(os.path.join(root, "repairbench")))
    log("capacity harness (SLO gate smoke, clean + chaos)...")
    cfg.update(bench_capacity(os.path.join(root, "capbench")))

    cluster = ProcCluster(root, masters=1, metanodes=metanodes,
                          datanodes=datanodes)
    try:
        cluster.client_master().create_volume("perf", cold=False)
        log("metadata (mdtest analog)...")
        cfg.update(bench_metadata(cluster, "perf", n_files, n_clients))
        log("streaming (fio analog)...")
        cfg.update(bench_stream(cluster, "perf", stream_mb))
        log("small files (tiny.md analog)...")
        cfg.update(bench_smallfile(cluster, "perf", max(100, n_files // 4)))
    finally:
        cluster.close()
    # metadata scale-out proof (ISSUE 15): its OWN 9-metanode ProcCluster
    # (3-replica groups of the 1/3/4-partition phases on disjoint triples)
    # under the raft-persist wire regime; placed right after the main
    # cluster phases — before the core-saturating sweeps below — per the
    # PR-8/12 floor-deflation lesson, so its per-phase A/B (phase-internal
    # like the others) sees an unthrottled host
    log("metadata scale-out (1 -> 4 partitions, load splits)...")
    cfg.update(bench_meta_scale(os.path.join(root, "metascale"),
                                files_per_phase=max(12, n_files // 50)))
    # the sweep saturates every core for a minute and CPU-throttled hosts
    # recover slowly, so it must run AFTER the cluster phases or their
    # throughput floors deflate ~2x; its own A/B is phase-internal, so
    # position costs it nothing. It also scales with n_files like the other
    # phases — smoke-size invocations get a smoke-size sweep.
    log("concurrent-connection sweep (evloop vs threaded A/B)...")
    if n_files >= 300:
        cfg.update(bench_concurrency())
    else:
        cfg.update(bench_concurrency(clients_axis=(64, 256), ops_per_client=6))
    # like bench_concurrency, the cache A/B runs AFTER the cluster phases:
    # its two MiniClusters + tight GET loops leave a throttle-recovering
    # host deflating the md/stream floors ~2x (measured: create_ops_1c
    # 12 -> 5.5 with this phase ahead of them); both its arms are
    # phase-internal, so position costs it nothing
    log("cache plane (zipfian GET A/B, EC vs cache tier)...")
    if n_files >= 300:
        cfg.update(bench_cache_zipf(os.path.join(root, "cachebench")))
    else:  # smoke invocations get a smoke-size zipf sweep
        cfg.update(bench_cache_zipf(os.path.join(root, "cachebench"),
                                    objects=12, obj_kb=32, gets=80))
    # ranged-read A/B rides the same post-ProcCluster slot (floor-deflation
    # lesson): its MiniClusters + 4 MiB puts would throttle-deflate the
    # md/stream floors if it ran ahead of them
    log("ranged reads (byte-window gather, healthy/degraded/cached)...")
    if n_files >= 300:
        cfg.update(bench_ranged(os.path.join(root, "rangedbench")))
    else:  # smoke invocations get a smoke-size range sweep
        cfg.update(bench_ranged(os.path.join(root, "rangedbench"),
                                blob_mb=2, range_kbs=(16, 256), gets_per=2))
    # the gateway phases run AFTER the ProcCluster phases for the same
    # reason as bench_concurrency/bench_cache_zipf (the PR-8/PR-12 floor-
    # deflation lesson): the 1024-conn sweep saturates every core, and a
    # throttle-recovering host would deflate the md/stream floors; both
    # arms of each A/B are phase-internal, so position costs nothing
    log("gateway serving-model sweep (evloop HTTP vs threaded A/B)...")
    if n_files >= 300:
        cfg.update(bench_gateway(os.path.join(root, "gwroot")))
    else:
        cfg.update(bench_gateway(os.path.join(root, "gwroot"),
                                 clients_axis=(32, 128), ops_per_client=6))
    log("gateway QoS fairness (noisy tenant vs victim tenant)...")
    cfg.update(bench_qos_fairness(os.path.join(root, "qosroot")))
    # repair-traffic codes A/B rides the same post-ProcCluster slot (floor-
    # deflation lesson): two more MiniClusters + a node kill each would
    # throttle-deflate the md/stream floors if they ran ahead of them
    log("repair-traffic codes (RG6P6 beta-fetch vs EC12P4 A/B)...")
    if n_files >= 300:
        cfg.update(bench_repair_codes(os.path.join(root, "repaircodes")))
    else:  # smoke invocations get a smoke-size A/B
        cfg.update(bench_repair_codes(os.path.join(root, "repaircodes"),
                                      stripes=4, blob_kb=60))
    # the rebalance-spread A/B boots two more ProcClusters — same post-
    # cluster slot (floor-deflation lesson); smoke invocations get a
    # shorter skew window over the 3-node floor
    log("rebalance spread (cfs-capacity --ab-rebalance A/B)...")
    if n_files >= 300:
        cfg.update(bench_rebalance_spread(os.path.join(root, "rebalab")))
    else:
        cfg.update(bench_rebalance_spread(os.path.join(root, "rebalab"),
                                          duration=3.0, rate=15.0,
                                          datanodes=3))
    _dump_metrics(cfg)
    return cfg


def _dump_metrics(cfg: dict) -> None:
    """Drop a /metrics snapshot next to the BENCH_*.json line so perf rounds
    carry drain-batch/codec-batch counters alongside the throughput numbers
    (the raft microbench ran in THIS process, so its drain histogram is in
    the raft role registry; the key counters also ride the JSON configs)."""
    try:
        from chubaofs_tpu.utils import exporter

        raft_stats = exporter.registry("raft").summary(
            "drain_batch", buckets=exporter.BATCH_BUCKETS).snapshot()
        cfg["raft_drain_batches_total"] = raft_stats["count"]
        cfg["raft_drain_entries_total"] = raft_stats["sum"]
        dump_path = os.environ.get("CFS_METRICS_DUMP", "PERF_metrics.prom")
        exporter.dump(dump_path)
        log(f"metrics snapshot -> {dump_path}")
    except Exception as e:  # never kill the bench line over a snapshot
        log(f"metrics snapshot failed: {type(e).__name__}: {e}")


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="cfs-perfbench")
    p.add_argument("--clients", type=int, default=4)
    p.add_argument("--files", type=int, default=600)
    p.add_argument("--stream-mb", type=int, default=64)
    p.add_argument("--root", default="")
    args = p.parse_args(argv)

    root = args.root or tempfile.mkdtemp(prefix="cfsperf")
    try:
        cfg = run(root, n_files=args.files, n_clients=args.clients,
                  stream_mb=args.stream_mb)
    finally:
        if not args.root:
            shutil.rmtree(root, ignore_errors=True)
    print(json.dumps({
        "metric": "mdtest_create_ops",
        "value": cfg.get(f"create_ops_{args.clients}c",
                         cfg.get("create_ops_1c", 0.0)),
        "unit": "ops/s",
        "configs": cfg,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
