"""cfs-trace — fetch persisted spans, render one trace, attribute its time.

The analysis half of the trace sink (utils/tracesink.py): span records are
flat JSON lines; this tool reassembles the hop tree (parent span ids link
in-process children; the carrier's span id links cross-process hops), renders
it as a WATERFALL or text FLAMEGRAPH, and runs the CRITICAL-PATH analyzer —
projecting every named stage (encode host/device ms, raft commit wait, shard
fan-out, pool checkout) onto the root span's wall time so "what fraction of
this PUT was encode vs raft vs wire?" has a printable answer. `--top`
aggregates per-hop p50/p99 over the recent-trace window instead.

Span sources, in precedence order: `--addr` targets' `/traces` side-doors
(repeatable — point it at every daemon of a localcluster, or once at a
console, whose `/api/trace` collector already fans out), or `--dir`, a trace
sink directory read straight from its rotor files.

Usage:
    cfs-trace <trace-id> --addr 127.0.0.1:9500 --addr 127.0.0.1:9600
    cfs-trace <trace-id> --dir /tmp/cfs-traces-1234 --flame
    cfs-trace --top --addr 127.0.0.1:9500
    cfs-trace --prof 5 --addr 127.0.0.1:9500   # stack-based profile

`--prof N` is the stack-sampled companion to the span-based flamegraph: it
asks the first --addr's `/debug/prof?seconds=N` side-door (utils/profiler)
for an on-demand capture and prints the collapsed-stack lines — the same
`path;to;frame <count>` format `--flame` emits for spans, so both feed the
same downstream renderers (flamegraph.pl, speedscope).

Also a library: build_tree / critical_path / waterfall / flamegraph /
aggregate are what the acceptance tests drive.
"""

from __future__ import annotations

import json
import os
import sys

# the one sweep-line interval union + overlap-ratio math both overlap
# consumers share (the scheduler's repair overlap ratio rides the same
# functions, so the dashboard metric and this CLI can never drift)
from chubaofs_tpu.blobstore.trace import intersect_len as _intersect
from chubaofs_tpu.blobstore.trace import overlap_ratio as _overlap_ratio
from chubaofs_tpu.blobstore.trace import union_len as _union

BAR_WIDTH = 40


# -- tree assembly -------------------------------------------------------------


def build_tree(records: list[dict]) -> tuple[list[dict], dict[str, list[dict]]]:
    """Flat span records -> (roots, children-by-parent-id). Spans whose
    parent never made it into the record set (dropped by sampling on one
    daemon, rotated out) surface as roots — a partial tree still renders."""
    by_id = {r["span_id"]: r for r in records if r.get("span_id")}
    children: dict[str, list[dict]] = {}
    roots: list[dict] = []
    for rec in sorted(records, key=lambda r: r.get("start", 0.0)):
        pid = rec.get("parent_span_id")
        if pid and pid != rec.get("span_id") and pid in by_id:
            children.setdefault(pid, []).append(rec)
        else:
            roots.append(rec)
    return roots, children


def _span_interval(rec: dict) -> tuple[float, float]:
    s = float(rec.get("start", 0.0))
    return s, s + rec.get("dur_us", 0) / 1e6


def _pick_root(records: list[dict], root_op: str | None) -> dict | None:
    roots, _ = build_tree(records)
    if root_op is not None:
        named = [r for r in records if r.get("op") == root_op]
        if named:
            return max(named, key=lambda r: r.get("dur_us", 0))
        return None
    if not roots:
        return None
    return max(roots, key=lambda r: r.get("dur_us", 0))


# -- critical path -------------------------------------------------------------


def critical_path(records: list[dict], root_op: str | None = None) -> dict:
    """Attribute the root span's wall time to named stages.

    Contributions, all projected (clipped) onto the root's wall interval:
      * every stage of the root and its descendants, under the stage name;
      * every DESCENDANT span's own interval, under `span:<op>` — so a hop
        that recorded no finer stages still attributes as itself.
    Coverage is the wall-clock UNION of all contributions over the root
    duration — overlap (a pipelined window, a shared codec batch) never
    counts twice, which is what makes "≥95% attributed" a real claim.
    Per-stage milliseconds are each name's own union (parallel shards of one
    stage don't double-count; different names may overlap by design)."""
    root = _pick_root(records, root_op)
    if root is None:
        return {"error": "no spans" if not records else
                f"no span with op {root_op!r}"}
    t0, t1 = _span_interval(root)
    _, children = build_tree(records)

    per_name: dict[str, list[tuple[float, float]]] = {}

    def clip(s: float, e: float) -> tuple[float, float] | None:
        s, e = max(s, t0), min(e, t1)
        return (s, e) if e > s else None

    def add_stages(rec: dict):
        base = float(rec.get("start", 0.0))
        for name, off_us, dur_us in rec.get("stages", ()):
            iv = clip(base + off_us / 1e6, base + (off_us + dur_us) / 1e6)
            if iv:
                per_name.setdefault(str(name), []).append(iv)

    seen: set[str] = set()

    def visit(rec: dict, is_root: bool):
        sid = rec.get("span_id")
        if sid in seen:
            return  # defensive: a cyclic/duplicated record set must not hang
        seen.add(sid)
        add_stages(rec)
        if not is_root:
            iv = clip(*_span_interval(rec))
            if iv:
                per_name.setdefault(f"span:{rec.get('op', '?')}", []).append(iv)
        for ch in children.get(sid, ()):
            visit(ch, False)

    visit(root, True)

    wall = t1 - t0
    stages = sorted(
        ({"stage": name, "ms": round(_union(ivs) * 1e3, 3),
          "calls": len(ivs)} for name, ivs in per_name.items()),
        key=lambda s: -s["ms"])
    covered = _union([iv for ivs in per_name.values() for iv in ivs])
    return {
        "trace_id": root.get("trace_id"),
        "root_op": root.get("op"),
        "root_span_id": root.get("span_id"),
        "wall_ms": round(wall * 1e3, 3),
        "attributed_ms": round(covered * 1e3, 3),
        "unattributed_ms": round(max(0.0, wall - covered) * 1e3, 3),
        "coverage": round(covered / wall, 4) if wall > 0 else 0.0,
        "spans": len(records),
        "stages": stages,
    }


def stage_overlap(records: list[dict], a: str, b: str) -> dict:
    """How much two stage families of a trace ran CONCURRENTLY: collect the
    intervals of every stage whose name matches `a` (exact or prefix — pass
    "codec." to cover codec.host+codec.device) and likewise `b`, then
    measure the intersection of the two interval unions. `ratio` is that
    intersection over the SMALLER union — 1.0 means the lesser stage was
    entirely hidden behind the greater (perfect pipelining), 0.0 means they
    ran back-to-back. The repair plane's download/decode overlap proof."""

    def intervals(prefix: str) -> list[tuple[float, float]]:
        out = []
        for rec in records:
            base = float(rec.get("start", 0.0))
            for name, off_us, dur_us in rec.get("stages", ()):
                if name == prefix or str(name).startswith(prefix):
                    s = base + off_us / 1e6
                    out.append((s, s + dur_us / 1e6))
        return out

    ia, ib = intervals(a), intervals(b)
    ratio = _overlap_ratio(ia, ib)
    return {
        "a": a, "b": b,
        "a_ms": round(_union(ia) * 1e3, 3), "b_ms": round(_union(ib) * 1e3, 3),
        "overlap_ms": round(_intersect(ia, ib) * 1e3, 3),
        "ratio": 0.0 if ratio is None else round(ratio, 4),
    }


# -- renderers -----------------------------------------------------------------


def _bar(t0: float, t1: float, s: float, e: float, ch: str = "#") -> str:
    """A BAR_WIDTH-wide timeline bar for [s, e) inside [t0, t1)."""
    if t1 <= t0:
        return " " * BAR_WIDTH
    lo = int((max(s, t0) - t0) / (t1 - t0) * BAR_WIDTH)
    hi = int((min(e, t1) - t0) / (t1 - t0) * BAR_WIDTH + 0.9999)
    lo = min(max(lo, 0), BAR_WIDTH)
    hi = min(max(hi, lo + 1), BAR_WIDTH)
    return " " * lo + ch * (hi - lo) + " " * (BAR_WIDTH - hi)


def waterfall(records: list[dict], stages: bool = True) -> str:
    """One trace as an offset-aligned text waterfall: spans as '#' bars in
    tree order (indent = depth), their named stages as '-' sub-bars."""
    if not records:
        return "(no spans)"
    roots, children = build_tree(records)
    t0 = min(_span_interval(r)[0] for r in records)
    t1 = max(_span_interval(r)[1] for r in records)
    head = records[0]
    lines = [f"trace {head.get('trace_id', '?')}  "
             f"wall {(t1 - t0) * 1e3:.2f}ms  spans {len(records)}"]
    label_w = max(min(36, max(len(r.get("op", "?")) + 2 for r in records)), 12)
    seen: set[str] = set()

    def visit(rec: dict, depth: int):
        sid = rec.get("span_id")
        if sid in seen:
            return
        seen.add(sid)
        s, e = _span_interval(rec)
        label = ("  " * depth + rec.get("op", "?"))[:label_w]
        lines.append(f"{label.ljust(label_w)} |{_bar(t0, t1, s, e)}| "
                     f"{(e - s) * 1e3:9.2f}ms")
        if stages:
            base = float(rec.get("start", 0.0))
            for name, off_us, dur_us in rec.get("stages", ()):
                ss = base + off_us / 1e6
                lbl = ("  " * depth + "· " + str(name))[:label_w]
                lines.append(
                    f"{lbl.ljust(label_w)} |"
                    f"{_bar(t0, t1, ss, ss + dur_us / 1e6, '-')}| "
                    f"{dur_us / 1e3:9.2f}ms")
        for ch in children.get(sid, ()):
            visit(ch, depth + 1)

    for root in roots:
        visit(root, 0)
    return "\n".join(lines)


def _stage_tree(rec: dict) -> tuple[list[tuple[str, float, float]],
                                    dict[int, list[int]], list[int]]:
    """A span's stages as a containment hierarchy: stage B whose interval
    sits inside a strictly-larger stage A is A's child (encode contains
    codec.host/codec.device). Returns (intervals, children-by-idx, tops)."""
    base = float(rec.get("start", 0.0))
    ivs = [(str(n), base + off / 1e6, base + (off + dur) / 1e6)
           for n, off, dur in rec.get("stages", ())]
    kids: dict[int, list[int]] = {}
    tops: list[int] = []
    for i, (_n, s, e) in enumerate(ivs):
        best = None
        for j, (_nj, sj, ej) in enumerate(ivs):
            if j == i or not (sj <= s and e <= ej) or (ej - sj) <= (e - s):
                continue  # strict containment only: equal intervals stay
                # siblings (no parent cycles)
            if best is None or (ej - sj) < (ivs[best][2] - ivs[best][1]):
                best = j
        if best is None:
            tops.append(i)
        else:
            kids.setdefault(best, []).append(i)
    return ivs, kids, tops


def flamegraph(records: list[dict]) -> str:
    """Collapsed-stack text flamegraph: one `path;to;frame <ms>` line per
    span and per stage (the format flamegraph.pl and speedscope ingest),
    self-time style. Stages nest by interval containment (a 10ms encode
    wait containing 7ms of codec.device emits 3/7, not 10/7), and a span
    frame excludes its child spans and top-level stages — summing a frame
    with its prefixed children reproduces the span's width, never more."""
    roots, children = build_tree(records)
    out: list[str] = []
    seen: set[str] = set()

    def emit_stage(ivs, kids, idx: int, path: str):
        name, s, e = ivs[idx]
        sub = kids.get(idx, ())
        covered = _union([(max(ivs[j][1], s), min(ivs[j][2], e))
                          for j in sub])
        out.append(f"{path};{name} {max(0.0, (e - s) - covered) * 1e3:.3f}")
        for j in sub:
            emit_stage(ivs, kids, j, f"{path};{name}")

    def visit(rec: dict, path: str):
        sid = rec.get("span_id")
        if sid in seen:
            return
        seen.add(sid)
        frame = f"{path};{rec.get('op', '?')}" if path else rec.get("op", "?")
        kid_spans = children.get(sid, ())
        s, e = _span_interval(rec)
        ivs, kids, tops = _stage_tree(rec)
        sub_ivs = [_span_interval(c) for c in kid_spans]
        sub_ivs += [(ivs[i][1], ivs[i][2]) for i in tops]
        covered = _union([(max(cs, s), min(ce, e))
                          for cs, ce in sub_ivs if min(ce, e) > max(cs, s)])
        self_ms = max(0.0, rec.get("dur_us", 0) / 1e3 - covered * 1e3)
        out.append(f"{frame} {self_ms:.3f}")
        for i in tops:
            emit_stage(ivs, kids, i, frame)
        for ch in kid_spans:
            visit(ch, frame)

    for root in roots:
        visit(root, "")
    return "\n".join(out)


def aggregate(records: list[dict]) -> dict[str, dict]:
    """Per-hop latency aggregation over many traces' records: op ->
    {count, p50_ms, p99_ms, max_ms} (nearest-rank percentiles)."""
    groups: dict[str, list[float]] = {}
    for rec in records:
        groups.setdefault(rec.get("op", "?"), []).append(
            rec.get("dur_us", 0) / 1e3)

    def pct(vals: list[float], q: float) -> float:
        return vals[min(len(vals) - 1, int(q * (len(vals) - 1) + 0.5))]

    out = {}
    for op, vals in groups.items():
        vals.sort()
        out[op] = {"count": len(vals), "p50_ms": round(pct(vals, 0.50), 3),
                   "p99_ms": round(pct(vals, 0.99), 3),
                   "max_ms": round(vals[-1], 3)}
    return out


def render_top(per_op: dict[str, dict]) -> str:
    if not per_op:
        return "(no recent spans)"
    w = max(len(op) for op in per_op)
    lines = [f"{'HOP'.ljust(w)}  {'COUNT':>7}  {'P50MS':>10}  "
             f"{'P99MS':>10}  {'MAXMS':>10}"]
    for op, st in sorted(per_op.items(), key=lambda kv: -kv[1]["p99_ms"]):
        lines.append(f"{op.ljust(w)}  {st['count']:>7}  {st['p50_ms']:>10g}  "
                     f"{st['p99_ms']:>10g}  {st['max_ms']:>10g}")
    return "\n".join(lines)


def render_report(rep: dict) -> str:
    if rep.get("error"):
        return f"error: {rep['error']}"
    lines = [f"critical path of {rep['root_op']}  trace {rep['trace_id']}",
             f"  wall {rep['wall_ms']}ms  attributed {rep['attributed_ms']}ms "
             f"({rep['coverage'] * 100:.1f}%)  "
             f"unattributed {rep['unattributed_ms']}ms  "
             f"spans {rep['spans']}"]
    for st in rep["stages"]:
        pct = st["ms"] / rep["wall_ms"] * 100 if rep["wall_ms"] else 0.0
        lines.append(f"  {st['stage'].ljust(24)} {st['ms']:>10.3f}ms "
                     f"{pct:>6.1f}%  x{st['calls']}")
    return "\n".join(lines)


# -- span sources --------------------------------------------------------------


def read_dir(logdir: str, trace_id: str | None = None) -> list[dict]:
    """Span records straight from a sink directory's rotor files
    (traces.log, traces.log.1, ...), oldest first."""
    def _order(name: str) -> int:
        # oldest first: highest rotation suffix, the live traces.log last
        if name == "traces.log":
            return 0
        try:
            return -int(name.rsplit(".", 1)[-1])
        except ValueError:
            return 0

    names = sorted((n for n in os.listdir(logdir)
                    if n == "traces.log" or n.startswith("traces.log.")),
                   key=_order)
    out: dict[str, dict] = {}
    for name in names:
        try:
            with open(os.path.join(logdir, name), encoding="utf-8") as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if not rec.get("span_id"):
                        continue
                    if trace_id is None or rec.get("trace_id") == trace_id:
                        out[rec["span_id"]] = rec
        except OSError:
            continue
    return sorted(out.values(), key=lambda r: r.get("start", 0.0))


def fetch(addrs: list[str], trace_id: str | None = None,
          n: int = 200) -> list[dict]:
    """Span records from every target, deduped by span id. For a trace-id
    fetch BOTH endpoint shapes are queried per target — the console's
    `/api/trace` collector (which fans out to every daemon) AND the local
    `/traces` side-door — because a console mounts both, and its local sink
    is usually empty: stopping at the first 200 would miss the rollup."""
    import urllib.parse

    from chubaofs_tpu.tools.cfsstat import scrape

    out: dict[str, dict] = {}
    tid_q = urllib.parse.quote(trace_id or "")  # hostile/typo'd ids stay inert
    for addr in addrs:
        paths = ([f"/api/trace?id={tid_q}", f"/traces?id={tid_q}"]
                 if trace_id else [f"/traces/recent?n={n}"])
        errors = []
        for path in paths:
            try:
                body = json.loads(scrape(addr, path, timeout=5))
            except Exception as e:
                errors.append(f"{addr}{path}: {e}")
                continue
            for rec in body.get("spans", ()):
                if rec.get("span_id"):
                    out.setdefault(rec["span_id"], rec)
        if len(errors) == len(paths):  # NO shape answered: say so
            print(f"warning: {'; '.join(errors)}", file=sys.stderr)
    return sorted(out.values(), key=lambda r: r.get("start", 0.0))


# -- CLI -----------------------------------------------------------------------


def main(argv=None, out=None) -> int:
    import argparse

    out = out or sys.stdout
    p = argparse.ArgumentParser(
        prog="cfs-trace",
        description="render + analyze persisted traces (sink side-doors)")
    p.add_argument("trace_id", nargs="?", default=None)
    p.add_argument("--addr", action="append", default=[],
                   help="daemon or console address (repeatable)")
    p.add_argument("--dir", default=None,
                   help="read a local trace-sink directory instead of HTTP")
    p.add_argument("--bundle", default=None,
                   help="read spans from a collected flight-recorder "
                        "bundle dir instead of live side-doors "
                        "(postmortem mode)")
    p.add_argument("--top", action="store_true",
                   help="per-hop p50/p99 over recent traces")
    p.add_argument("--prof", type=float, default=None, metavar="SECONDS",
                   help="fetch a SECONDS-long stack-sampled profile from "
                        "the first --addr's /debug/prof side-door and print "
                        "its collapsed stacks (flamegraph.pl format)")
    p.add_argument("--n", type=int, default=200,
                   help="recent spans to aggregate with --top")
    p.add_argument("--flame", action="store_true",
                   help="collapsed-stack flamegraph instead of a waterfall")
    p.add_argument("--no-report", action="store_true",
                   help="skip the critical-path report")
    p.add_argument("--root-op", default=None,
                   help="analyze this op's span as the critical-path root")
    p.add_argument("--overlap", default=None, metavar="A,B",
                   help="also report how much stage families A and B ran "
                        "concurrently (prefix match; e.g. "
                        "'download,codec.' proves repair download/decode "
                        "overlap)")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)

    if args.prof is not None:
        if not args.addr:
            p.error("--prof needs --addr (the daemon to profile)")
        from chubaofs_tpu.tools.cfsstat import scrape

        path = f"/debug/prof?seconds={args.prof:g}" \
            + ("&json=1" if args.json else "")
        try:
            body = scrape(args.addr[0], path,
                          timeout=max(30.0, args.prof * 2 + 10.0))
        except Exception as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        print(body.rstrip("\n"), file=out)
        return 0

    if not args.top and not args.trace_id:
        p.error("a trace id is required unless --top")
    if not args.addr and not args.dir and not args.bundle:
        env_dir = os.environ.get("CFS_TRACE_DIR")
        if env_dir:
            args.dir = env_dir
        else:
            p.error("give --addr (repeatable), --dir, or --bundle "
                    "(or set CFS_TRACE_DIR)")

    if args.bundle:
        from chubaofs_tpu.tools.cfsdoctor import read_bundle

        try:
            bundle = read_bundle(args.bundle)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        seen: dict[str, dict] = {}
        for payload in bundle["targets"].values():
            for rec in (payload.get("traces") or {}).get("records", []):
                if args.trace_id and rec.get("trace_id") != args.trace_id:
                    continue
                if rec.get("span_id"):
                    seen.setdefault(rec["span_id"], rec)
        records = sorted(seen.values(), key=lambda r: r.get("start", 0.0))
        if args.top:
            records = records[-args.n:]
    elif args.dir:
        records = read_dir(args.dir, args.trace_id)
        if args.top:
            records = records[-args.n:]
    else:
        records = fetch(args.addr, args.trace_id, n=args.n)

    if args.top:
        per_op = aggregate(records)
        print(json.dumps(per_op, indent=2) if args.json
              else render_top(per_op), file=out)
        return 0

    if not records:
        print(f"no spans for trace {args.trace_id}", file=sys.stderr)
        return 1
    rep = critical_path(records, root_op=args.root_op)
    overlap = None
    if args.overlap:
        a, _, b = args.overlap.partition(",")
        overlap = stage_overlap(records, a.strip(), b.strip())
    if args.json:
        blob = {"spans": records, "report": rep}
        if overlap is not None:
            blob["overlap"] = overlap
        print(json.dumps(blob, indent=2), file=out)
        return 0
    print(flamegraph(records) if args.flame else waterfall(records), file=out)
    if not args.no_report:
        print("", file=out)
        print(render_report(rep), file=out)
    if overlap is not None:
        print(f"overlap {overlap['a']} ∩ {overlap['b']}: "
              f"{overlap['overlap_ms']}ms of "
              f"min({overlap['a_ms']}, {overlap['b_ms']})ms "
              f"(ratio {overlap['ratio']})", file=out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
