"""cfs-top — live cluster dashboard over the console health/metrics rollup.

The `top(1)` of the observability plane: poll the console's `/api/health`
(SLO verdicts, unreachable daemons reported as failing) and `/api/metrics`
(every target's exposition in one scrape), diff adjacent polls, and render
one row per daemon target:

    TARGET          SLO       PUT/S  GET/S  PUT99MS  CONNS  BP/S  LAG99  CODEC/B  REPAIRQ

  * PUT/S / GET/S — access op completions per second (histogram _count
    deltas between frames);
  * PUT99MS — window p99 of the PUT latency histogram (bucket deltas, the
    SAME math utils/slo.py uses, so the dashboard and /health cannot
    disagree);
  * CONNS / BP/S / LAG99 — evloop live connections, read-pause events per
    second, and the window p99 of `cfs_evloop_loop_lag_ms` (the shard-
    saturation signal);
  * CODEC/B — mean codec batch occupancy over the window (jobs per drained
    device batch — "is the gateway feeding the chip?");
  * CACHE% — cache-plane hit ratio over the window (`cfs_cache_hits` /
    `cfs_cache_lookups` deltas; '-' when the target serves no cache);
  * THR% — QoS throttled-request share over the window
    (`cfs_objectnode_throttled` / `cfs_objectnode_requests` deltas; '-'
    when the target saw no shaped requests);
  * META — metadata plane: partitions hosted (`cfs_metanode_partitions`
    gauge) and the hottest single partition's ops/s over the window (max
    per-pid delta of `cfs_metanode_partition_ops{pid}` / dt — the
    load-split signal), rendered `parts/hot`; '-' when the target hosts
    no meta partitions;
  * REPAIRQ — repair tasks outstanding (`cfs_scheduler_tasks` gauge sum);
  * REPB/SH — repair-traffic cost over the window: bytes downloaded per
    repaired shard (`cfs_scheduler_repair_bytes_downloaded` /
    `cfs_scheduler_repaired_shards` deltas, restart-clamped; hedged bytes
    excluded by the scheduler's accounting); '-' when the window repaired
    nothing — regenerating modes (RG6P6) show this well under the RS
    k-shard cost;
  * UP — seconds since the daemon's `cfs_boot_time_seconds` boot stamp. A
    boot stamp that MOVED between frames is a confirmed restart — the row
    tags `(restart)` from that cross-check, not just from negative-delta
    clamping (which a counter reset can also cause);
  * ALERTS — alert instances currently firing (`cfs_alerts_firing`);
  * AUTO — autopilot plane (ISSUE 20): `actions/budget` — real actuator
    runs in the window (`cfs_autopilot_decisions{decision="executed"}`
    delta, restart-clamped) over the remaining hourly action budget
    (`cfs_autopilot_budget_remaining` gauge); '-' when this target's
    controller is disarmed (`cfs_autopilot_armed` absent or 0).

`--once` renders a single frame (two scrapes `--interval` apart) for CI and
scripts; without it the terminal refreshes in place until ^C. `--addr`
(repeatable) skips the console and polls daemons' `/health` + `/metrics`
directly. `--json` emits the frame as JSON instead of the table.

`--frames N --out path` is the archival mode (the capacity-report consumer,
cfs-capacity rides the same record shape): each frame is APPENDED to `path`
as one JSON line stamped with a run-relative monotonic `t`, and the process
exits after N frames — `cfs-top --json` alone only prints to a terminal.
"""

from __future__ import annotations

import json
import sys
import time

from chubaofs_tpu.utils.metrichist import (
    family_sum, hist_delta, hist_quantile, parse_key)
from chubaofs_tpu.utils.slo import FAILING, RANK

COLUMNS = ("TARGET", "SLO", "UP", "PUT/S", "GET/S", "PUT99MS", "CONNS",
           "BP/S", "LAG99", "CODEC/B", "CACHE%", "RDAMP", "THR%", "META",
           "REPAIRQ", "REPB/SH", "ALERTS", "AUTO")


# -- scraping ------------------------------------------------------------------


def split_rollup(text: str) -> dict[str, dict[str, float] | None]:
    """The console /api/metrics rollup -> {target: metrics-or-None}. The
    rollup tags each section `# == target ADDR ==` and an unreachable one
    `# == target ADDR UNREACHABLE: ... ==` — those map to None so the
    dashboard renders the corpse instead of dropping it."""
    from chubaofs_tpu.tools.cfsstat import parse_metrics

    out: dict[str, dict[str, float] | None] = {}
    cur: str | None = None
    body: list[str] = []

    def flush():
        if cur is not None and out.get(cur, "new") == "new":
            out[cur] = parse_metrics("\n".join(body))

    for line in text.splitlines():
        if line.startswith("# == target "):
            flush()
            rest = line[len("# == target "):].rstrip("= ").strip()
            body = []
            if " UNREACHABLE" in rest:
                cur = rest.split(" UNREACHABLE", 1)[0].strip()
                out[cur] = None
                cur = None  # nothing to parse for this section
            else:
                cur = rest
        else:
            body.append(line)
    flush()
    return out


def fetch_frame(console: str | None, addrs: list[str],
                timeout: float = 5.0) -> dict:
    """One poll: health verdicts + per-target metrics, stamped monotonic."""
    from chubaofs_tpu.tools.cfsstat import scrape

    health: dict[str, dict] = {}
    metrics: dict[str, dict | None] = {}
    errors: list[str] = []
    if console:
        try:
            roll = json.loads(scrape(console, "/api/health", timeout=timeout))
            for t in roll.get("targets", ()):
                health[t.get("target", "?")] = t
        except Exception as e:
            errors.append(f"{console}/api/health: {e}")
        try:
            metrics = split_rollup(
                scrape(console, "/api/metrics", timeout=timeout))
        except Exception as e:
            errors.append(f"{console}/api/metrics: {e}")
    else:
        for addr in addrs:
            try:
                health[addr] = {"target": addr, **json.loads(
                    scrape(addr, "/health", timeout=timeout))}
            except Exception:
                health[addr] = {"target": addr, "status": FAILING,
                                "reasons": ["unreachable"]}
            try:
                from chubaofs_tpu.tools.cfsstat import parse_metrics

                metrics[addr] = parse_metrics(
                    scrape(addr, "/metrics", timeout=timeout))
            except Exception:
                metrics[addr] = None
    return {"mono": time.monotonic(), "health": health, "metrics": metrics,
            "errors": errors}


# -- per-target row math -------------------------------------------------------


def _rate(prev: dict, cur: dict, family: str, dt: float) -> float:
    d = family_sum(cur, family) - family_sum(prev, family)
    if d < 0:
        # restart contract (same as metrichist.rates / hist_delta): the
        # counter restarted from zero, so the post-restart total is the
        # window's delta — a busy restarted daemon must not render idle
        d = family_sum(cur, family)
    return d / dt if dt > 0 else 0.0


def _p99(prev: dict, cur: dict, family: str) -> float | None:
    buckets, count = hist_delta(prev, cur, family)
    return hist_quantile(buckets, count, 0.99)


def _label_delta(prev: dict, cur: dict, family: str, label: str,
                 value: str) -> float:
    """Restart-clamped window delta of the series of `family` whose
    `label` equals `value` — family_sum would fold the labeled series
    together, and ratio/selector cells need one slice apart."""
    tot = 0.0
    for k, v in cur.items():
        name, labels = parse_key(k)
        if name != family or labels.get(label) != value:
            continue
        d = v - prev.get(k, 0.0)
        if d < 0:
            d = v  # counter restarted: the post-restart total is the window
        tot += d
    return tot


def _kind_delta(prev: dict, cur: dict, family: str, kind: str) -> float:
    return _label_delta(prev, cur, family, "kind", kind)


def _hottest_pid_rate(prev: dict, cur: dict, dt: float) -> float:
    """Max per-partition window rate of cfs_metanode_partition_ops{pid} —
    per-SERIES deltas (not family_sum: the hottest partition is the split
    signal, and summing would hide the skew), restart-clamped like every
    flow cell."""
    if dt <= 0:
        return 0.0
    best = 0.0
    for k, v in cur.items():
        if parse_key(k)[0] != "cfs_metanode_partition_ops":
            continue
        d = v - prev.get(k, 0.0)
        if d < 0:
            d = v  # counter restarted: the post-restart total is the window
        best = max(best, d / dt)
    return round(best, 2)


def compute_row(target: str, prev: dict | None, cur: dict | None,
                dt: float, health: dict | None) -> dict:
    """One dashboard row from two metric snapshots of one target."""
    h = health or {}
    row: dict = {"target": target, "slo": h.get("status", "?"),
                 "reasons": h.get("reasons", [])}
    if cur is None:
        # no metrics this frame — but the HEALTH verdict stands on its own:
        # only a target that answered neither surface renders as the
        # failing corpse. A transient /api/metrics hiccup on an otherwise
        # ok cluster must not flip every row to 'failing (unreachable)'.
        if not h or "unreachable" in (h.get("reasons") or ()):
            row["slo"] = FAILING
            row["unreachable"] = True
        return row
    # state gauges read from the current frame alone
    parts = family_sum(cur, "cfs_metanode_partitions")
    row["meta_parts"] = int(parts) if parts > 0 else None
    row["conns"] = int(family_sum(cur, "cfs_evloop_conns"))
    row["repair_q"] = int(family_sum(cur, "cfs_scheduler_tasks"))
    row["alerts"] = int(family_sum(cur, "cfs_alerts_firing"))
    # autopilot plane (ISSUE 20): armed flag + remaining budget are state
    # gauges (current frame); the actions count is a window delta below
    row["auto_armed"] = family_sum(cur, "cfs_autopilot_armed") > 0
    row["auto_budget"] = int(
        family_sum(cur, "cfs_autopilot_budget_remaining")) \
        if row["auto_armed"] else None
    # UP from the boot stamp (wall protocol: the daemon exports ITS wall
    # boot time, we subtract OUR wall clock — same contract as heartbeats)
    boot = family_sum(cur, "cfs_boot_time_seconds")
    now_wall = time.time()
    row["up_s"] = int(now_wall - boot) if boot > 0 else None
    if prev:
        prev_boot = family_sum(prev, "cfs_boot_time_seconds")
        if boot > 0 and prev_boot > 0 and boot > prev_boot + 0.5:
            # the boot stamp MOVED between frames: a restart happened, no
            # counter inference needed — the cross-check the (restart) tag
            # rides instead of relying only on negative-delta clamping
            row["restart"] = True
    if not prev:
        # no prior frame for this target (first poll, or its last scrape
        # failed): a delta against zero would render LIFETIME totals as a
        # window rate/p99 — a bogus spike; flow cells stay '-' until the
        # next poll, same no-data discipline as the SLO evaluator
        return row
    row["put_s"] = round(_rate(prev, cur, "cfs_access_put_count", dt), 2)
    row["get_s"] = round(_rate(prev, cur, "cfs_access_get_count", dt), 2)
    p99 = _p99(prev, cur, "cfs_access_put")
    row["put99_ms"] = None if p99 is None else round(p99 * 1e3, 2)
    row["bp_s"] = round(_rate(prev, cur, "cfs_evloop_backpressure", dt), 2)
    lag = _p99(prev, cur, "cfs_evloop_loop_lag_ms")
    row["lag99_ms"] = None if lag is None else round(lag, 2)  # already ms
    # mean jobs per drained codec batch over the window
    jobs = family_sum(cur, "cfs_codec_batch_jobs_sum") \
        - family_sum(prev, "cfs_codec_batch_jobs_sum")
    batches = family_sum(cur, "cfs_codec_batch_jobs_count") \
        - family_sum(prev, "cfs_codec_batch_jobs_count")
    row["codec_occ"] = round(jobs / batches, 2) if batches > 0 else None
    # cache-plane hit ratio over the window (ISSUE 12); '-' when this
    # target ran no cached lookups. _rate with dt=1 gives the restart-
    # clamped window DELTA — the same contract every flow cell rides.
    lookups = _rate(prev, cur, "cfs_cache_lookups", 1.0)
    hits = _rate(prev, cur, "cfs_cache_hits", 1.0)
    row["cache_pct"] = round(100.0 * hits / lookups, 1) if lookups > 0 else None
    # read amplification over the window (ISSUE 17): backend shard bytes
    # fetched per byte the callers asked for — ~1.0 means ranged reads move
    # window bytes only, stripe/range means whole-stripe gathers; '-' on
    # targets that served no reads this window
    req_b = _kind_delta(prev, cur, "cfs_access_read_bytes", "requested")
    shard_b = _kind_delta(prev, cur, "cfs_access_read_bytes", "shards_read")
    row["read_amp"] = round(shard_b / req_b, 2) if req_b > 0 else None
    # QoS throttled-request share over the window (ISSUE 14): what fraction
    # of this gateway's requests the per-tenant plane turned away; '-' on
    # targets that saw no shaped requests (plane unarmed, or not a gateway)
    reqs = _rate(prev, cur, "cfs_objectnode_requests", 1.0)
    thr = _rate(prev, cur, "cfs_objectnode_throttled", 1.0)
    row["thr_pct"] = round(100.0 * thr / reqs, 1) if reqs > 0 else None
    # metadata plane (ISSUE 15): the hottest single partition's window
    # ops/s (the load-split signal); partitions-hosted is a state gauge
    # above, so a metanode's first frame still renders `N/-`
    row["meta_hot_ops"] = _hottest_pid_rate(prev, cur, dt) \
        if row.get("meta_parts") else None
    # repair traffic (ISSUE 19): window bytes downloaded per repaired
    # shard; '-' when nothing was repaired this window. _rate with dt=1
    # gives the restart-clamped window delta, same as the cache cell.
    rep_sh = _rate(prev, cur, "cfs_scheduler_repaired_shards", 1.0)
    rep_b = _rate(prev, cur, "cfs_scheduler_repair_bytes_downloaded", 1.0)
    row["repair_bps"] = round(rep_b / rep_sh, 1) if rep_sh > 0 else None
    # autopilot actions this window: only REAL actuator runs count
    # (considered/damped/refused decisions are bookkeeping, not actions);
    # restart-clamped like every flow cell
    row["auto_acts"] = int(_label_delta(
        prev, cur, "cfs_autopilot_decisions", "decision", "executed")) \
        if row.get("auto_armed") else None
    return row


def compute_rows(prev_frame: dict, cur_frame: dict) -> list[dict]:
    dt = cur_frame["mono"] - prev_frame["mono"]
    targets = list(dict.fromkeys(
        list(cur_frame["metrics"]) + list(cur_frame["health"])))
    return [compute_row(t, (prev_frame["metrics"] or {}).get(t),
                        cur_frame["metrics"].get(t), dt,
                        cur_frame["health"].get(t))
            for t in targets]


# -- rendering -----------------------------------------------------------------


def _cell(v) -> str:
    if v is None:
        return "-"
    return f"{v:g}" if isinstance(v, float) else str(v)


def _meta_cell(r: dict) -> str:
    """META column: `parts/hot-ops` (e.g. `4/123.5`); '-' off-metanodes.
    hot-ops is '-' on the first frame (no prior to delta against)."""
    if r.get("meta_parts") is None:
        return "-"
    return f"{r['meta_parts']}/{_cell(r.get('meta_hot_ops'))}"


def _auto_cell(r: dict) -> str:
    """AUTO column: `actions/budget` (window actuator runs over remaining
    hourly budget, e.g. `1/5`); '-' when the controller is disarmed.
    actions is '-' on the first frame (no prior to delta against)."""
    if not r.get("auto_armed"):
        return "-"
    return f"{_cell(r.get('auto_acts'))}/{_cell(r.get('auto_budget'))}"


def render(rows: list[dict], errors: list[str] = ()) -> str:
    if not rows:
        return "(no targets)" + ("".join(f"\n! {e}" for e in errors))
    worst = max((r["slo"] for r in rows),
                key=lambda s: RANK.get(s, RANK[FAILING]))
    cells = [[r["target"], r["slo"]
              + (" (unreachable)" if r.get("unreachable") else "")
              + (" (restart)" if r.get("restart") else ""),
              _cell(r.get("up_s")),
              _cell(r.get("put_s")), _cell(r.get("get_s")),
              _cell(r.get("put99_ms")), _cell(r.get("conns")),
              _cell(r.get("bp_s")), _cell(r.get("lag99_ms")),
              _cell(r.get("codec_occ")), _cell(r.get("cache_pct")),
              _cell(r.get("read_amp")),
              _cell(r.get("thr_pct")), _meta_cell(r),
              _cell(r.get("repair_q")), _cell(r.get("repair_bps")),
              _cell(r.get("alerts")), _auto_cell(r)]
             for r in rows]
    widths = [max(len(COLUMNS[i]), max(len(row[i]) for row in cells))
              for i in range(len(COLUMNS))]
    lines = [f"cluster: {worst}   targets: {len(rows)}   "
             f"{time.strftime('%H:%M:%S')}"]
    lines.append("  ".join(c.ljust(w) for c, w in zip(COLUMNS, widths)))
    for row in cells:
        lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths)))
    for r in rows:
        for reason in r.get("reasons", ()):
            lines.append(f"! {r['target']}: {reason}")
    for e in errors:
        lines.append(f"! {e}")
    return "\n".join(lines)


# -- archival ------------------------------------------------------------------


def frame_record(t0: float, frame: dict, rows: list[dict]) -> dict:
    """One JSONL archive record: run-relative monotonic stamp + the computed
    rows. Monotonic (not wall) so frame spacing survives NTP steps; run-
    relative so two archives of the same scenario diff cleanly."""
    return {"t": round(frame["mono"] - t0, 3), "rows": rows,
            "errors": list(frame.get("errors", ()))}


# -- CLI -----------------------------------------------------------------------


def main(argv=None, out=None) -> int:
    import argparse

    out = out or sys.stdout
    p = argparse.ArgumentParser(
        prog="cfs-top",
        description="live cluster dashboard over the console rollup")
    p.add_argument("--console", default=None,
                   help="console address (uses /api/health + /api/metrics)")
    p.add_argument("--addr", action="append", default=[],
                   help="poll a daemon directly (repeatable; skips console)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between polls (and the rate window)")
    p.add_argument("--once", action="store_true",
                   help="render one frame and exit (CI mode)")
    p.add_argument("--json", action="store_true")
    p.add_argument("--frames", type=int, default=0,
                   help="archive N frames then exit (requires --out)")
    p.add_argument("--out", default="",
                   help="append frames as JSONL records to this path")
    args = p.parse_args(argv)
    if not args.console and not args.addr:
        p.error("give --console or --addr")
    if bool(args.frames) != bool(args.out):
        p.error("--frames and --out go together")
    if args.out:
        # archival is its own mode: a stray --once would truncate the
        # archive to 1 frame with exit 0, and --json would be silently
        # ignored — both are operator mistakes worth failing loudly on
        if args.frames < 1:
            p.error("--frames must be >= 1")
        if args.once or args.json:
            p.error("--out is the archival mode; drop --once/--json")

    interval = max(0.1, args.interval)
    prev = fetch_frame(args.console, args.addr)
    t0 = prev["mono"]
    archived = 0
    try:
        while True:
            time.sleep(interval)
            cur = fetch_frame(args.console, args.addr)
            rows = compute_rows(prev, cur)
            if args.out:
                with open(args.out, "a", encoding="utf-8") as f:
                    f.write(json.dumps(frame_record(t0, cur, rows)) + "\n")
                archived += 1
                if archived >= args.frames:
                    return 0
            elif args.json:
                print(json.dumps({"rows": rows, "errors": cur["errors"]},
                                 indent=2), file=out)
            else:
                if not args.once and out is sys.stdout:
                    out.write("\x1b[2J\x1b[H")  # clear + home: live refresh
                print(render(rows, cur["errors"]), file=out)
            if args.once:
                return 0
            prev = cur
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
