"""cfs-stat — scrape a daemon's /metrics twice and diff the snapshots.

The `iostat`-style ops companion to the observability plane: point it at any
daemon role's /metrics (master API, metanode/datanode statsListen side-door,
blobstore gateway, console rollup), take two snapshots `--interval` seconds
apart, and print per-metric deltas + rates — so a perf investigation reads
raft drain-batch and codec-batch counters moving in real time instead of
eyeballing two raw exposition dumps.

Usage:
    python -m chubaofs_tpu.tools.cfsstat --addr 127.0.0.1:17010 \
        [--interval 5] [--path /metrics] [--filter raft] [--json]

Also a library: parse_metrics / diff_metrics are the exposition-format
consumers the conformance tests drive.
"""

from __future__ import annotations

import json
import sys
import time


def parse_metrics(text: str) -> dict[str, float]:
    """Prometheus text exposition -> {'name{labels}': value}. Comment/TYPE
    lines are skipped; malformed lines raise (the conformance contract —
    a bad render must fail loudly here, not scrape as garbage)."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        key, _, val = line.rpartition(" ")
        if not key:
            raise ValueError(f"unparseable metric line: {line!r}")
        out[key] = float(val)
    return out


def parse_types(text: str) -> dict[str, str]:
    """# TYPE declarations -> {metric_family: kind}."""
    out: dict[str, str] = {}
    for line in text.splitlines():
        parts = line.strip().split()
        if len(parts) == 4 and parts[0] == "#" and parts[1] == "TYPE":
            out[parts[2]] = parts[3]
    return out


def diff_metrics(before: dict[str, float], after: dict[str, float],
                 interval_s: float, types: dict[str, str] | None = None) -> list[dict]:
    """Per-metric rows: value now, delta across the window, rate/s.
    Metrics new in `after` diff against 0; vanished ones are dropped.

    With `types` (parse_types of the scrape), a NEGATIVE delta on a
    monotonic series — a counter or a histogram's _bucket/_sum/_count —
    means the daemon restarted between the two scrapes: the series restarted
    from zero, so the post-restart value IS the window's delta. Such rows
    clamp to that and carry restart=True (rendered as a `(restart)` tag)
    instead of printing a bogus negative rate. Gauges go down legitimately
    and are never clamped; without `types` nothing is (the legacy
    two-plain-dicts library call)."""
    from chubaofs_tpu.utils.metrichist import is_monotonic

    rows = []
    for key in sorted(after):
        b = before.get(key, 0.0)
        a = after[key]
        delta = a - b
        restart = False
        if delta < 0 and types is not None and is_monotonic(key, types):
            delta = a
            restart = True
        rows.append({
            "metric": key,
            "value": a,
            "delta": round(delta, 6),
            "rate": round(delta / interval_s, 6) if interval_s > 0 else 0.0,
            "restart": restart,
        })
    return rows


REPAIR_METRIC_MARKS = ("cfs_scheduler_", "scrub", "repair")


def is_repair_metric(name: str) -> bool:
    """The repair-plane rollup filter (--repair): scheduler task gauges by
    kind/state, lease expiries, stale reports, probe failures, scrub
    progress, and the bytes-downloaded / shards-repaired counters the
    bytes-per-repaired-shard claim is computed from."""
    return any(mark in name for mark in REPAIR_METRIC_MARKS)


READ_METRIC_MARKS = ("cfs_access_read_bytes", "cfs_access_get",
                     "cfs_access_read_fail", "cfs_cache_", "cfs_bcache_",
                     "shard_get")


def is_read_metric(name: str) -> bool:
    """The read-path rollup filter (--reads, ISSUE 17): the read-amp byte
    ledger (requested/shards_read/decoded), GET latency/error families,
    cache-plane and block-store counters, and blobnode shard-get traffic."""
    return any(mark in name for mark in READ_METRIC_MARKS)


def read_amp_summary(before: dict[str, float],
                     after: dict[str, float]) -> dict | None:
    """Window read-amp rollup from two snapshots: shards_read/requested
    (and the decoded share), restart-clamped per series. None when the
    window served no reads — callers print nothing rather than 0.0."""
    def kind_delta(kind: str) -> float:
        tot = 0.0
        for key, a in after.items():
            if (not key.startswith("cfs_access_read_bytes")
                    or f'kind="{kind}"' not in key):
                continue
            d = a - before.get(key, 0.0)
            tot += a if d < 0 else d
        return tot

    req = kind_delta("requested")
    if req <= 0:
        return None
    shards = kind_delta("shards_read")
    decoded = kind_delta("decoded")
    return {"requested_bytes": req, "shards_read_bytes": shards,
            "decoded_bytes": decoded,
            "read_amp": round(shards / req, 3)}


def repair_summary(before: dict[str, float],
                   after: dict[str, float]) -> dict | None:
    """Window repair-traffic rollup from two snapshots (--repair, ISSUE 19):
    bytes-per-repaired-shard derived from the downloaded-bytes and
    repaired-shards counter deltas, restart-clamped per series, plus the
    hedged-byte and beta-path shares and per-mode helper bytes. None when
    the window repaired nothing — callers print `-` (idle) rather than a
    bogus 0.0 ratio."""
    def fam_of(key: str) -> str:
        # strip labels, then any bundle target prefix ("node1:cfs_...")
        return key.split("{", 1)[0].rsplit(":", 1)[-1]

    def fam_delta(fam: str) -> float:
        tot = 0.0
        for key, a in after.items():
            if fam_of(key) != fam:
                continue
            d = a - before.get(key, 0.0)
            tot += a if d < 0 else d
        return tot

    shards = fam_delta("cfs_scheduler_repaired_shards")
    if shards <= 0:
        return None
    dl = fam_delta("cfs_scheduler_repair_bytes_downloaded")
    helper: dict[str, float] = {}
    for key, a in after.items():
        if (fam_of(key) == "cfs_scheduler_repair_helper_bytes"
                and 'mode="' in key):
            m = key.split('mode="', 1)[1].split('"', 1)[0]
            d = a - before.get(key, 0.0)
            helper[m] = helper.get(m, 0.0) + (a if d < 0 else d)
    return {
        "repaired_shards": shards,
        "downloaded_bytes": dl,
        "hedged_bytes": fam_delta("cfs_scheduler_repair_bytes_hedged"),
        "beta_shards": fam_delta("cfs_scheduler_repair_beta_shards"),
        "helper_bytes": {k: v for k, v in helper.items() if v > 0},
        "bytes_per_repaired_shard": round(dl / shards, 1),
    }


def bundle_window(bundle: dict) -> tuple[dict, dict, dict, float]:
    """Offline (--bundle) window: the first vs last frozen metric-history
    snapshot across a bundle's targets, series keys prefixed with the
    target (a cfs-doctor read_bundle result — one daemon's flat bundle or
    a console incident dir). Returns (before, after, types, interval_s)."""
    before: dict[str, float] = {}
    after: dict[str, float] = {}
    types: dict[str, str] = {}
    interval = 0.0
    for tname, payload in bundle["targets"].items():
        snaps = (payload.get("metrics") or {}).get("snapshots", [])
        if not snaps:
            continue
        first, last = snaps[0], snaps[-1]
        interval = max(interval, (last.get("mono") or last.get("ts", 0.0))
                       - (first.get("mono") or first.get("ts", 0.0)))
        for k, v in first.get("metrics", {}).items():
            before[f"{tname}:{k}"] = v
        for k, v in last.get("metrics", {}).items():
            after[f"{tname}:{k}"] = v
        for fam, kind in last.get("types", {}).items():
            types[f"{tname}:{fam}"] = kind
    if not after:
        raise ValueError("bundle froze no metric snapshots")
    return before, after, types, interval


def scrape(addr: str, path: str = "/metrics", timeout: float = 10.0) -> str:
    from chubaofs_tpu.rpc.pool import NullPool

    # one-shot scrape: the NullPool keeps the no-direct-HTTPConnection
    # invariant (obslint rule 3) without parking a socket per target
    pool = NullPool(timeout=timeout)
    conn, _ = pool.checkout(addr)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read().decode("utf-8", "replace")
        if resp.status != 200:
            raise OSError(f"{addr}{path}: HTTP {resp.status}: {body[:200]}")
        return body
    finally:
        pool.checkin(addr, conn)


def main(argv=None, out=None) -> int:
    import argparse

    out = out or sys.stdout
    p = argparse.ArgumentParser(
        prog="cfs-stat", description="scrape + diff two /metrics snapshots")
    p.add_argument("--addr", help="daemon host:port")
    p.add_argument("--bundle", default="",
                   help="diff the first vs last frozen metric-history "
                        "snapshot of a collected flight-recorder bundle "
                        "instead of scraping live (postmortem mode)")
    p.add_argument("--path", default="/metrics")
    p.add_argument("--interval", type=float, default=5.0,
                   help="seconds between the two snapshots")
    p.add_argument("--filter", default="",
                   help="only metrics whose name contains this substring")
    p.add_argument("--repair", action="store_true",
                   help="repair-plane rollup: only scheduler/scrub/repair "
                        "metrics (task counts by kind/state, lease "
                        "expiries, probe failures, scrub progress, repair "
                        "traffic), statics included")
    p.add_argument("--reads", action="store_true",
                   help="read-path rollup: read-amp byte ledger, GET "
                        "latency/errors, cache plane, blobnode shard-get "
                        "traffic — plus a computed read_amp summary line")
    p.add_argument("--all", action="store_true",
                   help="include zero-delta metrics")
    p.add_argument("--slowops", action="store_true",
                   help="also fetch the daemon's recent slow-op audit "
                        "entries (/slowops; /api/slowops on a console) and "
                        "print them next to the diff")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    if not args.addr and not args.bundle:
        p.error("give --addr or --bundle")

    bundle = None
    if args.bundle:
        from chubaofs_tpu.tools.cfsdoctor import read_bundle

        try:
            bundle = read_bundle(args.bundle)
            before, after, types, elapsed = bundle_window(bundle)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
    else:
        try:
            t0 = time.monotonic()
            before = parse_metrics(scrape(args.addr, args.path))
            time.sleep(max(0.0, args.interval))
            text = scrape(args.addr, args.path)
            after = parse_metrics(text)
            types = parse_types(text)
            elapsed = time.monotonic() - t0
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1

    slowops: list[dict] = []
    if args.slowops and bundle is not None:
        for tname, payload in bundle["targets"].items():
            slowops.extend({**rec, "target": tname} for rec in
                           (payload.get("slowops") or {}).get("slowops", []))
        slowops.sort(key=lambda r: r.get("ts", ""))
    elif args.slowops:
        # /api/slowops first: on a console that's the cluster-wide rollup
        # (its local /slowops is an empty log), on a master the same local
        # data; plain daemons 404 it and fall back to /slowops
        slow_err = None
        for path in ("/api/slowops", "/slowops"):
            try:
                slowops = json.loads(scrape(args.addr, path)).get("slowops", [])
                slow_err = None
                break
            except Exception as e:
                slow_err = f"{args.addr}{path}: {e}"
        if slow_err is not None:  # neither shape answered — not a quiet
            print(f"warning: slowops unavailable: {slow_err}",  # cluster
                  file=sys.stderr)

    rows = diff_metrics(before, after, elapsed, types=types)
    if args.filter:
        rows = [r for r in rows if args.filter in r["metric"]]
    if args.repair:
        # a repair inventory is mostly GAUGES sitting still (tasks by
        # kind/state): statics are the point, so --repair implies --all
        rows = [r for r in rows if is_repair_metric(r["metric"])]
    elif args.reads:
        rows = [r for r in rows if is_read_metric(r["metric"])]
        if not args.all:
            rows = [r for r in rows if r["delta"] != 0]
    elif not args.all:
        rows = [r for r in rows if r["delta"] != 0]
    amp = read_amp_summary(before, after) if args.reads else None
    rep = repair_summary(before, after) if args.repair else None
    if args.json:
        blob = {"interval_s": round(elapsed, 3), "rows": rows}
        if amp is not None:
            blob["read_amp"] = amp
        if args.repair:
            blob["repair"] = rep
        if args.slowops:
            blob["slowops"] = slowops
        print(json.dumps(blob, indent=2), file=out)
        return 0
    if not rows:
        print(f"(no metric moved in {elapsed:.1f}s; --all shows statics)",
              file=out)
    else:
        w = max(len(r["metric"]) for r in rows)
        print(f"{'METRIC'.ljust(w)}  {'VALUE':>14}  {'DELTA':>12}  {'RATE/S':>12}",
              file=out)
        for r in rows:
            tag = "  (restart)" if r.get("restart") else ""
            print(f"{r['metric'].ljust(w)}  {r['value']:>14g}  "
                  f"{r['delta']:>12g}  {r['rate']:>12g}{tag}", file=out)
    if args.slowops:
        shown = slowops[-20:]
        note = (f"showing last {len(shown)} of {len(slowops)}"
                if len(slowops) > len(shown) else f"{len(slowops)} recent")
        print(f"\nSLOW OPS ({note})", file=out)
        for rec in shown:
            print(f"  {rec.get('ts', '-')}  {rec.get('module', '?')}."
                  f"{rec.get('op', '?')}  {rec.get('latency_ms', 0):.1f}ms"
                  f"  trace={rec.get('trace_id', '-')}"
                  + (f"  err={rec['err']}" if rec.get("err") else ""),
                  file=out)
            if rec.get("track"):
                print(f"    track: {rec['track']}", file=out)
    if amp is not None:
        print(f"\nread_amp: {amp['read_amp']:g}  "
              f"(shards_read {amp['shards_read_bytes']:g}B / "
              f"requested {amp['requested_bytes']:g}B; "
              f"decoded {amp['decoded_bytes']:g}B)", file=out)
    if args.repair:
        if rep is None:
            print("\nbytes/repaired-shard: -  (no shards repaired this "
                  "window)", file=out)
        else:
            helper = "".join(
                f", helper[{m}] {v:g}B"
                for m, v in sorted(rep["helper_bytes"].items()))
            print(f"\nbytes/repaired-shard: "
                  f"{rep['bytes_per_repaired_shard']:g}  "
                  f"(downloaded {rep['downloaded_bytes']:g}B / "
                  f"{rep['repaired_shards']:g} shards; "
                  f"hedged {rep['hedged_bytes']:g}B, "
                  f"beta {rep['beta_shards']:g}{helper})", file=out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
