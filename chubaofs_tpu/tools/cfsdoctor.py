"""cfs-doctor — collect, inspect, and diff incident flight-recorder bundles.

The postmortem face of the incident plane (ISSUE 18): the per-daemon
flight recorder (`utils/flightrec.py`) freezes evidence when an alert
fires; the console `/api/incident` fans out and assembles one cross-daemon
incident directory; this tool is how an operator drives both by hand and
reads the result after the cluster is gone.

    cfs-doctor collect --console 127.0.0.1:8500          # via the console
    cfs-doctor collect --addr H:P --addr H:P             # direct fan-out
    cfs-doctor list [--dir DIR]                          # what's on disk
    cfs-doctor inspect BUNDLE_DIR [--json]               # incident summary
    cfs-doctor diff OLD_DIR NEW_DIR                      # what moved

`inspect` renders cause→evidence: the firing alert, its burn-rate window,
the top-moving metric families over the frozen snapshots, the slowest
spans, the in-window slowops (trace ids joined against the event
timeline), the autopilot actions taken (or damped/refused) inside the
window, and the hot profile thread buckets.

Also a library: `read_bundle` / `assemble_incident` / `correlate` /
`summarize` are shared with the console collector and the `--bundle`
offline mode of cfs-events / cfs-stat / cfs-trace.
"""

from __future__ import annotations

import json
import os
import sys
import time

from chubaofs_tpu.utils import flightrec

SLOWOP_TS_FMT = "%Y-%m-%d %H:%M:%S"
WINDOW_LOOKBACK_S = 120.0   # evidence window opens this far before the
                            # alert's since-stamp (the burn window that
                            # fired it plus margin for the slow tail)


# -- bundle loading ------------------------------------------------------------


def read_bundle(path: str) -> dict:
    """Load a bundle directory — either one daemon's flat bundle (the
    flightrec section files) or a console-assembled incident directory
    (incident.json + one subdir per target). Returns
    {path, kind, incident, targets: {name: payload}}."""
    path = os.path.abspath(path)
    inc = flightrec._read_json(os.path.join(path, "incident.json"))
    if inc is not None:
        targets: dict[str, dict] = {}
        for name in sorted(os.listdir(path)):
            sub = os.path.join(path, name)
            if os.path.isdir(sub):
                targets[name] = flightrec.bundle_payload(sub)
        return {"path": path, "kind": "incident", "incident": inc,
                "targets": targets}
    payload = flightrec.bundle_payload(path)
    if not payload:
        raise ValueError(f"{path}: not a bundle (no incident.json, "
                         f"no section files)")
    return {"path": path, "kind": "daemon", "incident": None,
            "targets": {"local": payload}}


# -- collection (shared with console /api/incident) ----------------------------


def assemble_incident(rows: list[tuple[str, dict | None]], out_root: str,
                      fingerprint: str = "", trigger: str = "manual",
                      alert: dict | None = None) -> dict:
    """Materialize one cross-daemon incident directory from per-target
    `/debug/bundle?collect=1` responses. Unreachable targets (None or a
    non-bundle response) are LISTED, never fatal — a partial incident
    still explains most of the failure. Returns the incident record
    (also written as incident.json)."""
    ts = time.time()
    name = f"{flightrec._slug(fingerprint or trigger)}-{int(ts)}"
    inc_dir = os.path.join(out_root, name)
    collected, missed = [], []
    targets: dict[str, dict] = {}
    for addr, out in rows:
        payload = (out or {}).get("payload")
        if not isinstance(payload, dict):
            missed.append(addr)
            continue
        tslug = flightrec._slug(addr)
        flightrec.write_payload(os.path.join(inc_dir, tslug), payload)
        targets[tslug] = payload
        collected.append(addr)
        if alert is None and payload.get("alert"):
            alert = payload["alert"]
    incident = {"dir": inc_dir, "name": name, "ts": ts,
                "fingerprint": fingerprint, "trigger": trigger,
                "alert": alert or None,
                "targets": collected, "unreachable": missed,
                "correlation": correlate(targets, alert, ts)}
    os.makedirs(inc_dir, exist_ok=True)
    flightrec._write_json(os.path.join(inc_dir, "incident.json"), incident)
    return incident


def _parse_slowop_ts(s: str) -> float | None:
    try:
        return time.mktime(time.strptime(s, SLOWOP_TS_FMT))
    except (ValueError, TypeError, OverflowError):
        return None


def correlate(targets: dict[str, dict], alert: dict | None,
              capture_ts: float) -> dict:
    """Cause→evidence join: the firing alert's rule and window, the
    in-window slowops' trace ids, and the timeline events those trace ids
    (or the window) implicate."""
    since = (alert or {}).get("since") or capture_ts
    start, end = since - WINDOW_LOOKBACK_S, capture_ts + 1.0
    slowops, trace_ids = [], []
    for tname, payload in targets.items():
        for rec in (payload.get("slowops") or {}).get("slowops", []):
            ts = _parse_slowop_ts(rec.get("ts", ""))
            if ts is None or not start <= ts <= end:
                continue
            slowops.append({"target": tname, **rec})
            tid = rec.get("trace_id")
            if tid and tid not in trace_ids:
                trace_ids.append(tid)
    slowops.sort(key=lambda r: -float(r.get("latency_ms", 0.0)))
    events = []
    for tname, payload in targets.items():
        for ev in (payload.get("events") or {}).get("events", []):
            ts = ev.get("ts", 0.0)
            in_window = isinstance(ts, (int, float)) and start <= ts <= end
            if in_window or ev.get("trace_id") in trace_ids:
                events.append({"target": tname, **ev})
    events.sort(key=lambda e: e.get("ts", 0.0))
    return {"rule": (alert or {}).get("name", ""),
            "window": {"start": start, "end": end},
            "slowops": slowops[:50], "trace_ids": trace_ids[:50],
            "events": events[-200:]}


# -- summary (the inspect view) ------------------------------------------------


def burn_families(snaps: list[dict], top: int = 10) -> list[dict]:
    """Top-moving monotonic families across the frozen snapshot window —
    first vs last, restart-clamped, histogram children collapsed onto
    their family (via _count; _bucket/_sum would double-count)."""
    from chubaofs_tpu.utils.metrichist import family_of, is_monotonic

    if len(snaps) < 2:
        return []
    first, last = snaps[0], snaps[-1]
    span = max(1e-9, (last.get("mono") or last.get("ts", 0.0))
               - (first.get("mono") or first.get("ts", 0.0)))
    types = last.get("types", {})
    fams: dict[str, float] = {}
    for key, a in last.get("metrics", {}).items():
        if not is_monotonic(key, types):
            continue
        fam, sfx = family_of(key)
        if sfx in ("_bucket", "_sum"):
            continue
        d = a - first.get("metrics", {}).get(key, 0.0)
        if d < 0:
            d = a  # restart contract: post-restart total IS the delta
        fams[fam] = fams.get(fam, 0.0) + d
    rows = [{"family": f, "delta": round(d, 3),
             "rate": round(d / span, 3)}
            for f, d in fams.items() if d > 0]
    rows.sort(key=lambda r: -r["rate"])
    return rows[:top]


def summarize(bundle: dict) -> dict:
    """One incident summary from a read_bundle() result: alert → window →
    top burn-rate families → slowest spans → in-window slowops → hot
    profile buckets."""
    targets = bundle["targets"]
    inc = bundle.get("incident") or {}
    alert = inc.get("alert")
    capture_ts = inc.get("ts", 0.0)
    if alert is None:
        for payload in targets.values():
            if payload.get("alert"):
                alert = payload["alert"]
                break
    if not capture_ts:
        for payload in targets.values():
            capture_ts = max(capture_ts,
                             (payload.get("meta") or {}).get("ts", 0.0))
    corr = inc.get("correlation") or correlate(targets, alert,
                                               capture_ts or time.time())

    burns = []
    for tname, payload in targets.items():
        snaps = (payload.get("metrics") or {}).get("snapshots", [])
        for row in burn_families(snaps, top=5):
            burns.append({"target": tname, **row})
    burns.sort(key=lambda r: -r["rate"])

    spans = []
    for tname, payload in targets.items():
        for rec in (payload.get("traces") or {}).get("records", []):
            spans.append({"target": tname, "op": rec.get("op", "?"),
                          "dur_us": rec.get("dur_us", 0),
                          "trace_id": rec.get("trace_id", "")})
    spans.sort(key=lambda s: -float(s.get("dur_us") or 0))

    # the autopilot decision log frozen per target (ISSUE 20): name every
    # action the controller took (or damped/refused) inside the evidence
    # window, keyed by the causal alert fingerprint
    w = corr.get("window") or {}
    w_start, w_end = w.get("start", 0.0), w.get("end", float("inf"))
    autopilot = []
    for tname, payload in targets.items():
        ap = payload.get("autopilot") or {}
        for rec in ap.get("decisions") or []:
            ts = rec.get("ts", 0.0)
            if isinstance(ts, (int, float)) and w_start <= ts <= w_end:
                autopilot.append({"target": tname, **rec})
    autopilot.sort(key=lambda r: r.get("ts", 0.0))

    profile: dict[str, int] = {}
    coverage = []
    for payload in targets.values():
        prof = payload.get("profile") or {}
        for bucket, n in (prof.get("threads") or {}).items():
            profile[bucket] = profile.get(bucket, 0) + int(n)
        if prof.get("samples"):
            coverage.append(prof.get("coverage", 0.0))
    hot = sorted(profile.items(), key=lambda kv: -kv[1])[:10]

    return {"path": bundle["path"], "kind": bundle["kind"],
            "targets": sorted(targets),
            "unreachable": inc.get("unreachable", []),
            "fingerprint": inc.get("fingerprint")
            or next((p.get("meta", {}).get("fingerprint", "")
                     for p in targets.values()), ""),
            "alert": alert, "window": corr.get("window", {}),
            "burn_families": burns[:10],
            "slow_spans": spans[:10],
            "slowops": corr.get("slowops", [])[:10],
            "trace_ids": corr.get("trace_ids", []),
            "autopilot_actions": autopilot[-20:],
            "profile_hot": [{"bucket": b, "samples": n} for b, n in hot],
            "profile_coverage": round(sum(coverage) / len(coverage), 4)
            if coverage else 0.0}


def _fmt_ts(ts: float) -> str:
    if not ts:
        return "-"
    return time.strftime(SLOWOP_TS_FMT, time.localtime(ts))


def render_summary(s: dict, out) -> None:
    print(f"INCIDENT {s['path']}", file=out)
    print(f"  kind={s['kind']}  targets={len(s['targets'])}"
          + (f"  unreachable={','.join(s['unreachable'])}"
             if s["unreachable"] else ""), file=out)
    a = s.get("alert")
    if a:
        print(f"  alert: {a.get('name', '?')} [{a.get('severity', '?')}] "
              f"value={a.get('value')}  since={_fmt_ts(a.get('since', 0))}"
              f"  {a.get('description', '')}", file=out)
    elif s.get("fingerprint"):
        print(f"  fingerprint: {s['fingerprint']}", file=out)
    w = s.get("window") or {}
    if w:
        print(f"  window: {_fmt_ts(w.get('start', 0))} .. "
              f"{_fmt_ts(w.get('end', 0))}", file=out)
    if s["burn_families"]:
        print("  top burn-rate families:", file=out)
        for r in s["burn_families"]:
            print(f"    {r['family']:<44} {r['rate']:>10g}/s  "
                  f"(+{r['delta']:g} @{r['target']})", file=out)
    if s["slow_spans"]:
        print("  slowest spans:", file=out)
        for r in s["slow_spans"]:
            print(f"    {r['op']:<32} {r['dur_us'] / 1000.0:>9.1f}ms  "
                  f"trace={r['trace_id']}  @{r['target']}", file=out)
    if s["slowops"]:
        print(f"  in-window slowops ({len(s['trace_ids'])} traces):",
              file=out)
        for r in s["slowops"]:
            print(f"    {r.get('ts', '-')}  {r.get('module', '?')}."
                  f"{r.get('op', '?')}  {float(r.get('latency_ms', 0)):.1f}ms"
                  f"  trace={r.get('trace_id', '-')}  @{r['target']}",
                  file=out)
    if s.get("autopilot_actions"):
        print("  autopilot actions in window:", file=out)
        for r in s["autopilot_actions"]:
            print(f"    {_fmt_ts(r.get('ts', 0))}  "
                  f"{r.get('decision', '?'):<12} "
                  f"{r.get('actuator') or '-':<24} "
                  f"{r.get('fingerprint', '')}  @{r['target']}", file=out)
    if s["profile_hot"]:
        print(f"  hot profile buckets "
              f"(coverage {s['profile_coverage']:.0%}):", file=out)
        for r in s["profile_hot"]:
            print(f"    {r['bucket']:<32} {r['samples']:>8} samples",
                  file=out)


# -- diff ----------------------------------------------------------------------


def _merged_last_metrics(bundle: dict) -> tuple[dict, dict, float]:
    """(metrics, types, ts) from every target's newest frozen snapshot —
    keys prefixed with the target so two roles can't collide."""
    metrics: dict[str, float] = {}
    types: dict[str, str] = {}
    ts = 0.0
    for tname, payload in bundle["targets"].items():
        snaps = (payload.get("metrics") or {}).get("snapshots", [])
        if not snaps:
            continue
        last = snaps[-1]
        ts = max(ts, last.get("ts", 0.0))
        for k, v in last.get("metrics", {}).items():
            metrics[f"{tname}:{k}"] = v
        for fam, kind in last.get("types", {}).items():
            types[f"{tname}:{fam}"] = kind
    return metrics, types, ts


def diff_bundles(old: dict, new: dict) -> dict:
    """What moved between two bundles: metric deltas (restart-clamped via
    the shared cfs-stat differ), alert-state changes, event-count deltas
    by type."""
    from chubaofs_tpu.tools.cfsstat import diff_metrics

    m0, _t0, ts0 = _merged_last_metrics(old)
    m1, t1, ts1 = _merged_last_metrics(new)
    interval = max(0.0, ts1 - ts0)
    rows = [r for r in diff_metrics(m0, m1, interval, types=t1)
            if r["delta"] != 0]
    rows.sort(key=lambda r: -abs(r["delta"]))

    def alert_names(b):
        out = set()
        a = (b.get("incident") or {}).get("alert")
        if a:
            out.add(a.get("name", "?"))
        for p in b["targets"].values():
            if p.get("alert"):
                out.add(p["alert"].get("name", "?"))
        return out

    def event_counts(b):
        out: dict[str, int] = {}
        for p in b["targets"].values():
            for ev in (p.get("events") or {}).get("events", []):
                t = ev.get("type", "?")
                out[t] = out.get(t, 0) + 1
        return out

    e0, e1 = event_counts(old), event_counts(new)
    return {"interval_s": round(interval, 1),
            "metrics": rows[:40],
            "alerts": {"old": sorted(alert_names(old)),
                       "new": sorted(alert_names(new))},
            "events": {t: e1.get(t, 0) - e0.get(t, 0)
                       for t in sorted(set(e0) | set(e1))
                       if e1.get(t, 0) != e0.get(t, 0)}}


# -- CLI -----------------------------------------------------------------------


def _get_json(addr: str, path: str, timeout: float = 30.0) -> dict:
    from chubaofs_tpu.tools.cfsstat import scrape

    return json.loads(scrape(addr, path, timeout=timeout))


def _cmd_collect(args, out) -> int:
    import urllib.parse

    q = "?fingerprint=" + urllib.parse.quote(args.fingerprint or "") \
        + "&trigger=" + urllib.parse.quote(args.trigger)
    if args.console:
        incident = _get_json(args.console, "/api/incident" + q)
        if incident.get("error"):
            print(f"error: {incident['error']}", file=sys.stderr)
            return 1
    else:
        rows = []
        for addr in args.addr:
            try:
                rows.append((addr, _get_json(
                    addr, "/debug/bundle?collect=1" + q.replace("?", "&"))))
            except Exception:
                rows.append((addr, None))
        out_root = args.out or os.path.join(flightrec.flight_dir(),
                                            "incidents")
        incident = assemble_incident(rows, out_root,
                                     fingerprint=args.fingerprint or "",
                                     trigger=args.trigger)
    if args.json:
        print(json.dumps(incident, indent=2, default=str), file=out)
        return 0
    print(f"collected: {incident['dir']}", file=out)
    if incident.get("unreachable"):
        print(f"unreachable: {', '.join(incident['unreachable'])}",
              file=out)
    if not incident.get("targets"):
        print("error: no target answered /debug/bundle "
              "(is CFS_FLIGHT set on the daemons?)", file=sys.stderr)
        return 1
    render_summary(summarize(read_bundle(incident["dir"])), out)
    return 0


def _cmd_list(args, out) -> int:
    root = args.dir or flightrec.flight_dir()
    rec = flightrec.FlightRecorder(root)
    rows = [b for b in rec.list_bundles()
            if os.path.exists(os.path.join(b["path"], "manifest.json"))]
    inc_root = os.path.join(root, "incidents")
    incidents = []
    if os.path.isdir(inc_root):
        for name in sorted(os.listdir(inc_root)):
            inc = flightrec._read_json(
                os.path.join(inc_root, name, "incident.json"))
            if inc is not None:
                incidents.append(inc)
    if args.json:
        print(json.dumps({"dir": root, "bundles": rows,
                          "incidents": incidents}, indent=2), file=out)
        return 0
    if not rows and not incidents:
        print(f"(no bundles under {root})", file=out)
        return 0
    for b in rows:
        print(f"bundle    {_fmt_ts(b['ts'])}  {b['trigger']:<8} "
              f"{b['fingerprint'] or '-':<32} {b['bytes']:>8}B  {b['path']}",
              file=out)
    for inc in incidents:
        print(f"incident  {_fmt_ts(inc.get('ts', 0))}  "
              f"{inc.get('trigger', '?'):<8} "
              f"{inc.get('fingerprint') or '-':<32} "
              f"targets={len(inc.get('targets', []))}  {inc['dir']}",
              file=out)
    return 0


def _cmd_inspect(args, out) -> int:
    try:
        s = summarize(read_bundle(args.bundle))
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(s, indent=2, default=str), file=out)
    else:
        render_summary(s, out)
    return 0


def _cmd_diff(args, out) -> int:
    try:
        d = diff_bundles(read_bundle(args.old), read_bundle(args.new))
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(d, indent=2), file=out)
        return 0
    print(f"DIFF {args.old} -> {args.new}  ({d['interval_s']}s apart)",
          file=out)
    if d["alerts"]["old"] != d["alerts"]["new"]:
        print(f"  alerts: {d['alerts']['old']} -> {d['alerts']['new']}",
              file=out)
    for t, delta in d["events"].items():
        print(f"  events {t:<24} {delta:+d}", file=out)
    for r in d["metrics"]:
        tag = "  (restart)" if r.get("restart") else ""
        print(f"  {r['metric']:<64} {r['delta']:>+12g}{tag}", file=out)
    if not d["metrics"]:
        print("  (no metric moved)", file=out)
    return 0


def main(argv=None, out=None) -> int:
    import argparse

    out = out or sys.stdout
    p = argparse.ArgumentParser(
        prog="cfs-doctor",
        description="collect / inspect / diff incident bundles")
    sub = p.add_subparsers(dest="cmd", required=True)

    c = sub.add_parser("collect", help="capture an incident now")
    c.add_argument("--console", help="console host:port (/api/incident)")
    c.add_argument("--addr", action="append", default=[],
                   help="daemon host:port to fan out to directly "
                        "(repeatable; alternative to --console)")
    c.add_argument("--fingerprint", default="",
                   help="alert fingerprint to key the incident by")
    c.add_argument("--trigger", default="manual")
    c.add_argument("--out", help="incident root (default: flight dir)")
    c.add_argument("--json", action="store_true")

    ls = sub.add_parser("list", help="bundles + incidents on disk")
    ls.add_argument("--dir", help="bundle root (default: CFS_FLIGHT_DIR)")
    ls.add_argument("--json", action="store_true")

    i = sub.add_parser("inspect", help="render one bundle's summary")
    i.add_argument("bundle")
    i.add_argument("--json", action="store_true")

    d = sub.add_parser("diff", help="what moved between two bundles")
    d.add_argument("old")
    d.add_argument("new")
    d.add_argument("--json", action="store_true")

    args = p.parse_args(argv)
    if args.cmd == "collect":
        if not args.console and not args.addr:
            print("error: need --console or at least one --addr",
                  file=sys.stderr)
            return 2
        return _cmd_collect(args, out)
    if args.cmd == "list":
        return _cmd_list(args, out)
    if args.cmd == "inspect":
        return _cmd_inspect(args, out)
    return _cmd_diff(args, out)


if __name__ == "__main__":
    sys.exit(main())
