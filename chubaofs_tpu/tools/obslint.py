"""obslint — static lint for the observability plane's invariants.

1. **No high-cardinality metric labels.** A label whose KEY names a per-object
   id (inode, blob id, volume id, extent id, request/trace id, path, upload
   id) explodes the registry: every distinct value mints a fresh time series,
   and one busy volume turns /metrics into a memory leak. Label sets must be
   bounded by construction (op names, reasons, disk kinds). Labels whose
   values are configured identities rather than literals — `tenant` in the
   capacity harness — are bounded at RUNTIME instead: the subsystem declares
   the closed set via `exporter.declare_label_values`, and any undeclared
   value is rejected at the metric call (the runtime half of this rule).

2. **No new ad-hoc stats dicts.** Counters live in `exporter.Registry` (role
   registries), where they are locked, rendered, and scrape-able — not in
   `self.stats = {...}` dict literals that every subsystem reinvents and no
   endpoint can see. The two pre-registry dicts that were MIGRATED to the
   registry (raft drain, codec batches) remain as documented read-only legacy
   views and are allowlisted here.

3. **No direct `http.client.HTTPConnection(...)` outside `rpc/pool.py`.**
   Every HTTP connection rides the keep-alive pool (or its NullPool opt-out)
   so reuse/evict counters stay truthful and the connect-per-request data
   path can never be silently reintroduced.

4. **No latency/deadline arithmetic on `time.time()`.** The wall clock jumps
   (NTP slew/step, manual set); a retry deadline or an idle-TTL delta built
   from it can expire instantly or never. Any `+`/`-` arithmetic whose
   operand is a direct `time.time()` call is flagged — elapsed times and
   deadlines use `time.monotonic()` (or `perf_counter`). Wall stamps that
   only get STORED or COMPARED as timestamps (proposal `now=`, mtimes,
   heartbeat records) don't involve such arithmetic and pass; files whose
   wall-clock arithmetic is cross-process protocol (authnode ticket
   freshness windows) are allowlisted.

5. **No `sock.sendall(pkt.encode())` framing outside the packet layer.**
   `encode()` concatenates header + arg + a possibly multi-MB payload into
   one fresh bytes object — the exact copy the zero-copy iovec path
   (`proto/packet.send_packet` via `sendmsg`) exists to avoid. Call sites
   use `send_packet` (or queue iovecs through `rpc/evloop.py`); only those
   two files may hand-frame packet bytes onto a socket. `# obslint: <why>`
   pragmas an exception.

6. **No bare `print(` diagnostics in daemon code.** Outside `tools/` and
   `cli/` (whose stdout IS the user interface), a print is a log line that
   no .log file rotates, no level filters, and no operator finds — daemon
   diagnostics route through `utils/logger.py` or the structured audit
   trails (`utils/auditlog.py`). The few legitimate prints — a boot line a
   harness parses off stdout, a structured audit line flushed to stderr —
   are PROTOCOL, and each carries a reasoned `# obslint: <why>` pragma
   saying so.

7. **No ad-hoc state-transition writes outside `utils/`.** A bare
   `sys.stderr.write(...)` or a hand-rolled audit record (a dict literal
   carrying an `"audit"` key) in daemon code is a state transition only a
   log-grep can find — no ring, no rotation, no /events, no cursor, no
   cluster merge. Transitions route through `utils/events.EventJournal`
   (`events.emit(...)`), whose records the console `/api/events` rollup and
   `cfs-events` serve. The sanctioned writers live under `utils/` (the
   journal itself, the auditlog rotor, the lock sanitizer's stderr audit
   line); `tools/`/`cli/` stdout-stderr is the user interface, as in rule 6.
   A reasoned `# obslint: <why>` pragma documents a true protocol line.

8. **Every `EVENT_TYPES` name has an emit site.** The journal's type set is
   a closed contract: the runtime validator accepts exactly these names,
   dashboards and tests filter on them, and cfs-events documents them. A
   type nobody emits is a dead promise that silently rots the timeline —
   nothing can ever appear under it, and readers can't tell "quiet" from
   "unwired". This is a package-GLOBAL pass (`lint_event_types`): a name
   counts as covered when a string literal reaches any `*emit*(...)` call's
   first argument (including computed `"a" if c else "b"` forms) or an
   `etype`-named assignment anywhere in the package.

9. **Every actuator invocation in `autopilot/` emits a typed event.** The
   autopilot's whole contract is the auditable cause→action→resolution
   timeline: an actuator `.apply(`/`.rollback(` call whose function emits no
   `autopilot_*` event is an invisible actuation — the cluster changed and
   the timeline can't say why, which is exactly the operator trust the
   closed loop lives or dies on. Scoped per FUNCTION (the emit must share
   the function with the invocation, so the event can carry the causal
   fingerprint from the same frame); a reasoned `# obslint: <why>` pragma
   documents a true exception.

Wired into tier-1 (tests/test_obslint.py) so a regression fails fast.

File-walk, pragma, and CLI plumbing live in tools/lintcore.py, shared with
racelint (the concurrency pass) so the two linters cannot drift.
"""

from __future__ import annotations

import ast
import sys

from chubaofs_tpu.tools import lintcore

# label keys that smell like unbounded per-object ids
BANNED_LABEL_KEYS = {
    "ino", "inode", "bid", "blob_id", "vid", "vuid", "extent", "extent_id",
    "req_id", "request_id", "trace_id", "path", "upload_id", "key", "tx_id",
    "partition_id",
}

# metric-emitting call attributes whose `labels` argument we inspect
_METRIC_METHODS = {"counter", "gauge", "summary", "tp"}

# (path suffix, attribute) pairs of the documented legacy stat dicts — the
# registry migration kept them as read-only views for perfbench/tests
ALLOWED_STATS_DICTS = {
    ("raft/server.py", "drain_stats"),
    ("codec/service.py", "stats"),
}

# the ONE module allowed to construct HTTPConnection: the keep-alive pool
CONN_POOL_PATH = "rpc/pool.py"

# the packet-framing layer: the only files allowed to sendall(pkt.encode())
# (rule 5) — everyone else goes through send_packet's sendmsg iovec path
PACKET_LAYER_PATHS = lintcore.PACKET_LAYER_PATHS

# files whose wall-clock arithmetic is PROTOCOL, not latency: authnode
# verifies request-timestamp freshness across processes, where monotonic
# clocks don't compare and wall time is the contract
ALLOWED_WALLCLOCK_FILES = ("authnode/server.py",)

# directory SEGMENTS whose stdout IS the interface — rule 6 (bare print)
# does not apply: operator CLIs and the lint/bench tools themselves.
# Matched as path segments (not prefixes) so linting an installed package
# (relpath `tools/x.py`) and linting a checkout root (relpath
# `chubaofs_tpu/tools/x.py`) agree — the same contract as path_matches
PRINT_OK_DIRS = ("tools", "cli")

# rule 7's sanctioned writers: utils/ owns the journal, the auditlog rotor
# and the sanitizer's structured stderr line; tools/cli stderr is operator
# diagnostics (their stdout is the interface, rule 6's contract)
EVENTS_OK_DIRS = ("utils", "tools", "cli")


# rule 9's scope: the closed-loop controller package, where every actuator
# invocation must leave a fingerprint-stamped record on the timeline
AUTOPILOT_DIR = "autopilot"
ACTUATOR_CALL_ATTRS = ("apply", "rollback")


def _in_autopilot_dir(relpath: str) -> bool:
    parts = relpath.replace("\\", "/").split("/")
    return AUTOPILOT_DIR in parts[:-1]


def _in_print_ok_dir(relpath: str) -> bool:
    parts = relpath.replace("\\", "/").split("/")
    return any(seg in PRINT_OK_DIRS for seg in parts[:-1])


def _in_events_ok_dir(relpath: str) -> bool:
    parts = relpath.replace("\\", "/").split("/")
    return any(seg in EVENTS_OK_DIRS for seg in parts[:-1])


def _is_stderr_attr(node: ast.expr) -> bool:
    """`sys.stderr` (any `import sys as _sys` alias)."""
    return (isinstance(node, ast.Attribute) and node.attr == "stderr"
            and isinstance(node.value, ast.Name)
            and node.value.id.lstrip("_") == "sys")


def _is_walltime_call(node: ast.expr) -> bool:
    """A direct time.time() call (any `* as <alias>` import of the module:
    `time.time()`, `_time.time()`)."""
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "time"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id.lstrip("_") == "time")


def _names_a_packet(node: ast.expr) -> bool:
    """True when an expression's terminal name reads as a Packet (`pkt`,
    `reply_packet`, `self.pkt`, ...) — rule 5's receiver filter."""
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Name):
        name = node.id
    else:
        return False
    return "pkt" in name.lower() or "packet" in name.lower()


def _labels_arg(call: ast.Call) -> ast.expr | None:
    """The labels argument of a metric call: 2nd positional or labels=."""
    if len(call.args) >= 2:
        return call.args[1]
    for kw in call.keywords:
        if kw.arg == "labels":
            return kw.value
    return None


def lint_source(src: str, relpath: str) -> list[str]:
    """Lint one file's source; returns human-readable findings."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{relpath}: syntax error: {e}"]
    src_lines = src.splitlines()
    findings: list[str] = []
    for node in ast.walk(tree):
        # -- rule 1: banned/high-cardinality metric label keys --------------
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _METRIC_METHODS:
            labels = _labels_arg(node)
            if isinstance(labels, ast.Dict):
                for k, v in zip(labels.keys, labels.values):
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        if k.value.lower() in BANNED_LABEL_KEYS:
                            findings.append(
                                f"{relpath}:{node.lineno}: metric label key "
                                f"{k.value!r} is a per-object id — unbounded "
                                "cardinality; put the id in the trace/log, "
                                "not a label")
                    if isinstance(v, ast.JoinedStr):
                        findings.append(
                            f"{relpath}:{node.lineno}: metric label value is "
                            "an f-string — interpolated ids mint unbounded "
                            "series; use a bounded enum value")
        # -- rule 3: direct HTTPConnection construction outside the pool ----
        # a reasoned `# obslint: <why>` pragma documents the exceptions that
        # are the WORKLOAD, not a client: bench load generators where one
        # keep-alive conn per simulated client is the thing being measured,
        # and per-tenant signed S3 clients the pool doesn't model
        if isinstance(node, ast.Call) and not relpath.endswith(CONN_POOL_PATH):
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            if name in ("HTTPConnection", "HTTPSConnection") \
                    and not lintcore.has_pragma(src_lines, node.lineno,
                                                "obslint"):
                findings.append(
                    f"{relpath}:{node.lineno}: direct {name}( construction — "
                    "every HTTP conn rides rpc/pool.py (ConnectionPool or "
                    "NullPool), so keep-alive reuse and evict counters stay "
                    "truthful; the unpooled path must not sneak back")
        # -- rule 4: latency/deadline arithmetic on the wall clock ----------
        if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Add, ast.Sub)) \
                and (_is_walltime_call(node.left) or _is_walltime_call(node.right)) \
                and not lintcore.path_matches(relpath, ALLOWED_WALLCLOCK_FILES) \
                and not lintcore.has_pragma(src_lines, node.lineno, "wallclock"):
            # a `# wallclock: <why>` pragma documents the exception — wall
            # arithmetic that IS the protocol (e.g. a tx deadline riding a
            # raft proposal, compared by every replica)
            findings.append(
                f"{relpath}:{node.lineno}: latency/deadline arithmetic on "
                "time.time() — the wall clock jumps (NTP, manual set); "
                "deltas and deadlines use time.monotonic()")
        # -- rule 5: hand-framed sendall(pkt.encode()) outside the layer ----
        # only when the encode() receiver NAMES a packet (pkt/packet/...):
        # sendall(json.dumps(cmd).encode()) and friends are text protocols,
        # not the shard-payload concat this rule exists for
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "sendall" and len(node.args) == 1 \
                and isinstance(node.args[0], ast.Call) \
                and isinstance(node.args[0].func, ast.Attribute) \
                and node.args[0].func.attr == "encode" \
                and _names_a_packet(node.args[0].func.value) \
                and not lintcore.path_matches(relpath, PACKET_LAYER_PATHS) \
                and not lintcore.has_pragma(src_lines, node.lineno, "obslint"):
            findings.append(
                f"{relpath}:{node.lineno}: sendall(<x>.encode()) hand-frames "
                "a packet through a full payload concat — the zero-copy "
                "iovec path (proto/packet.send_packet via sendmsg) exists "
                "so multi-MB shard buffers cross the wire uncopied; use "
                "send_packet or the evloop write queue")
        # -- rule 6: bare print( diagnostics in daemon code -----------------
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "print" \
                and not _in_print_ok_dir(relpath) \
                and not lintcore.has_pragma(src_lines, node.lineno, "obslint"):
            findings.append(
                f"{relpath}:{node.lineno}: bare print( in daemon code — "
                "stdout/stderr diagnostics bypass rotation, levels, and "
                "every log consumer; route through utils/logger.py or the "
                "structured audit trails, or pragma a protocol line with "
                "`# obslint: <why>`")
        # -- rule 7: ad-hoc state-transition writes outside utils/ ----------
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "write" \
                and _is_stderr_attr(node.func.value) \
                and not _in_events_ok_dir(relpath) \
                and not lintcore.has_pragma(src_lines, node.lineno, "obslint"):
            findings.append(
                f"{relpath}:{node.lineno}: bare sys.stderr.write( in daemon "
                "code — a state transition written here reaches no ring, no "
                "rotation, no /events cursor; route it through "
                "utils/events.emit() (or pragma a protocol line with "
                "`# obslint: <why>`)")
        if isinstance(node, ast.Dict) and not _in_events_ok_dir(relpath) \
                and any(isinstance(k, ast.Constant) and k.value == "audit"
                        for k in node.keys if k is not None) \
                and not lintcore.has_pragma(src_lines, node.lineno, "obslint"):
            findings.append(
                f"{relpath}:{node.lineno}: hand-rolled audit dict (literal "
                "with an 'audit' key) — structured transition records belong "
                "in utils/events.EventJournal so the console rollup and "
                "cfs-events can serve them; use events.emit() or pragma "
                "with `# obslint: <why>`")
        # -- rule 2: ad-hoc self.*stats* = {...} dict counters --------------
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                        and ("stats" in tgt.attr or tgt.attr.endswith("_counters"))):
                    if any(relpath.endswith(sfx) and tgt.attr == attr
                           for sfx, attr in ALLOWED_STATS_DICTS):
                        continue
                    findings.append(
                        f"{relpath}:{node.lineno}: ad-hoc stats dict "
                        f"`self.{tgt.attr} = {{...}}` — counters belong in "
                        "exporter.registry(<role>) so /metrics can render "
                        "them (allowlisted legacy views excepted)")
    # -- rule 9: silent actuator invocations inside autopilot/ --------------
    if _in_autopilot_dir(relpath):
        findings.extend(_lint_actuator_emits(tree, src_lines, relpath))
    return findings


def _scope_calls(fn: ast.AST):
    """Call nodes in a function's OWN scope — nested def/async-def bodies
    are their own rule-9 scopes and are not descended into (an emit hidden
    in a closure can't prove the outer invocation was recorded)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _lint_actuator_emits(tree: ast.AST, src_lines: list[str],
                         relpath: str) -> list[str]:
    """Rule 9: inside autopilot/, any function invoking an actuator
    (`<x>.apply(` / `<x>.rollback(`) must, in the SAME function, emit an
    event whose type literal starts `autopilot_` — the invocation and its
    timeline record share a frame, so the record carries the causal
    fingerprint. `# obslint: <why>` on the invocation line escapes."""
    findings: list[str] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        invocations: list[ast.Call] = []
        emits_typed = False
        for call in _scope_calls(fn):
            f = call.func
            if isinstance(f, ast.Attribute) \
                    and f.attr in ACTUATOR_CALL_ATTRS:
                invocations.append(call)
            name = (f.attr if isinstance(f, ast.Attribute)
                    else f.id if isinstance(f, ast.Name) else "")
            if "emit" in name and call.args:
                for sub in ast.walk(call.args[0]):
                    if isinstance(sub, ast.Constant) \
                            and isinstance(sub.value, str) \
                            and sub.value.startswith("autopilot_"):
                        emits_typed = True
        if emits_typed:
            continue
        for call in invocations:
            if lintcore.has_pragma(src_lines, call.lineno, "obslint"):
                continue
            findings.append(
                f"{relpath}:{call.lineno}: actuator `.{call.func.attr}(` in "
                f"`{fn.name}` with no autopilot_* event emitted in the same "
                "function — an unrecorded actuation breaks the cause→action"
                "→resolution audit trail; emit autopilot_executed/"
                "autopilot_rolled_back here (or pragma with "
                "`# obslint: <why>`)")
    return findings


def _emit_literals(tree: ast.AST) -> set[str]:
    """Every string literal that can reach an emit call in this module: a
    literal anywhere inside a Call whose callee name/attr mentions `emit`
    (covers `events.emit("x", ...)`, `self._emit_bp("x", ...)`, and the
    IfExp form `ev.emit("a" if c else "b", ...)`), plus literals assigned
    to an `etype`-named variable (the alert plane computes the type first,
    then emits it)."""
    out: set[str] = set()

    def literals_under(node: ast.AST) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                out.add(sub.value)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else "")
            if "emit" in name and node.args:
                literals_under(node.args[0])
        elif isinstance(node, ast.Assign):
            if any(isinstance(t, ast.Name) and "etype" in t.id
                   for t in node.targets):
                literals_under(node.value)
    return out


def lint_event_types(root: str | None = None) -> list[str]:
    """Rule 8, a package-GLOBAL pass (per-file rules can't see it): every
    name in `events.EVENT_TYPES` must have at least one emit site somewhere
    in the package. A type with no emitter is a dead timeline contract —
    dashboards and tests filter on it, the runtime validator accepts it,
    and nothing can ever appear."""
    from chubaofs_tpu.utils.events import EVENT_TYPES

    emitted: set[str] = set()
    for abspath, relpath in lintcore.iter_py_files(
            root or lintcore.package_root()):
        try:
            with open(abspath, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=relpath)
        except (OSError, SyntaxError):
            continue
        emitted |= _emit_literals(tree)
    return [f"utils/events.py: EVENT_TYPES entry `{t}` has no emit( site "
            f"in the package — a dead event type silently rots the "
            f"timeline contract (emit it or prune it)"
            for t in EVENT_TYPES if t not in emitted]


def run(root: str | None = None) -> list[str]:
    """Lint every .py file under the package (rules 1-7 plus rule 9's
    autopilot actuator-audit pass), then the package-global event-type
    coverage pass (rule 8); returns all findings."""
    return lintcore.run_package(lint_source, root) + lint_event_types(root)


def main(argv=None) -> int:
    return lintcore.lint_main(
        "obslint", "lint metric-label cardinality + ad-hoc stats dicts",
        run, argv)


if __name__ == "__main__":
    sys.exit(main())
