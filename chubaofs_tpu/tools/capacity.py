"""cfs-capacity — SLO-gated open-loop capacity harness (ROADMAP item 7).

Simulate the million-user day and let the health plane judge it: a seeded,
deterministic workload generator drives a real cluster with a multi-tenant
mix (blob PUT/GET/DELETE through the SDK access path plus FUSE-style
metadata ops and hot-volume file IO), zipfian key popularity, and a
configurable diurnal ramp — OPEN loop, so the ARRIVAL rate sets the pace and
a slow cluster accumulates backlog instead of quietly throttling the bench.
Meanwhile a collector thread polls the console's `/api/health` +
`/api/metrics` and archives timestamped cfs-top frames to a JSONL capacity
report. The run FAILS (nonzero exit, flipped SLOs named) if any burn-window
SLO flips to failing on any target — the same gate discipline
`cfs-chaos-soak --sanitize` gave the lock sanitizer, applied to capacity.

Dataflow:  generator → cluster → health rollup → gate → archived report

Knobs (env defaults, CLI flags override):

    CFS_CAP_TENANTS   tenant count (default 4; archetypes cycle)
    CFS_CAP_ZIPF_S    zipf skew exponent s (default 1.2)
    CFS_CAP_RAMP      arrival ramp shape: diurnal | flat | spike
    CFS_CAP_SEED      generator seed (default 0)

Determinism contract (the chaos-scheduler reproducibility contract applied
to load): `plan_ops` is a pure function of its arguments — same seed ⇒ the
IDENTICAL op sequence (tenant, kind, key, size, arrival time) and identical
per-tenant op counts, run over run. Execution-side completion order rides
thread scheduling and is not part of the contract.

The closing actuator: `--rebalance` arms the master's hot-volume spreading
sweep (`rebalance_hot`, cmd.py's rebalanceHotSecs knob), and
`--ab-rebalance` runs the same seeded scenario twice — rebalance off, then
on — reporting the per-node ops spread of each so the A/B shows the skew
the generator created and the spread reduction the actuator bought.

The closed loop (ISSUE 20): `--autopilot` arms a console-fed Autopilot on
the collector's alert polls — firing alerts map through the declarative
bindings to MasterClient actuators (rebalance/split), gated by budget,
cooldown, and flap damping, every decision a typed `autopilot_*` event.
`--ab-autopilot` runs the control arm (off) then the closed loop (on);
`--scenario hotspot|tenant-storm|node-kill` injects the canned stress both
arms must face.

    cfs-capacity --seed 7 --duration 20 --out cap.jsonl
    cfs-capacity --seed 7 --failpoints 'blobnode.put_shard=delay(0.08)' \
        --daemon-env CFS_SLO_PUT_P99_MS=20      # must exit nonzero
    cfs-capacity --seed 7 --ab-rebalance --datanodes 5
    cfs-capacity --seed 7 --scenario hotspot --ab-autopilot --datanodes 5
"""

from __future__ import annotations

import bisect
import itertools
import json
import math
import os
import random
import sys
import threading
import time
import zlib
from dataclasses import dataclass

from chubaofs_tpu.utils import exporter
from chubaofs_tpu.utils.config import env_float, env_int
from chubaofs_tpu.utils.locks import SanitizedLock
from chubaofs_tpu.utils.slo import FAILING, OK, RANK

# -- the plan (pure, seeded) ---------------------------------------------------

# tenant archetypes: op blends along the system-characteristics axes of
# arxiv 1709.05365 (write-heavy ingest, read-heavy serving, metadata-bound,
# delete-heavy churn). Tenants cycle through these by index.
PROFILES: list[tuple[str, dict[str, float]]] = [
    ("ingest", {"blob_put": 0.45, "blob_get": 0.20, "blob_delete": 0.05,
                "meta_create": 0.15, "meta_stat": 0.10, "meta_list": 0.05}),
    ("serve", {"blob_get": 0.60, "blob_put": 0.10, "meta_stat": 0.20,
               "meta_list": 0.10}),
    ("metabound", {"meta_create": 0.30, "meta_stat": 0.35, "meta_list": 0.15,
                   "meta_delete": 0.10, "blob_put": 0.05, "blob_get": 0.05}),
    ("churn", {"blob_put": 0.25, "blob_get": 0.25, "blob_delete": 0.25,
               "meta_create": 0.10, "meta_delete": 0.15}),
]

# blends gain these when the cluster has a hot (replica-tier) volume: the
# datanode plane must see the same zipfian skew the rebalancer acts on
HOT_BLEND = {"hot_write": 0.15, "hot_read": 0.35}

OP_KINDS = ("blob_put", "blob_get", "blob_delete", "meta_create", "meta_stat",
            "meta_list", "meta_delete", "hot_write", "hot_read")
STATUSES = ("ok", "error", "miss")


@dataclass(frozen=True)
class Op:
    at: float      # arrival offset from run start (s) — the open-loop clock
    tenant: str
    kind: str
    key: int       # zipf-ranked object key within the tenant's keyspace
    size: int      # payload bytes for writes


def zipf_cdf(n: int, s: float) -> list[float]:
    """Cumulative zipf weights over ranks 1..n (bisect target)."""
    weights = [1.0 / (r ** s) for r in range(1, n + 1)]
    total = sum(weights)
    cdf, acc = [], 0.0
    for w in weights:
        acc += w
        cdf.append(acc / total)
    return cdf


def ramp_factor(frac: float, shape: str) -> float:
    """Arrival-rate multiplier at run fraction `frac` in [0, 1]."""
    if shape == "flat":
        return 1.0
    if shape == "spike":
        return 3.0 if 0.45 <= frac < 0.55 else 0.7
    # diurnal: night floor ramping to a midday peak and back (half-sine)
    return 0.25 + 0.75 * math.sin(math.pi * min(max(frac, 0.0), 1.0))


def plan_ops(seed: int, n_tenants: int, duration_s: float, base_rate: float,
             zipf_s: float, keys_per_tenant: int = 64, ramp: str = "diurnal",
             mean_kb: int = 16, hot: bool = False,
             storm: str | None = None) -> dict:
    """The full open-loop schedule, a pure function of its arguments: a
    seeded arrival process (rate = base_rate x ramp) where each op draws a
    tenant, a blend-weighted kind, a zipf-popular key, and a size. Returns
    {"tenants", "ops", "per_tenant"} — per_tenant is the count audit the
    determinism test compares run-over-run. `storm` names one tenant that
    soaks up 60% of the arrivals (the tenant-storm scenario): the mix stays
    seeded-deterministic, only the tenant draw is biased."""
    rng = random.Random(seed)
    tenants = [f"t{i}" for i in range(n_tenants)]
    blends: dict[str, list[tuple[str, float]]] = {}
    for i, t in enumerate(tenants):
        blend = dict(PROFILES[i % len(PROFILES)][1])
        if hot:
            blend.update(HOT_BLEND)
        total = sum(blend.values())
        acc, items = 0.0, []
        for kind, w in sorted(blend.items()):
            acc += w / total
            items.append((kind, acc))
        blends[t] = items
    cdf = zipf_cdf(keys_per_tenant, zipf_s)
    ops: list[Op] = []
    per_tenant: dict[str, dict[str, int]] = {t: {} for t in tenants}
    t_now = 0.0
    while True:
        rate = base_rate * max(0.05, ramp_factor(t_now / duration_s, ramp))
        t_now += rng.expovariate(rate)
        if t_now >= duration_s:
            break
        if storm is not None and storm in tenants and rng.random() < 0.6:
            tenant = storm
        else:
            tenant = tenants[rng.randrange(n_tenants)]
        roll = rng.random()
        kind = next(k for k, edge in blends[tenant] if roll <= edge)
        key = bisect.bisect_left(cdf, rng.random())
        size = max(1024, min(256 << 10, int(rng.expovariate(1.0 / (mean_kb * 1024)))))
        ops.append(Op(round(t_now, 6), tenant, kind, key, size))
        pt = per_tenant[tenant]
        pt[kind] = pt.get(kind, 0) + 1
    return {"tenants": tenants, "ops": ops, "per_tenant": per_tenant,
            "seed": seed}


# -- drivers -------------------------------------------------------------------


class CapacityDriver:
    """The cluster face the executor calls. Blob verbs ride the SDK access
    client (PUT returns an opaque location token), metadata and hot-tier
    verbs ride FsClients. `fs()`/`hot_fs()` may be called from worker
    threads concurrently — implementations hand out thread-local clients
    when the transport needs it. `tenant` rides every blob verb so a
    multi-tenant surface (the S3 gateway) can present per-tenant
    credentials; the SDK drivers ignore it."""

    def blob_put(self, data: bytes, tenant: str | None = None) -> str:
        raise NotImplementedError

    def blob_get(self, token: str, tenant: str | None = None) -> bytes:
        raise NotImplementedError

    def blob_delete(self, token: str, tenant: str | None = None) -> None:
        raise NotImplementedError

    def fs(self):
        raise NotImplementedError

    def hot_fs(self):
        return None


class RemoteDriver(CapacityDriver):
    """Over a daemon cluster: AccessClient for blobs, RemoteCluster
    FsClients (thread-local — the metanode packet transport is per-client)
    for metadata / hot IO."""

    def __init__(self, master_addrs: list[str], access_addrs: list[str],
                 cold_volume: str, hot_volume: str | None = None):
        from chubaofs_tpu.blobstore.gateway import AccessClient

        self.master_addrs = list(master_addrs)
        self.access_addrs = list(access_addrs)
        self.cold_volume = cold_volume
        self.hot_volume = hot_volume
        self.ac = AccessClient(self.access_addrs)
        self._tls = threading.local()

    def _clients(self):
        if not hasattr(self._tls, "fs"):
            from chubaofs_tpu.sdk.cluster import RemoteCluster

            rc = RemoteCluster(self.master_addrs,
                               access_addrs=self.access_addrs)
            self._tls.fs = rc.client(self.cold_volume)
            self._tls.hot = (rc.client(self.hot_volume)
                             if self.hot_volume else None)
        return self._tls

    def blob_put(self, data: bytes, tenant: str | None = None) -> str:
        return self.ac.put(data).to_json()

    def blob_get(self, token: str, tenant: str | None = None) -> bytes:
        return self.ac.get(token)

    def blob_delete(self, token: str, tenant: str | None = None) -> None:
        self.ac.delete(token)

    def fs(self):
        return self._clients().fs

    def hot_fs(self):
        return self._clients().hot


class LocalDriver(CapacityDriver):
    """Over an in-process deploy.FsCluster (the bench/CI smoke): blobs ride
    the MiniCluster access layer directly, metadata the in-proc clients."""

    def __init__(self, cluster, cold_volume: str, hot_volume: str | None = None):
        self.cluster = cluster
        self.access = cluster.blobstore.access
        self._fs = cluster.client(cold_volume)
        self._hot = cluster.client(hot_volume) if hot_volume else None

    def blob_put(self, data: bytes, tenant: str | None = None) -> str:
        return self.access.put(data).to_json()

    def blob_get(self, token: str, tenant: str | None = None) -> bytes:
        return self.access.get(token)

    def blob_delete(self, token: str, tenant: str | None = None) -> None:
        self.access.delete(token)

    def fs(self):
        return self._fs

    def hot_fs(self):
        return self._hot


class S3Driver(CapacityDriver):
    """Blob verbs over the objectnode S3 surface with PER-TENANT sigv4
    credentials (ISSUE 14): the tenant mix lands on the gateway the QoS
    plane shapes, so `cfs-capacity --s3` gates fairness through the same
    SLO burn-window verdict as every other scenario. Each tenant owns its
    bucket (`cap-<tenant>`); a PUT mints a fresh key and the returned
    token is the object path. Any non-2xx — INCLUDING a 429/503 throttle —
    surfaces as an op error, which is exactly what feeds the error-ratio
    and per-tenant throttle SLOs the gate reads. Metadata/hot verbs
    delegate to an inner SDK driver (the S3 dialect has no metadata-op
    analog)."""

    def __init__(self, s3_addr: str, creds: dict[str, tuple[str, str]],
                 inner: CapacityDriver | None = None):
        self.addr = s3_addr
        self.creds = dict(creds)
        self.inner = inner
        self._tls = threading.local()
        self._uid = itertools.count()

    def _request(self, method: str, path: str, tenant: str,
                 body: bytes = b"") -> tuple[int, bytes]:
        import http.client

        from chubaofs_tpu.objectnode.auth import sign_v4

        ak, sk = self.creds[tenant]
        hdrs = sign_v4(method, path, "", {"host": self.addr}, ak, sk,
                       payload=body)
        conn = getattr(self._tls, "conn", None)
        for attempt in (0, 1):  # one free retry on a stale keep-alive conn
            if conn is None:
                host, port = self.addr.rsplit(":", 1)
                conn = http.client.HTTPConnection(  # obslint: per-tenant sigv4 S3 client; the rpc pool neither signs nor models per-tenant conns
                    host, int(port), timeout=60)
                self._tls.conn = conn
            try:
                conn.request(method, path, body=body or None, headers=hdrs)
                resp = conn.getresponse()
                return resp.status, resp.read()
            except Exception:
                conn.close()
                conn = self._tls.conn = None
                if attempt:
                    raise
        raise RuntimeError("unreachable")

    def ensure_buckets(self) -> None:
        for tenant in self.creds:
            status, body = self._request("PUT", f"/cap-{tenant}", tenant)
            if status != 200 and b"BucketAlreadyExists" not in body:
                raise RuntimeError(
                    f"bucket create for {tenant}: HTTP {status} {body[:200]}")

    def blob_put(self, data: bytes, tenant: str | None = None) -> str:
        path = f"/cap-{tenant}/o{next(self._uid)}"
        status, body = self._request("PUT", path, tenant, body=data)
        if status != 200:
            raise RuntimeError(f"S3 PUT {path}: HTTP {status} {body[:120]}")
        return path

    def blob_get(self, token: str, tenant: str | None = None) -> bytes:
        status, body = self._request("GET", token, tenant)
        if status != 200:
            raise RuntimeError(f"S3 GET {token}: HTTP {status} {body[:120]}")
        return body

    def blob_delete(self, token: str, tenant: str | None = None) -> None:
        status, body = self._request("DELETE", token, tenant)
        if status not in (200, 204):
            raise RuntimeError(f"S3 DELETE {token}: HTTP {status} "
                               f"{body[:120]}")

    def fs(self):
        return self.inner.fs() if self.inner is not None else None

    def hot_fs(self):
        return self.inner.hot_fs() if self.inner is not None else None


# -- the open-loop executor ----------------------------------------------------


class DataLossError(AssertionError):
    """A created blob vanished or read back different bytes — the one
    failure class the gate reports independently of the SLO verdict."""


class Workload:
    """Executes a plan open-loop: ops are SUBMITTED at their arrival times
    regardless of completion progress, so a cluster that can't keep up shows
    rising lateness and server-side latency (which is exactly what the SLO
    burn windows exist to catch) instead of a silently stretched run.

    Correctness ledger: per-(tenant, key) the last PUT's crc32 is held and
    every GET verifies against it — byte-identical reads and no created-blob
    loss are hard failures, not metrics. Per-key locks serialize ops on one
    key (per-object consistency), so verification is exact while distinct
    keys still fan out across the worker pool."""

    def __init__(self, driver: CapacityDriver, plan: dict, seed: int = 0,
                 workers: int = 8):
        self.driver = driver
        self.plan = plan
        self.workers = workers
        self.rng = random.Random(f"capacity-payload-{seed}")
        # tenant is a BOUNDED label from here on: any stray string aborts
        exporter.declare_label_values("tenant", plan["tenants"])
        self.reg = exporter.registry("capacity")
        # registries are process-global: baseline every counter this run will
        # read so an A/B's second phase reports ITS ops, not the sum
        self._base = {(t, k, s): self.reg.counter(
            "ops", {"tenant": t, "op": k, "status": s}).value
            for t in plan["tenants"] for k in OP_KINDS for s in STATUSES}
        self._lock = SanitizedLock(name="capacity.workload")
        self._blob: dict[tuple[str, int], tuple[str, int]] = {}  # (t,k) -> (token, crc)
        self._hotcrc: dict[tuple[str, int], int] = {}
        self._keylocks: dict[tuple[str, int], threading.Lock] = {}
        self.corruptions: list[str] = []
        self.max_late_s = 0.0

    # -- bookkeeping ----------------------------------------------------------

    def _keylock(self, tenant: str, key: int) -> threading.Lock:
        with self._lock:
            lk = self._keylocks.get((tenant, key))
            if lk is None:
                lk = self._keylocks[(tenant, key)] = threading.Lock()
            return lk

    def _count(self, op: Op, status: str) -> None:
        self.reg.counter("ops", {"tenant": op.tenant, "op": op.kind,
                                 "status": status}).add()

    def _payload(self, size: int) -> bytes:
        with self._lock:  # Random instances are not thread-safe
            return self.rng.randbytes(size)

    # -- op bodies ------------------------------------------------------------

    def _path(self, op: Op) -> str:
        return f"/cap/{op.tenant}/k{op.key}"

    def _exec(self, op: Op) -> str:
        k = (op.tenant, op.key)
        with self._keylock(*k):
            if op.kind == "blob_put":
                data = self._payload(op.size)
                token = self.driver.blob_put(data, tenant=op.tenant)
                with self._lock:
                    old = self._blob.get(k)
                    self._blob[k] = (token, zlib.crc32(data))
                if old:  # overwrite semantics: retire the displaced blob
                    self.driver.blob_delete(old[0], tenant=op.tenant)
                return "ok"
            if op.kind == "blob_get":
                with self._lock:
                    ent = self._blob.get(k)
                if ent is None:
                    return "miss"  # nothing PUT under this key yet
                data = self.driver.blob_get(ent[0], tenant=op.tenant)
                if zlib.crc32(data) != ent[1]:
                    raise DataLossError(
                        f"blob {k} read back different bytes")
                return "ok"
            if op.kind == "blob_delete":
                with self._lock:
                    ent = self._blob.pop(k, None)
                if ent is None:
                    return "miss"
                self.driver.blob_delete(ent[0], tenant=op.tenant)
                return "ok"
            if op.kind in ("hot_write", "hot_read"):
                return self._exec_hot(op, k)
            return self._exec_meta(op)

    def _exec_hot(self, op: Op, k: tuple) -> str:
        from chubaofs_tpu.sdk.fs import FsError

        fs = self.driver.hot_fs()
        if fs is None:
            return "miss"  # no hot volume in this topology
        path = f"/hot/{op.tenant}/k{op.key}"
        if op.kind == "hot_write":
            data = self._payload(op.size)
            fs.mkdirs(f"/hot/{op.tenant}")
            fs.write_file(path, data)
            with self._lock:
                self._hotcrc[k] = zlib.crc32(data)
            return "ok"
        with self._lock:
            want = self._hotcrc.get(k)
        if want is None:
            return "miss"
        try:
            data = fs.read_file(path)
        except FsError:
            raise DataLossError(f"hot file {path} vanished") from None
        if zlib.crc32(data) != want:
            raise DataLossError(f"hot file {path} read back different bytes")
        return "ok"

    def _exec_meta(self, op: Op) -> str:
        from chubaofs_tpu.sdk.fs import FsError

        fs = self.driver.fs()
        path = self._path(op)
        try:
            if op.kind == "meta_create":
                fs.mkdirs(f"/cap/{op.tenant}")
                try:
                    fs.create(path)
                except FsError as e:
                    if e.code != "EEXIST":
                        raise
                return "ok"
            if op.kind == "meta_stat":
                fs.stat(path)
                return "ok"
            if op.kind == "meta_list":
                fs.readdir(f"/cap/{op.tenant}")
                return "ok"
            if op.kind == "meta_delete":
                fs.unlink(path)
                return "ok"
        except FsError as e:
            if e.code in ("ENOENT", "ENOTDIR"):
                return "miss"  # deletes/stats race by design under churn
            raise
        raise ValueError(f"unknown op kind {op.kind!r}")

    def _run_one(self, op: Op, sched_mono: float) -> None:
        # lateness measured at EXECUTION start, not submit: submit to the
        # unbounded executor queue is instant, so only this stamp exposes
        # the backlog an overwhelmed cluster accumulates (the open-loop
        # signal this harness exists to surface)
        late_s = time.monotonic() - sched_mono
        with self._lock:
            if late_s > self.max_late_s:
                self.max_late_s = late_s
        self.reg.summary("op_lateness_s").observe(max(0.0, late_s))
        try:
            with self.reg.tp("op_latency", {"op": op.kind}):
                status = self._exec(op)
        except DataLossError as e:
            with self._lock:
                self.corruptions.append(str(e))
            self._count(op, "error")
            return
        except Exception:
            status = "error"
        self._count(op, status)

    # -- the loop -------------------------------------------------------------

    def run(self, drain_timeout: float = 120.0) -> dict:
        from concurrent.futures import ThreadPoolExecutor, wait

        start = time.monotonic()
        futs = []
        pool = ThreadPoolExecutor(self.workers, thread_name_prefix="cap-worker")
        try:
            for op in self.plan["ops"]:
                delay = (start + op.at) - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                futs.append(pool.submit(self._run_one, op, start + op.at))
            _, pending = wait(futs, timeout=drain_timeout)
        finally:
            # no `with`: the context exit is shutdown(wait=True), which would
            # block PAST drain_timeout on a wedged cluster — the gate must
            # get to report. cancel_futures drops the queued backlog so
            # abandoned ops don't keep executing into the counters either.
            pool.shutdown(wait=False, cancel_futures=True)
        return self.summary(abandoned=len(pending),
                            wall_s=time.monotonic() - start)

    def summary(self, abandoned: int = 0, wall_s: float = 0.0) -> dict:
        per_tenant: dict[str, dict] = {}
        totals = dict.fromkeys(STATUSES, 0)
        for t in self.plan["tenants"]:
            row: dict[str, int] = {}
            for kind in OP_KINDS:
                for status in STATUSES:
                    v = int(self.reg.counter(
                        "ops", {"tenant": t, "op": kind,
                                "status": status}).value
                        - self._base[(t, kind, status)])
                    if v:
                        row[f"{kind}_{status}"] = v
                        totals[status] += v
            per_tenant[t] = row
        return {"ops_planned": len(self.plan["ops"]), **{
            f"ops_{s}": v for s, v in totals.items()},
            "ops_abandoned": abandoned, "wall_s": round(wall_s, 2),
            "max_late_s": round(self.max_late_s, 3),
            "corruptions": list(self.corruptions),
            "per_tenant": per_tenant}

    def close(self) -> None:
        exporter.declare_label_values("tenant", None)


# -- the collector + gate ------------------------------------------------------


def failing_slos(health: dict[str, dict]) -> dict[str, list[str]]:
    """target -> names of its FAILING SLOs (['unreachable'] for a corpse,
    ['failing'] for a target failing without naming one)."""
    out: dict[str, list[str]] = {}
    for target, h in (health or {}).items():
        if (h or {}).get("status") != FAILING:
            continue
        names = sorted(name for name, s in (h.get("slos") or {}).items()
                       if (s or {}).get("status") == FAILING)
        if not names:
            names = (["unreachable"]
                     if "unreachable" in (h.get("reasons") or ()) else
                     ["failing"])
        out[target] = names
    return out


class Collector(threading.Thread):
    """Polls the console (or direct daemon addrs) every `interval` and
    archives one cfs-top frame per poll as a JSONL record — the capacity
    report — while accumulating the gate's evidence: every (target, slo)
    pair seen failing and the worst status observed."""

    def __init__(self, out_path: str, console: str | None = None,
                 addrs: list[str] | None = None, interval: float = 1.0,
                 autopilot=None):
        super().__init__(name="cap-collector", daemon=True)
        self.out_path = out_path
        self.console = console
        self.addrs = list(addrs or [])
        self.interval = interval
        # the console-fed closed loop (ISSUE 20): each alert poll is also
        # forwarded to an Autopilot's observe_rollup, so the controller
        # sees the firing↔resolved edges the harness's gate judges by
        self.autopilot = autopilot
        self._halt = threading.Event()
        self._lock = SanitizedLock(name="capacity.collector")
        self.frames = 0
        self.health_frames = 0  # frames that carried >=1 target verdict
        self.worst = OK
        self.flipped: dict[str, set] = {}
        self.poll_errors = 0
        # the event/alert timeline rides the same report (ISSUE 13): each
        # frame archives the events that arrived since the last poll
        # (cursor-paged, so nothing double-archives) and the alerts
        # currently firing; the verdict NAMES every alert that fired
        self._events_cursor: dict = {}
        self.alerts_fired: dict[str, set] = {}  # target -> rule names

    def stop(self, timeout: float = 30.0) -> None:
        self._halt.set()
        self.join(timeout=timeout)

    def _poll_timeline(self, rec: dict) -> None:
        """Fold the since-last-poll event slice and the firing alerts into
        this frame's archive record. Best-effort per surface: a console
        that predates the event plane costs a poll error, never the frame."""
        from chubaofs_tpu.tools.cfsevents import fetch_alerts, fetch_events

        try:
            evs, cursor, _ = fetch_events(self.console, self.addrs,
                                          cursor=self._events_cursor, n=500)
            self._events_cursor = cursor
            rec["events"] = [
                {"ts": e.get("ts"), "type": e.get("type"),
                 "severity": e.get("severity"), "entity": e.get("entity"),
                 "target": e.get("target", ""), "detail": e.get("detail")}
                for e in evs]
        except Exception:
            rec["events"] = None  # surface unavailable, distinct from []
            with self._lock:
                self.poll_errors += 1
        try:
            roll = fetch_alerts(self.console, self.addrs)
            firing: dict[str, list[str]] = {}
            for row in roll.get("targets", ()):
                names = sorted({a["name"] for a in row.get("alerts", ())
                                if a.get("state") == "firing"})
                if names:
                    firing[row["target"]] = names
                    with self._lock:
                        self.alerts_fired.setdefault(
                            row["target"], set()).update(names)
            rec["alerts"] = firing
            if self.autopilot is not None:
                # the whole rollup, all states: observe_rollup dedups the
                # firing set itself and derives the resolved edges
                self.autopilot.observe_rollup(
                    [a for row in roll.get("targets", ())
                     for a in row.get("alerts", ())])
        except Exception:
            rec["alerts"] = None
            with self._lock:
                self.poll_errors += 1

    def _poll_once(self, t0: float, prev: dict) -> dict:
        from chubaofs_tpu.tools.cfstop import (
            compute_rows, fetch_frame, frame_record)

        cur = fetch_frame(self.console, self.addrs)
        rows = compute_rows(prev, cur)
        rec = frame_record(t0, cur, rows)
        self._poll_timeline(rec)
        flips = failing_slos(cur["health"])
        statuses = [h.get("status", FAILING)
                    for h in cur["health"].values()] or [OK]
        worst_now = max(statuses, key=lambda s: RANK.get(s, RANK[FAILING]))
        rec["worst"] = worst_now if worst_now in RANK else FAILING
        rec["failing"] = {t: sorted(n) for t, n in flips.items()}
        with self._lock:
            self.frames += 1
            if cur["health"]:
                self.health_frames += 1
            if cur["errors"]:
                self.poll_errors += 1
            if RANK.get(rec["worst"], RANK[FAILING]) > RANK[self.worst]:
                self.worst = rec["worst"]
            for target, names in flips.items():
                self.flipped.setdefault(target, set()).update(names)
        with open(self.out_path, "a", encoding="utf-8") as f:
            f.write(json.dumps(rec) + "\n")
        return cur

    def run(self) -> None:
        from chubaofs_tpu.tools.cfstop import fetch_frame

        prev = fetch_frame(self.console, self.addrs)
        t0 = prev["mono"]
        while not self._halt.wait(self.interval):
            try:
                prev = self._poll_once(t0, prev)
            except Exception:
                with self._lock:
                    self.poll_errors += 1
        # one closing frame so a fault injected near the end still lands
        try:
            self._poll_once(t0, prev)
        except Exception:
            with self._lock:
                self.poll_errors += 1

    def verdict(self) -> dict:
        """The gate: failing iff any SLO flipped on any target — or iff the
        collector gathered NO health evidence at all. A dead/misaddressed
        console yields empty health dicts on every poll; an all-green
        verdict built on zero verdicts would let a capacity run pass
        blind, so absence of evidence fails the gate loudly."""
        with self._lock:
            flipped = {t: sorted(n) for t, n in self.flipped.items()}
            if self.health_frames == 0:
                flipped.setdefault("collector", []).append("no-health-data")
            return {"verdict": FAILING if flipped else self.worst,
                    "flipped": flipped, "frames": self.frames,
                    "health_frames": self.health_frames,
                    "poll_errors": self.poll_errors,
                    # the gate NAMES the alerts that fired during the run —
                    # the operator reads which rule paged, not just that an
                    # SLO burn window flipped
                    "alerts_fired": {t: sorted(n)
                                     for t, n in self.alerts_fired.items()}}


# -- spread measurement (the A/B's metric) -------------------------------------


class SpreadMonitor(threading.Thread):
    """Accumulates per-datanode op load across heartbeat windows by sampling
    the master registry; windows are deduped on last_heartbeat so each
    report counts once. The summary is the per-node ops spread the
    rebalance A/B compares (coefficient of variation + max/mean)."""

    def __init__(self, mc, interval: float = 0.5):
        super().__init__(name="cap-spread", daemon=True)
        self.mc = mc
        self.interval = interval
        self._halt = threading.Event()
        self._lock = SanitizedLock(name="capacity.spread")
        self.totals: dict[int, float] = {}
        self._seen_hb: dict[int, float] = {}

    def stop(self, timeout: float = 10.0) -> None:
        self._halt.set()
        self.join(timeout=timeout)

    def sample(self) -> None:
        cluster = self.mc.get_cluster()
        with self._lock:
            for n in cluster["nodes"]:
                if n.get("kind") != "data":
                    continue
                nid = int(n["node_id"])
                hb = float(n.get("last_heartbeat") or 0.0)
                if hb and self._seen_hb.get(nid) == hb:
                    continue  # same window as last sample
                self._seen_hb[nid] = hb
                self.totals[nid] = self.totals.get(nid, 0.0) + sum(
                    (n.get("loads") or {}).values())

    def run(self) -> None:
        while not self._halt.wait(self.interval):
            try:
                self.sample()
            except Exception:
                pass  # master hiccup: next sample catches up
        try:
            self.sample()
        except Exception:
            pass

    def summary(self) -> dict:
        with self._lock:
            totals = dict(self.totals)
        vals = list(totals.values())
        if not vals or sum(vals) <= 0:
            return {"per_node": totals, "cv": 0.0, "max_over_mean": 0.0}
        mean = sum(vals) / len(vals)
        var = sum((v - mean) ** 2 for v in vals) / len(vals)
        return {"per_node": {str(k): round(v, 1) for k, v in totals.items()},
                "cv": round(math.sqrt(var) / mean, 3),
                "max_over_mean": round(max(vals) / mean, 3)}


# -- orchestration -------------------------------------------------------------


def run_capacity(args, rebalance: bool, root: str, out_path: str,
                 autopilot: bool | None = None) -> dict:
    """One full harness phase: boot a ProcCluster + console, run the seeded
    open-loop workload under the collector, tear down, return the summary
    (gate verdict + workload ledger + spread). With `autopilot` a
    console-fed Autopilot rides the Collector's alert polls and drives the
    master through MasterClient actuators — the closed loop under test."""
    from chubaofs_tpu.testing.harness import ProcCluster

    autopilot = (getattr(args, "autopilot", False)
                 if autopilot is None else autopilot)
    scenario = getattr(args, "scenario", "none")

    env = {}
    for kv in args.daemon_env:
        k, _, v = kv.partition("=")
        env[k] = v
    if args.failpoints:
        env["CFS_FAILPOINTS"] = args.failpoints
    # the harness IS an incident consumer: arm every daemon's flight
    # recorder so an SLO-gate flip can collect evidence (--daemon-env
    # CFS_FLIGHT=0 opts out for the zero-overhead A/B)
    env.setdefault("CFS_FLIGHT", "1")
    if getattr(args, "cache_mb", 0) > 0:
        # the cache-tier A/B lever: the blobstore daemon's MiniCluster
        # builds a BlobCache from this env knob, so the harness's zipfian
        # GET head rides the tiered read plane (cfs_cache_* families then
        # show up in the capacity report's frames)
        env["CFS_CACHE_MB"] = str(args.cache_mb)
    master_extra = {}
    if rebalance:
        master_extra["rebalanceHotSecs"] = args.rebalance_secs
    s3_mode = bool(getattr(args, "s3", False))
    s3_creds: dict[str, tuple[str, str]] = {}
    if s3_mode:
        # deterministic per-tenant credentials, minted BEFORE the daemons
        # boot so the objectnode's QoS plane can be told the tenant set up
        # front — random create-time keys would all fold into the 'other'
        # label and per-tenant shaping/SLOs could never engage
        s3_creds = {t: (f"cap-ak-{t}", f"cap-sk-{t}")
                    for t in (f"t{i}" for i in range(args.tenants))}
        env.setdefault("CFS_QOS_TENANTS",
                       ",".join(ak for ak, _ in s3_creds.values()))
    cluster = ProcCluster(root, masters=args.masters,
                          metanodes=args.metanodes, datanodes=args.datanodes,
                          blobstore=True, objectnode=s3_mode, env=env,
                          master_extra=master_extra or None)
    collector = spread = workload = None
    try:
        mc = cluster.client_master()
        mc.create_volume("cap_cold", cold=True)
        hot_vol = None
        if args.datanodes >= 3:
            mc.create_volume("cap_hot", cold=False,
                             dp_count=max(3, args.datanodes))
            hot_vol = "cap_hot"
        targets = [cluster.access_addr] + cluster.stats_addrs()
        console = cluster.spawn_console(metrics_addrs=targets)
        # scenario shaping: pure plan-side skew, so the A/B phases see the
        # identical injected stress (the determinism contract holds — the
        # scenario only changes plan_ops arguments)
        zipf_s, ramp, storm = args.zipf_s, args.ramp, None
        if scenario == "hotspot":
            zipf_s, ramp = max(zipf_s, 3.0), "spike"
        elif scenario == "tenant-storm":
            storm = "t0"
        plan = plan_ops(args.seed, args.tenants, args.duration, args.rate,
                        zipf_s, keys_per_tenant=args.keys,
                        ramp=ramp, hot=hot_vol is not None, storm=storm)
        driver = RemoteDriver(cluster.master_addrs, [cluster.access_addr],
                              "cap_cold", hot_volume=hot_vol)
        if s3_mode:
            # the tenant mix lands on the S3 gateway instead of the SDK
            # access client: per-tenant master users (the deterministic
            # credentials the daemon env already declares), per-tenant
            # buckets, sigv4 on every blob verb — the surface the
            # CFS_QOS_* plane (armed via --daemon-env) shapes. Meta/hot
            # verbs still ride the SDK driver underneath.
            for t in plan["tenants"]:
                ak, sk = s3_creds[t]
                mc.create_user(f"cap-{t}", ak=ak, sk=sk)
            driver = S3Driver(cluster.s3_addr, s3_creds, inner=driver)
            driver.ensure_buckets()
        ctl = None
        if autopilot:
            from chubaofs_tpu import autopilot as ap

            ctl = ap.Autopilot(bindings=ap.default_bindings(), enabled=True)
            for act in ap.client_actuators(mc):
                ctl.register(act)
        collector = Collector(out_path, console=console,
                              interval=args.interval, autopilot=ctl)
        spread = SpreadMonitor(mc)
        collector.start()
        spread.start()
        workload = Workload(driver, plan, seed=args.seed,
                            workers=args.workers)
        killer = None
        if scenario == "node-kill" and args.datanodes >= 3:
            # SIGKILL a replica-bearing datanode mid-run: the repair plane
            # (and the autopilot, when armed) must absorb it — with <3
            # datanodes there is no replicated volume to survive the loss
            victim = f"datanode{args.datanodes - 1}"
            killer = threading.Timer(max(1.0, args.duration * 0.4),
                                     lambda: cluster.kill(victim))
            killer.daemon = True
            killer.start()
        ledger = workload.run()
        if killer is not None:
            killer.cancel()
        time.sleep(max(2 * args.interval, 1.0))  # tail windows land
        spread.stop()
        collector.stop()
        out = {"rebalance": rebalance, "report": out_path,
               **collector.verdict(), **ledger,
               "spread": spread.summary()}
        if ctl is not None:
            ctl.tick()  # settle gates that expired after the last poll
            st = ctl.status()
            by: dict[str, int] = {}
            for d in st["decisions"]:
                by[d["decision"]] = by.get(d["decision"], 0) + 1
            out["autopilot"] = {"enabled": True, "decisions": by,
                                "actions": by.get("executed", 0),
                                "rolled_back": by.get("rolled_back", 0),
                                "budget": st["budget"]}
        if ledger["corruptions"]:
            out["verdict"] = FAILING
            out["flipped"] = {**out.get("flipped", {}),
                              "workload": ["data-loss"]}
        if out["verdict"] == FAILING:
            # gate flipped: collect the cross-daemon incident bundle NOW,
            # while the cluster (and its rings) is still alive — the
            # failure report prints the path. Best-effort: a collection
            # error must never mask the verdict itself.
            try:
                from chubaofs_tpu.tools.cfsstat import scrape

                incident = json.loads(
                    scrape(console, "/api/incident?trigger=capacity_gate",
                           timeout=60.0))
                out["incident_bundle"] = incident.get("dir")
            except Exception:
                out["incident_bundle"] = None
        return out
    finally:
        for th in (collector, spread):
            if th is not None and th.is_alive():
                th.stop()
        if workload is not None:
            workload.close()
        cluster.close()


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="cfs-capacity", description=__doc__)
    p.add_argument("--seed", type=int, default=env_int("CFS_CAP_SEED", 0))
    p.add_argument("--tenants", type=int,
                   default=env_int("CFS_CAP_TENANTS", 4))
    p.add_argument("--zipf-s", type=float,
                   default=env_float("CFS_CAP_ZIPF_S", 1.2))
    p.add_argument("--ramp", default=os.environ.get("CFS_CAP_RAMP", "diurnal"),
                   choices=("diurnal", "flat", "spike"))
    p.add_argument("--duration", type=float, default=30.0,
                   help="workload length (s)")
    p.add_argument("--rate", type=float, default=40.0,
                   help="peak arrival rate (ops/s, open loop)")
    p.add_argument("--keys", type=int, default=64,
                   help="keyspace size per tenant (zipf ranks)")
    p.add_argument("--workers", type=int, default=8)
    p.add_argument("--interval", type=float, default=1.0,
                   help="collector poll period (s) — also the burn-window "
                        "snapshot cadence on the polled daemons")
    p.add_argument("--out", default="", help="capacity report JSONL path "
                   "(default <root>/capacity.jsonl)")
    p.add_argument("--root", default="", help="cluster state dir")
    p.add_argument("--masters", type=int, default=1)
    p.add_argument("--metanodes", type=int, default=3)
    p.add_argument("--datanodes", type=int, default=0,
                   help=">=3 adds a hot volume + hot IO to the blends")
    p.add_argument("--failpoints", default="",
                   help="CFS_FAILPOINTS spec injected into every daemon "
                        "(e.g. 'blobnode.put_shard=delay(0.08)')")
    p.add_argument("--daemon-env", action="append", default=[],
                   metavar="K=V", help="extra env for daemons (repeatable; "
                   "e.g. CFS_SLO_PUT_P99_MS=20)")
    p.add_argument("--cache-mb", type=int,
                   default=env_int("CFS_CACHE_MB", 0),
                   help="arm the blobstore daemon's tiered read cache with "
                        "this memory budget (MiB); 0 = cold EC path only")
    p.add_argument("--s3", action="store_true",
                   help="drive the tenant mix at the objectnode S3 surface "
                        "(per-tenant users + buckets + sigv4) instead of "
                        "the SDK access client; combine with --daemon-env "
                        "CFS_QOS_*=... to shape it")
    p.add_argument("--rebalance", action="store_true",
                   help="arm the master's hot-volume spreading sweep")
    p.add_argument("--rebalance-secs", type=float, default=2.0)
    p.add_argument("--ab-rebalance", action="store_true",
                   help="run the same seeded scenario twice (rebalance "
                        "off, then on) and report both spreads")
    p.add_argument("--autopilot", action="store_true",
                   help="arm the console-fed autopilot: firing alerts "
                        "drive master actuators through the declarative "
                        "bindings, gated by budget/cooldown/flap damping")
    p.add_argument("--ab-autopilot", action="store_true",
                   help="run the same seeded scenario twice (autopilot "
                        "off, then on); only the ON phase gates the exit "
                        "code — the OFF phase is the control arm")
    p.add_argument("--scenario", default="none",
                   choices=("none", "hotspot", "tenant-storm", "node-kill"),
                   help="canned stress: zipf hotspot under a spike ramp, "
                        "one tenant soaking 60%% of arrivals, or a "
                        "mid-run datanode SIGKILL (needs --datanodes>=3)")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)

    import shutil
    import tempfile

    root = args.root or tempfile.mkdtemp(prefix="cfscap")
    try:
        if args.ab_rebalance:
            res_off = run_capacity(
                args, rebalance=False, root=os.path.join(root, "off"),
                out_path=args.out or os.path.join(root, "capacity-off.jsonl"))
            res_on = run_capacity(
                args, rebalance=True, root=os.path.join(root, "on"),
                out_path=(args.out + ".on" if args.out
                          else os.path.join(root, "capacity-on.jsonl")))
            result = {"metric": "capacity_ab", "seed": args.seed,
                      "off": res_off, "on": res_on,
                      "spread_cv_off": res_off["spread"]["cv"],
                      "spread_cv_on": res_on["spread"]["cv"]}
            failing = (res_off["verdict"] == FAILING
                       or res_on["verdict"] == FAILING)
        elif args.ab_autopilot:
            res_off = run_capacity(
                args, rebalance=args.rebalance, autopilot=False,
                root=os.path.join(root, "off"),
                out_path=args.out or os.path.join(root, "capacity-off.jsonl"))
            res_on = run_capacity(
                args, rebalance=args.rebalance, autopilot=True,
                root=os.path.join(root, "on"),
                out_path=(args.out + ".on" if args.out
                          else os.path.join(root, "capacity-on.jsonl")))
            result = {"metric": "capacity_ab_autopilot", "seed": args.seed,
                      "scenario": args.scenario,
                      "off": res_off, "on": res_on,
                      "verdict_off": res_off["verdict"],
                      "verdict_on": res_on["verdict"],
                      "actions_on": (res_on.get("autopilot") or {})
                      .get("actions", 0)}
            # the control arm is EXPECTED to degrade under a scenario —
            # only the closed-loop arm gates the exit code
            failing = res_on["verdict"] == FAILING
        else:
            res = run_capacity(
                args, rebalance=args.rebalance, root=root,
                out_path=args.out or os.path.join(root, "capacity.jsonl"))
            result = {"metric": "capacity_verdict", "seed": args.seed, **res}
            failing = res["verdict"] == FAILING
    finally:
        if not args.root:
            shutil.rmtree(root, ignore_errors=True)

    print(json.dumps(result) if args.json
          else json.dumps(result, indent=2))
    if failing:
        flipped = result.get("flipped") or {
            **result.get("off", {}).get("flipped", {}),
            **result.get("on", {}).get("flipped", {})}
        alerts = result.get("alerts_fired") or {
            **result.get("off", {}).get("alerts_fired", {}),
            **result.get("on", {}).get("alerts_fired", {})}
        bundle = (result.get("incident_bundle")
                  or result.get("off", {}).get("incident_bundle")
                  or result.get("on", {}).get("incident_bundle"))
        print(f"CAPACITY GATE FAILED: {json.dumps(flipped)}"
              f" alerts={json.dumps(alerts)}"
              + (f" incident_bundle={bundle} (cfs-doctor inspect)"
                 if bundle else ""),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
