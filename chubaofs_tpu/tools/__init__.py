"""Operator tools: fsck, authtool, fdstore, preload (fsck/ authtool/
fdstore/ preload/ analogs)."""
