"""A/B harness: fused (auto-pipelined) vs manual-DMA double-buffered kernel.

Runs the bench methodology (slope timing, median-of-passes, HBM floor) over
the BASELINE configs for BOTH kernel lowerings and prints one JSON line per
(config, kernel) plus a final verdict line. Used to decide whether
CFS_GF_PIPELINED should become the default (PERF.md headroom #1) — the
answer is chip-empirical, so the tool exists instead of a guess.

    python -m chubaofs_tpu.tools.kernel_ab [--tile-sweep]
"""

from __future__ import annotations

import json
import sys

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="cfs-kernel-ab")
    p.add_argument("--tile-sweep", action="store_true",
                   help="also sweep pipelined tile sizes on EC(12,4)")
    p.add_argument("--batch", type=int, default=16)
    args = p.parse_args(argv)

    # bench.py's watchdog probe, then its timing machinery
    sys.path.insert(0, "/root/repo")
    from bench import _resolve_device, hbm_floor, stage_grouped, throughput

    import jax

    from chubaofs_tpu.ops import pallas_gf_pipe, rs

    dev = _resolve_device()
    log(f"device={dev}")
    rng = np.random.default_rng(0)
    MiB = 1 << 20

    configs = [
        ("ec4p2_1mib", 4, 2, 1 * MiB, 64),
        ("ec6p3_4mib", 6, 3, 4 * MiB, 24),
        ("ec12p4_8mib", 12, 4, 8 * MiB, args.batch),
    ]
    results: dict[str, dict[str, float]] = {}
    for name, n, m, stripe, batch in configs:
        k = -(-stripe // n // 128) * 128
        kernel = rs.get_kernel(n, m)
        host = rng.integers(0, 256, (batch, n, k), dtype=np.uint8)
        mat_s, data = stage_grouped(dev, host, kernel.parity_bits)
        floor = hbm_floor(batch * (n + m) * k, dev)
        res: dict[str, float] = {}

        from chubaofs_tpu.ops import pallas_gf

        per = throughput(
            jax.jit(lambda s: pallas_gf.gf_matmul_bytes_fused(mat_s, s)),
            (data,), floor=floor)
        res["fused_gbps"] = round(batch * n * k / per / 1e9, 2)
        log(f"{name}: fused {res['fused_gbps']} GB/s")

        for label, static in (("pipelined", False), ("pipelined_static", True)):
            try:
                per = throughput(
                    jax.jit(lambda s, st=static:
                            pallas_gf_pipe.gf_matmul_bytes_pipelined(
                                mat_s, s, static_slots=st)),
                    (data,), floor=floor)
                res[f"{label}_gbps"] = round(batch * n * k / per / 1e9, 2)
                log(f"{name}: {label} {res[f'{label}_gbps']} GB/s")
                if not static:
                    break  # dynamic variant compiled: static is redundant
            except Exception as e:  # Mosaic rejection is a RESULT, not a crash
                res[f"{label}_error"] = str(e)[-400:]
                log(f"{name}: {label} FAILED: {str(e)[-300:]}")
        results[name] = res
        print(json.dumps({"config": name, **res}), flush=True)

    ec12 = results.get("ec12p4_8mib", {})
    # the sweep uses whichever slot strategy actually compiled
    sweep_static = "pipelined_gbps" not in ec12
    if args.tile_sweep and ("pipelined_gbps" in ec12
                            or "pipelined_static_gbps" in ec12):
        name, n, m, stripe, batch = configs[-1]
        k = -(-stripe // n // 128) * 128
        kernel = rs.get_kernel(n, m)
        host = rng.integers(0, 256, (batch, n, k), dtype=np.uint8)
        mat_s, data = stage_grouped(dev, host, kernel.parity_bits)
        floor = hbm_floor(batch * (n + m) * k, dev)
        for kt in (2048, 4096, 7424, 14848, 29696):
            try:
                per = throughput(
                    jax.jit(lambda s, kt=kt:
                            pallas_gf_pipe.gf_matmul_bytes_pipelined(
                                mat_s, s, tile_k=kt,
                                static_slots=sweep_static)),
                    (data,), floor=floor)
                gbps = round(batch * n * k / per / 1e9, 2)
            except Exception as e:
                gbps = f"ERR {str(e)[-120:]}"
            print(json.dumps({"config": "ec12p4_tile_sweep", "tile_k": kt,
                              "gbps": gbps}), flush=True)

    def best(r):
        cands = [("fused", r["fused_gbps"]),
                 ("pipelined", r.get("pipelined_gbps", 0)),
                 ("pipelined_static", r.get("pipelined_static_gbps", 0))]
        return max(cands, key=lambda c: c[1])[0]

    # verdict names the exact variant: production selects it with
    # CFS_GF_PIPELINED=1 (dynamic) or CFS_GF_PIPELINED=static
    winner = {name: best(r) for name, r in results.items()}
    print(json.dumps({"verdict": winner}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
