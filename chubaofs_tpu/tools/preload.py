"""preload — warm the node-local block cache with cold-tier data.

Reference counterpart: preload/ (865 LoC: walks a cold volume's subtree and
pulls data through the cache tier ahead of a training job's reads). Kept:
subtree walk with concurrency, read-through the bcache so warmed extents
serve later reads locally, a byte/file budget, and a summary report.
"""

from __future__ import annotations

import concurrent.futures as futures
from dataclasses import dataclass

from chubaofs_tpu.sdk.fs import FsClient, FsError


@dataclass
class PreloadStats:
    files: int = 0
    bytes: int = 0
    errors: int = 0

    def summary(self) -> str:
        return (f"preloaded {self.files} files / {self.bytes} bytes"
                f" ({self.errors} errors)")


class Preloader:
    def __init__(self, fs: FsClient, workers: int = 8,
                 max_bytes: int | None = None, chunk: int = 4 << 20):
        """fs should carry a bcache for the warmth to persist locally; without
        one this still validates readability end-to-end."""
        self.fs = fs
        self.workers = workers
        self.max_bytes = max_bytes
        self.chunk = chunk

    def _walk(self, path: str):
        st = self.fs.stat(path)
        if not st["is_dir"]:
            yield path, st["size"]
            return
        stack = [path.rstrip("/") or "/"]
        while stack:
            d = stack.pop()
            for name in self.fs.readdir(d):
                child = f"{d.rstrip('/')}/{name}"
                try:
                    cst = self.fs.stat(child)
                except FsError:
                    continue
                if cst["is_dir"]:
                    stack.append(child)
                else:
                    yield child, cst["size"]

    def _pull(self, path: str, size: int) -> int:
        pulled = 0
        for off in range(0, size, self.chunk):
            n = min(self.chunk, size - off)
            data = self.fs.read_file(path, off, n)
            pulled += len(data)
        return pulled

    def run(self, path: str = "/") -> PreloadStats:
        stats = PreloadStats()
        budget = self.max_bytes
        with futures.ThreadPoolExecutor(max_workers=self.workers) as pool:
            pending = {}
            for fpath, size in self._walk(path):
                if budget is not None:
                    if budget <= 0:
                        break
                    budget -= size
                pending[pool.submit(self._pull, fpath, size)] = fpath
            for fut in futures.as_completed(pending):
                try:
                    stats.bytes += fut.result()
                    stats.files += 1
                except (FsError, OSError):
                    stats.errors += 1
        return stats


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="cfs-preload",
                                description="warm the local block cache")
    p.add_argument("--addr", action="append", required=True)
    p.add_argument("--volume", required=True)
    p.add_argument("--access", action="append", default=None,
                   help="blobstore access gateway (cold volumes)")
    p.add_argument("--path", default="/")
    p.add_argument("--workers", type=int, default=8)
    p.add_argument("--max-bytes", type=int, default=None)
    args = p.parse_args(argv)

    from chubaofs_tpu.sdk.cluster import RemoteCluster

    fs = RemoteCluster(args.addr, access_addrs=args.access).client(args.volume)
    stats = Preloader(fs, workers=args.workers,
                      max_bytes=args.max_bytes).run(args.path)
    print(stats.summary())
    return 0 if stats.errors == 0 else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
