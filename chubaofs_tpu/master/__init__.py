"""Master — cluster resource manager (reference master/ equivalent)."""

from chubaofs_tpu.master.master import Master, MasterSM, VolumeView, MetaPartitionView

__all__ = ["Master", "MasterSM", "VolumeView", "MetaPartitionView"]
