"""Master — the cluster's resource manager, one raft group over all masters.

Reference counterpart: master/ (Server.Start server.go:137-175, single raft
group ID 1, MetadataFsm, Cluster.scheduleTask's 16 background loops
cluster.go:329-3587, IDAllocator id_allocator.go:176-272, vol/meta-partition
management vol.go + meta_partition.go). Kept:

  * every mutation is a raft-applied op on MasterSM (the MetadataFsm analog);
  * volumes own a list of meta partitions, each an inode range [start, end)
    replicated across 3 metanodes; the last partition is unbounded and is SPLIT
    when its cursor approaches the range end (meta_partition splitting);
  * node registry with heartbeats; background check loops are explicit tick
    methods (check_meta_partitions) the deployment pumps, like scheduleTask.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from chubaofs_tpu.raft.server import MultiRaft, StateMachine
from chubaofs_tpu.utils.locks import SanitizedLock

MASTER_GROUP = 1
META_RANGE_STEP = 1 << 24  # inos per partition before splitting
SPLIT_HEADROOM = 1 << 20  # split when cursor is this close to the end
INF = 1 << 63
NODESET_CAPACITY = 18  # nodes per nodeset (master/topology.go default)


class MasterError(Exception):
    pass


@dataclass
class NodeInfo:
    node_id: int
    kind: str  # "meta" | "data"
    addr: str = ""
    raft_addr: str = ""  # TCP raft transport address (daemon mode)
    last_heartbeat: float = 0.0
    partition_count: int = 0
    cursors: dict[int, int] = field(default_factory=dict)  # pid -> cursor (meta)
    status: str = "active"  # active | decommissioned
    zone: str = ""  # fault domain (master/topology.go:43 zones)
    nodeset: int = 0  # zone-local nodeset index (bounded failure groups)
    total_space: int = 0  # bytes, node-reported via heartbeat (statinfo)
    used_space: int = 0
    # pid -> ops served in the node's last heartbeat window (datanode/
    # metanode take_loads() delta) — the hot-volume rebalancer's and the
    # meta splitter's accounting feed
    loads: dict[int, float] = field(default_factory=dict)
    # pid -> replicated split_info for meta partitions FROZEN mid-split on
    # this node (metanode split_reports()) — the resume sweep's feed: a
    # split whose orchestrator died finishes from the partition's own state
    splits: dict[int, dict] = field(default_factory=dict)

    @property
    def schedulable(self) -> bool:
        return getattr(self, "status", "active") == "active"


@dataclass
class MetaPartitionView:
    partition_id: int
    start: int
    end: int  # exclusive; INF for the tail partition
    peers: list[int] = field(default_factory=list)
    leader: int | None = None
    # GENESIS range — the range this partition's raft group was CREATED
    # with, before any split shrank the live view. Every re-create of the
    # partition on a node (respawn re-host, migration replica, replica-count
    # heal) MUST use this range, not start/end: a recovering SM replays its
    # WAL from index 1, and ops recorded before an in-log range change
    # (freeze_range/complete_split/set_range_end) were applied under the
    # genesis range — an SM born with the post-split view range would
    # silently refuse them (OutOfRange/WrongPartition no-ops during replay)
    # and lose committed entries. 0 = derive from start/end at construction.
    start0: int = 0
    end0: int = 0

    def __post_init__(self):
        # creation sites construct views with start/end = the creation
        # range, so capturing it here IS the genesis; restore passes the
        # persisted values explicitly (old snapshots: re-derived — those
        # partitions predate mid-range splits, where view == genesis)
        if not self.start0:
            self.start0 = self.start
        if not self.end0:
            self.end0 = self.end


@dataclass
class DataPartitionView:
    """One replicated data partition (master/data_partition.go analog):
    peers are datanode ids (raft membership), hosts their repl addresses;
    hosts[0] is the chain-replication leader."""

    partition_id: int
    peers: list[int] = field(default_factory=list)
    hosts: list[str] = field(default_factory=list)
    status: str = "rw"  # rw | ro | unavail


@dataclass
class VolumeView:
    name: str
    vol_id: int
    owner: str = ""
    capacity: int = 0
    cold: bool = False  # cold volumes store data in the blobstore (EC tier)
    # reads may hit any replica (relaxed consistency — a follower can trail
    # the leader's latest random overwrite); ref proto/mount_options.go
    # FollowerRead + sdk/data/stream follower-read
    follower_read: bool = False
    # per-volume client QoS (MB/s, 0 = unlimited): the master owns the
    # limits and every client reads them from its volume view, so an
    # operator change flows master -> clients on the next view refresh
    # (ref master/limiter.go qos assignment flowing to clients)
    qos_read_mbps: int = 0
    qos_write_mbps: int = 0
    meta_partitions: list[MetaPartitionView] = field(default_factory=list)
    data_partitions: list[DataPartitionView] = field(default_factory=list)


@dataclass
class UserInfo:
    """master/user.go analog: an identity with S3 credentials + vol policy."""

    user_id: str
    access_key: str
    secret_key: str
    user_type: str = "normal"  # root | admin | normal
    own_vols: list[str] = field(default_factory=list)
    # vol -> granted actions, e.g. ["perm:readonly"] / ["perm:writable"]
    authorized_vols: dict[str, list[str]] = field(default_factory=dict)


class MasterSM(StateMachine):
    """Replicated master state (MetadataFsm + Cluster state analog)."""

    def __init__(self):
        self.nodes: dict[int, NodeInfo] = {}
        self.volumes: dict[str, VolumeView] = {}
        self.users: dict[str, UserInfo] = {}  # user_id -> info
        self.ak_index: dict[str, str] = {}  # access_key -> user_id
        # fault domains group zones (master/topology.go:43 + vol.go domain
        # placement): any assignment turns domain mode ON; unassigned zones
        # act as their own singleton domains
        self.zone_domains: dict[str, str] = {}
        self.next_id = 100  # shared id space for volumes + partitions

    # raft hooks -------------------------------------------------------------

    def apply(self, data, index: int):
        op, args = data
        try:
            return ("ok", getattr(self, "_op_" + op)(**args))
        except MasterError as e:
            return ("err", str(e))

    def snapshot(self) -> bytes:
        """Sectioned CRC-framed snapshot (raft.snapcodec) — the reference
        streams master state as typed RocksDB records (metadata_fsm), never
        as one opaque language-native blob."""
        from dataclasses import asdict

        from chubaofs_tpu.raft import snapcodec

        w = snapcodec.SnapshotWriter()
        w.add("meta", {"next_id": self.next_id,
                       "zone_domains": self.zone_domains})
        w.add_batched("nodes", (asdict(n) for n in self.nodes.values()))
        w.add_batched("volumes", (asdict(v) for v in self.volumes.values()))
        w.add_batched("users", (asdict(u) for u in self.users.values()))
        return w.getvalue()

    def restore(self, payload: bytes) -> None:
        from chubaofs_tpu.raft import snapcodec

        self.nodes, self.volumes, self.users, self.ak_index = {}, {}, {}, {}
        self.zone_domains = {}

        def load_nodes(batch):
            for d in batch:
                d["cursors"] = {int(k): v for k, v in d["cursors"].items()}
                # .get: snapshots from before load accounting existed
                d["loads"] = {int(k): float(v)
                              for k, v in d.get("loads", {}).items()}
                d["splits"] = {int(k): dict(v)
                               for k, v in d.get("splits", {}).items()}
                n = NodeInfo(**d)
                self.nodes[n.node_id] = n

        def load_volumes(batch):
            for d in batch:
                v = VolumeView(
                    name=d["name"], vol_id=d["vol_id"], owner=d["owner"],
                    capacity=d["capacity"], cold=d["cold"],
                    # .get: snapshots from before each option existed
                    follower_read=d.get("follower_read", False),
                    qos_read_mbps=d.get("qos_read_mbps", 0),
                    qos_write_mbps=d.get("qos_write_mbps", 0),
                    meta_partitions=[MetaPartitionView(**m)
                                     for m in d["meta_partitions"]],
                    data_partitions=[DataPartitionView(**p)
                                     for p in d["data_partitions"]],
                )
                self.volumes[v.name] = v

        def load_users(batch):
            for d in batch:
                u = UserInfo(**d)
                self.users[u.user_id] = u
                self.ak_index[u.access_key] = u.user_id

        def load_meta(m):
            self.next_id = m["next_id"]
            # older snapshots predate fault domains
            self.zone_domains = dict(m.get("zone_domains", {}))

        snapcodec.restore_sections(payload, {
            "meta": load_meta,
            "nodes": load_nodes,
            "volumes": load_volumes,
            "users": load_users,
        })

    # ops ---------------------------------------------------------------------

    def _op_alloc_id(self):
        self.next_id += 1
        return self.next_id

    def _op_register_node(self, node_id: int, kind: str, addr: str,
                          raft_addr: str = "", now: float = 0.0,
                          zone: str = ""):
        # `now` is stamped by the PROPOSER: calling time.time() inside apply
        # would make replicas and WAL replay record different values, so a
        # restarted master could trust dead nodes as freshly heartbeaten
        if node_id not in self.nodes:  # racelint: _op_* appliers are serialized by the raft drain pump
            self.nodes[node_id] = NodeInfo(
                node_id, kind, addr, zone=zone,
                nodeset=self._assign_nodeset(kind, zone),
            )
        n = self.nodes[node_id]
        if n.kind != kind:  # operator config error: one id, two roles
            raise MasterError(
                f"node id {node_id} already registered as {n.kind!r}")
        if addr:  # re-registration after restart carries the new address
            n.addr = addr
        if raft_addr:
            n.raft_addr = raft_addr
        if zone and zone != n.zone:
            # late-reported or operator-changed zone: re-home the nodeset too,
            # or the capacity bound would silently break in the new zone
            n.nodeset = self._assign_nodeset(kind, zone)
            n.zone = zone
        n.last_heartbeat = max(n.last_heartbeat, now)
        return node_id

    def _op_set_zone_domain(self, zone: str, domain: str):
        """Assign a zone to a fault domain (master/topology.go:43). An empty
        domain clears the assignment; clearing the last one turns domain
        mode off."""
        if domain:
            self.zone_domains[zone] = domain
        else:
            self.zone_domains.pop(zone, None)
        return dict(self.zone_domains)

    def _assign_nodeset(self, kind: str, zone: str) -> int:
        """Smallest zone-local nodeset with spare capacity — deterministic over
        replicated state, so every replica assigns identically
        (master/topology.go nodeset grouping, capacity-bounded)."""
        counts: dict[int, int] = {}
        for n in self.nodes.values():
            if n.kind == kind and n.zone == zone:
                counts[n.nodeset] = counts.get(n.nodeset, 0) + 1
        ns = 0
        while counts.get(ns, 0) >= NODESET_CAPACITY:
            ns += 1
        return ns

    def _op_heartbeat(self, node_id: int, partition_count: int = 0,
                      cursors: dict | None = None, now: float = 0.0,
                      total_space: int | None = None,
                      used_space: int | None = None,
                      loads: dict | None = None,
                      splits: dict | None = None):
        n = self.nodes.get(node_id)
        if n is None:
            raise MasterError(f"unknown node {node_id}")
        n.last_heartbeat = max(n.last_heartbeat, now)
        if n.status == "inactive":
            n.status = "active"  # liveness recovery; decommissioned stays out
        n.partition_count = partition_count
        # space report (statinfo source, master/cluster.go UpdateStatInfo):
        # None = no report, leaves state alone
        if total_space is not None:
            n.total_space = int(total_space)
        if used_space is not None:
            n.used_space = int(used_space)
        # a dict REPLACES the cursor set (even when empty — a restarted node
        # reports no partitions, and the ensure sweep must see that to re-send
        # create tasks); None means "no report" and leaves state alone
        if cursors is not None:
            n.cursors = {int(k): v for k, v in cursors.items()}
        # per-partition op-load window (same replace-vs-no-report contract)
        if loads is not None:
            n.loads = {int(k): float(v) for k, v in loads.items()}
        # frozen mid-split partitions this node hosts (resume sweep feed)
        if splits is not None:
            n.splits = {int(k): dict(v) for k, v in splits.items()}
        return None

    def _op_create_volume(self, name: str, owner: str, capacity: int, cold: bool,
                          vol_id: int, partition_id: int, peers: list[int],
                          follower_read: bool = False):
        if name in self.volumes:
            raise MasterError(f"volume {name!r} exists")
        vol = VolumeView(name=name, vol_id=vol_id, owner=owner, capacity=capacity,
                         cold=cold, follower_read=follower_read)
        vol.meta_partitions.append(
            MetaPartitionView(partition_id, start=1, end=INF, peers=peers)
        )
        self.volumes[name] = vol
        for p in peers:
            if p in self.nodes:
                self.nodes[p].partition_count += 1
        return vol

    def _op_update_volume(self, name: str, capacity: int | None = None,
                          follower_read: bool | None = None,
                          qos_read_mbps: int | None = None,
                          qos_write_mbps: int | None = None):
        """Vol expand/shrink + option updates (master/vol.go updateVol).
        Capacity is an admin quota: usage enforcement stays with the
        write-time quota charges, so shrinking below current usage stops
        NEW growth rather than deleting data (the reference's semantics)."""
        vol = self.volumes.get(name)
        if vol is None:
            raise MasterError(f"unknown volume {name!r}")
        if capacity is not None:
            if capacity <= 0:
                raise MasterError("capacity must be positive")
            vol.capacity = int(capacity)
        if follower_read is not None:
            vol.follower_read = bool(follower_read)
        if qos_read_mbps is not None:
            vol.qos_read_mbps = max(0, int(qos_read_mbps))
        if qos_write_mbps is not None:
            vol.qos_write_mbps = max(0, int(qos_write_mbps))
        return vol

    def _op_remove_node(self, node_id: int):
        """Prune a registry entry (stale-node pruner); refuses while any
        partition still lists the node."""
        n = self.nodes.get(node_id)
        if n is None:
            return None
        for vol in self.volumes.values():
            for mp in vol.meta_partitions:
                if node_id in mp.peers:
                    raise MasterError(f"node {node_id} still hosts mp")
            for dp in vol.data_partitions:
                if node_id in dp.peers:
                    raise MasterError(f"node {node_id} still hosts dp")
        del self.nodes[node_id]
        return node_id

    def _op_split_partition(self, vol_name: str, partition_id: int, split_at: int,
                            new_partition_id: int, peers: list[int]):
        vol = self.volumes.get(vol_name)
        if vol is None:
            raise MasterError(f"unknown volume {vol_name!r}")
        tail = vol.meta_partitions[-1]
        if tail.partition_id != partition_id:
            raise MasterError("only the tail partition splits")
        tail.end = split_at
        vol.meta_partitions.append(
            MetaPartitionView(new_partition_id, start=split_at, end=INF, peers=peers)
        )
        return vol.meta_partitions[-1]

    def _op_split_partition_mid(self, vol_name: str, partition_id: int,
                                split_at: int, new_partition_id: int,
                                peers: list[int]):
        """THE atomic view swap of a mid-range load split (ISSUE 15): in one
        master-raft commit the old partition's range shrinks to
        [start, split_at) and the sibling enters the view owning
        [split_at, old_end) — no inode is ever owned by zero or two
        partitions in the authoritative view. Idempotent: a resumed
        orchestrator re-proposing an already-swapped split no-ops."""
        vol = self.volumes.get(vol_name)
        if vol is None:
            raise MasterError(f"unknown volume {vol_name!r}")
        for mp in vol.meta_partitions:
            if mp.partition_id == new_partition_id:
                return mp  # already swapped (resume replay)
        for i, mp in enumerate(vol.meta_partitions):
            if mp.partition_id != partition_id:
                continue
            if not (mp.start < split_at < mp.end):
                raise MasterError(
                    f"split_at {split_at} outside ({mp.start}, {mp.end})")
            new_mp = MetaPartitionView(new_partition_id, start=split_at,
                                       end=mp.end, peers=list(peers))
            mp.end = split_at
            # keep meta_partitions sorted by start: routing (and the tail
            # convention meta_partitions[-1]) depend on range order
            vol.meta_partitions.insert(i + 1, new_mp)
            for p in peers:
                if p in self.nodes:
                    self.nodes[p].partition_count += 1
            return new_mp
        raise MasterError(f"unknown partition {partition_id}")

    def _op_set_partition_leader(self, vol_name: str, partition_id: int, leader: int | None):
        vol = self.volumes.get(vol_name)
        if vol is None:
            raise MasterError(f"unknown volume {vol_name!r}")
        for mp in vol.meta_partitions:
            if mp.partition_id == partition_id:
                mp.leader = leader
                return None
        raise MasterError(f"unknown partition {partition_id}")

    def _op_create_data_partition(self, vol_name: str, partition_id: int,
                                  peers: list[int], hosts: list[str]):
        vol = self.volumes.get(vol_name)
        if vol is None:
            raise MasterError(f"unknown volume {vol_name!r}")
        vol.data_partitions.append(
            DataPartitionView(partition_id, peers=peers, hosts=hosts))
        for p in peers:
            if p in self.nodes:
                self.nodes[p].partition_count += 1
        return vol.data_partitions[-1]

    def _op_update_dp_hosts(self, vol_name: str, partition_id: int, hosts: list[str]):
        vol = self.volumes.get(vol_name)
        if vol is None:
            raise MasterError(f"unknown volume {vol_name!r}")
        for dp in vol.data_partitions:
            if dp.partition_id == partition_id:
                dp.hosts = hosts
                return None
        raise MasterError(f"unknown data partition {partition_id}")

    def _op_set_dp_status(self, vol_name: str, partition_id: int, status: str):
        vol = self.volumes.get(vol_name)
        if vol is None:
            raise MasterError(f"unknown volume {vol_name!r}")
        for dp in vol.data_partitions:
            if dp.partition_id == partition_id:
                dp.status = status
                return None
        raise MasterError(f"unknown data partition {partition_id}")

    def _op_delete_volume(self, name: str):
        vol = self.volumes.pop(name, None)
        if vol is None:
            raise MasterError(f"unknown volume {name!r}")
        for u in self.users.values():
            if name in u.own_vols:
                u.own_vols.remove(name)
            u.authorized_vols.pop(name, None)
        return vol

    # -- decommission bookkeeping (master decommission APIs) -------------------

    def _op_set_node_status(self, node_id: int, status: str):
        n = self.nodes.get(node_id)
        if n is None:
            raise MasterError(f"unknown node {node_id}")
        n.status = status
        return None

    def _op_update_mp_peers(self, vol_name: str, partition_id: int,
                            peers: list[int]):
        vol = self.volumes.get(vol_name)
        if vol is None:
            raise MasterError(f"unknown volume {vol_name!r}")
        for mp in vol.meta_partitions:
            if mp.partition_id == partition_id:
                mp.peers = list(peers)
                return None
        raise MasterError(f"unknown partition {partition_id}")

    def _op_update_dp_members(self, vol_name: str, partition_id: int,
                              peers: list[int], hosts: list[str]):
        vol = self.volumes.get(vol_name)
        if vol is None:
            raise MasterError(f"unknown volume {vol_name!r}")
        for dp in vol.data_partitions:
            if dp.partition_id == partition_id:
                dp.peers = list(peers)
                dp.hosts = list(hosts)
                return None
        raise MasterError(f"unknown data partition {partition_id}")

    # -- user store (master/user.go analog) -----------------------------------

    def _op_create_user(self, user_id: str, access_key: str, secret_key: str,
                        user_type: str = "normal"):
        if user_id in self.users:
            raise MasterError(f"user {user_id!r} exists")
        if access_key in self.ak_index:
            raise MasterError("duplicate access key")
        u = UserInfo(user_id, access_key, secret_key, user_type)
        self.users[user_id] = u
        self.ak_index[access_key] = user_id
        return u

    def _op_delete_user(self, user_id: str):
        u = self.users.get(user_id)
        if u is None:
            raise MasterError(f"unknown user {user_id!r}")
        if u.own_vols:
            raise MasterError(f"user {user_id!r} still owns volumes {u.own_vols}")
        del self.users[user_id]
        self.ak_index.pop(u.access_key, None)
        return None

    def _op_user_own_vol(self, user_id: str, vol_name: str, add: bool):
        u = self.users.get(user_id)
        if u is None:
            raise MasterError(f"unknown user {user_id!r}")
        if add and vol_name not in u.own_vols:
            u.own_vols.append(vol_name)
        if not add and vol_name in u.own_vols:
            u.own_vols.remove(vol_name)
        return u

    def _op_update_user_policy(self, user_id: str, vol_name: str,
                               actions: list[str], grant: bool):
        u = self.users.get(user_id)
        if u is None:
            raise MasterError(f"unknown user {user_id!r}")
        if grant:
            u.authorized_vols[vol_name] = list(actions)
        else:
            u.authorized_vols.pop(vol_name, None)
        return u


class Master:
    """Leader-side service facade over the replicated MasterSM.

    The deployment wires `metanode_hook(partition_id, start, end, peers)` so
    partition creation reaches the metanodes (admin-task analog of
    master/cluster_task.go).
    """

    def __init__(self, raft: MultiRaft, sm: MasterSM):
        import threading

        self.raft = raft
        self.sm = sm
        # one migration at a time: an HTTP client retrying a slow decommission
        # must not start a second concurrent membership-change dance
        self._decomm_lock = threading.Lock()
        self.metanode_hook = None  # (pid, start, end, peers) -> None
        self.datanode_hook = None  # (pid, peers, hosts) -> None
        # decommission plumbing (deployment-wired, like the create hooks):
        # raft_config_hook(kind, pid, action, node_id, peers) proposes a
        # membership change on the partition's raft leader;
        # remove_partition_hook(kind, pid, node_id) drops the group+state on
        # the retired replica
        self.raft_config_hook = None
        self.remove_partition_hook = None
        # metadata-op plumbing for the mid-range split orchestrator
        # (deployment-wired): meta_op_hook(pid, peers, op, args, read=False)
        # runs one metanode op on the partition's leader with retry/hint
        # handling and returns its result
        self.meta_op_hook = None
        # load-split trigger: a meta partition whose heartbeat-window op
        # count reaches this splits at its median live inode. 0 = off (the
        # operator or the capacity harness triggers explicit splits instead).
        # CFS_META_SPLIT_OPS env / metaSplitOps daemon config.
        import os as _os

        try:
            self.meta_split_ops = float(
                _os.environ.get("CFS_META_SPLIT_OPS", "0") or 0)
        except ValueError:
            self.meta_split_ops = 0.0
        # nodes already fully drained by the dead-node sweep; in-memory only
        # (rebuilt by one sweep after a restart), cleared on returning heartbeat.
        # Own micro-lock: heartbeat clears this set on its hot path and must
        # never wait out a migration-length _decomm_lock hold
        self._drained_lock = SanitizedLock(name="master.drained")
        self._dead_drained: set[int] = set()

    def _apply(self, op: str, **args):
        # rides raft group commit: concurrent admin/heartbeat handler threads
        # coalesce into shared WAL-flush + replication rounds on GroupID=1
        res = self.raft.propose(MASTER_GROUP, (op, args)).result(timeout=5)
        if res[0] == "err":
            raise MasterError(res[1])
        return res[1]

    def _apply_batch(self, ops: list[tuple[str, dict]], timeout: float = 5.0) -> list:
        """Propose many master ops as ONE drained raft batch (one WAL flush,
        one replication fan-out); results FIFO, each op failing alone."""
        futs = self.raft.propose_batch(MASTER_GROUP, [(op, args) for op, args in ops])
        out = []
        for fut in futs:
            res = fut.result(timeout=timeout)
            if res[0] == "err":
                raise MasterError(res[1])
            out.append(res[1])
        return out

    @property
    def is_leader(self) -> bool:
        return self.raft.is_leader(MASTER_GROUP)

    # -- node admin -----------------------------------------------------------

    def register_node(self, node_id: int, kind: str, addr: str = "",
                      raft_addr: str = "", zone: str = "") -> None:
        self._apply("register_node", node_id=node_id, kind=kind, addr=addr,
                    raft_addr=raft_addr, now=time.time(), zone=zone)

    def set_zone_domain(self, zone: str, domain: str) -> dict:
        """Assign/clear a zone's fault domain (replicated)."""
        return self._apply("set_zone_domain", zone=zone, domain=domain)

    def topology(self) -> dict:
        """zones -> nodesets -> node ids (master/topology.go view analog)."""
        out: dict[str, dict[int, list[int]]] = {}
        for n in self.sm.nodes.values():
            out.setdefault(n.zone, {}).setdefault(n.nodeset, []).append(n.node_id)
        for zone in out.values():
            for ids in zone.values():
                ids.sort()
        return out

    def heartbeat(self, node_id: int, partition_count: int = 0,
                  cursors: dict | None = None,
                  total_space: int | None = None,
                  used_space: int | None = None,
                  loads: dict | None = None,
                  splits: dict | None = None):
        # a returning node may receive new placements again, so the dead-node
        # sweep must re-examine it if it dies a second time
        with self._drained_lock:
            self._dead_drained.discard(node_id)
        self._apply("heartbeat", node_id=node_id, partition_count=partition_count,
                    cursors=cursors, now=time.time(),
                    total_space=total_space, used_space=used_space,
                    loads=loads, splits=splits)

    def cluster_stat(self) -> dict:
        """Cluster/zone space + health rollup from node heartbeat reports.

        Reference: Cluster.scheduleToUpdateStatInfo (master/cluster.go:335)
        maintains this in a ticker; here the rollup derives on read — the
        node table is small and raft-replicated, so a loop would only add
        staleness."""
        def bucket():
            return {"total_space": 0, "used_space": 0, "nodes": 0, "active": 0}

        def kbucket():  # a rollup with nested per-kind sub-rollups
            return {**bucket(), "data": bucket(), "meta": bucket()}

        zones: dict[str, dict] = {}
        # per-kind rollups, like the reference's separate DataNodeStatInfo /
        # MetaNodeStatInfo (proto/model.go:162): metanode WAL-dir capacity
        # must not inflate storage capacity. The top-level total/used fields
        # remain the MERGED sum (all node kinds) for dashboard backward-compat.
        total = {**kbucket(), "meta_partitions": 0, "data_partitions": 0}
        for n in self.sm.nodes.values():
            z = zones.setdefault(n.zone, kbucket())
            for agg in (z, total, total[n.kind], z[n.kind]):
                agg["total_space"] += n.total_space
                agg["used_space"] += n.used_space
                agg["nodes"] += 1
                agg["active"] += 1 if n.status == "active" else 0
        for vol in self.sm.volumes.values():
            total["meta_partitions"] += len(vol.meta_partitions)
            total["data_partitions"] += len(vol.data_partitions)
        total["volumes"] = len(self.sm.volumes)
        total["zones"] = zones
        return total

    # -- volume admin -----------------------------------------------------------

    def domain_of(self, zone: str) -> str:
        """Fault domain owning a zone; unassigned zones are their own
        singleton domains (reference default-domain behavior)."""
        return self.sm.zone_domains.get(zone, zone)

    def _spread_by_zone(self, cands: list[NodeInfo], count: int,
                        kind: str) -> list[NodeInfo]:
        """Fault-domain- and zone-aware replica spread (master/topology.go
        placement contract + vol.go domain mode): with domain assignments
        present, replicas spread one-per-DOMAIN first — so a whole-domain
        loss (power/network failure of several co-dependent zones) leaves
        count-1 replicas when >= count domains exist — then per zone inside
        each domain; without assignments, domains degenerate to zones and
        the behavior is the plain zone spread. With fewer groups than
        `count`, round-robin so no group holds two replicas before every
        group holds one. (Decommission/dead-node replacements go through
        _pick_addition, which adds survivor-aware zone/domain bias.)"""
        if len(cands) < count:
            raise MasterError(f"need {count} {kind}nodes, have {len(cands)}")
        by_zone: dict[str, list[NodeInfo]] = {}
        for n in sorted(cands, key=lambda n: n.partition_count):
            by_zone.setdefault(n.zone, []).append(n)
        # group zones into domains; inside a domain, zones interleave so the
        # secondary spread (across zones within the picked domain) holds too
        by_domain: dict[str, list[NodeInfo]] = {}
        for zone, ns in by_zone.items():
            by_domain.setdefault(self.domain_of(zone), []).append(ns)
        groups = []
        for zone_lists in by_domain.values():
            zone_lists.sort(key=lambda ns: ns[0].partition_count)
            merged: list[NodeInfo] = []
            rank = 0
            while any(rank < len(ns) for ns in zone_lists):
                for ns in zone_lists:
                    if rank < len(ns):
                        merged.append(ns[rank])
                rank += 1
            groups.append(merged)
        groups.sort(key=lambda ns: ns[0].partition_count)
        picked: list[NodeInfo] = []
        if len(groups) >= count:
            for ns in groups[:count]:
                picked.append(ns[0])
        else:
            rank = 0
            while len(picked) < count:
                advanced = False
                for ns in groups:
                    if rank < len(ns):
                        picked.append(ns[rank])
                        advanced = True
                        if len(picked) == count:
                            break
                if not advanced:
                    raise MasterError(f"need {count} {kind}nodes, have {len(picked)}")
                rank += 1
        return picked

    def _pick_addition(self, kind: str, survivors: list[int],
                       prefer_zone: str | None = None,
                       exclude: set[int] = frozenset()) -> NodeInfo:
        """One extra replica for a partition that keeps `survivors`. With
        `prefer_zone` (a migration victim's zone) still healthy, stay there —
        the replacement preserves the existing spread by construction.
        Otherwise candidates rank by NOT sharing a fault domain with any
        survivor, then not sharing a zone, then emptiest — so whole-domain
        losses re-home (and under-replication heals) into a domain/zone that
        does not already hold a replica (vol.go domain placement on the
        repair path). `exclude` bars extra nodes (the migration VICTIM) from
        candidacy WITHOUT counting them in the spread ranking: the victim's
        domain is exactly where a replica is no longer held."""
        barred = set(survivors) | set(exclude)
        cands = [n for n in self.sm.nodes.values()
                 if n.kind == kind and n.schedulable
                 and n.node_id not in barred]
        if not cands:
            raise MasterError(f"need 1 {kind}node, have 0")
        if prefer_zone is not None:
            in_zone = [n for n in cands if n.zone == prefer_zone]
            if in_zone:
                return min(in_zone, key=lambda n: n.partition_count)
        surv_zones = {self.sm.nodes[p].zone for p in survivors
                      if p in self.sm.nodes}
        surv_doms = {self.domain_of(z) for z in surv_zones}
        return min(cands, key=lambda n: (
            self.domain_of(n.zone) in surv_doms,
            n.zone in surv_zones,
            n.partition_count,
        ))

    def _pick_meta_peers(self, count: int = 3,
                         exclude: set[int] = frozenset()) -> list[int]:
        metas = [n for n in self.sm.nodes.values()
                 if n.kind == "meta" and n.schedulable and n.node_id not in exclude]
        return [n.node_id
                for n in self._spread_by_zone(metas, count, "meta")]

    def _pick_data_peers(self, count: int = 3,
                         exclude: set[int] = frozenset()) -> list[NodeInfo]:
        datas = [n for n in self.sm.nodes.values()
                 if n.kind == "data" and n.schedulable and n.node_id not in exclude]
        return self._spread_by_zone(datas, count, "data")

    def create_volume(self, name: str, owner: str = "", capacity: int = 1 << 40,
                      cold: bool = False, data_partitions: int = 3,
                      follower_read: bool = False) -> VolumeView:
        # both ids in one drained raft batch: one commit round, not two
        vol_id, pid = self._apply_batch([("alloc_id", {}), ("alloc_id", {})])
        peers = self._pick_meta_peers()
        vol = self._apply(
            "create_volume", name=name, owner=owner, capacity=capacity, cold=cold,
            vol_id=vol_id, partition_id=pid, peers=peers,
            follower_read=follower_read,
        )
        if self.metanode_hook:
            self.metanode_hook(pid, 1, INF, peers)
        if not cold:
            for _ in range(data_partitions):
                self.create_data_partition(name)
        return self.sm.volumes[name]

    def create_data_partition(self, vol_name: str) -> DataPartitionView:
        """Place one 3-replica data partition on the emptiest datanodes
        (master/vol.go createDataPartition analog)."""
        dp_id = self._apply("alloc_id")
        nodes = self._pick_data_peers()
        view = self._apply(
            "create_data_partition", vol_name=vol_name, partition_id=dp_id,
            peers=[n.node_id for n in nodes], hosts=[n.addr for n in nodes],
        )
        if self.datanode_hook:
            self.datanode_hook(dp_id, view.peers, view.hosts)
        return view

    def _current_hosts(self, peers: list[int], stored: list[str]) -> list[str]:
        """Resolve replica addresses from the live node registry; datanode
        addresses change across restarts (ephemeral ports in tests)."""
        out = []
        for i, p in enumerate(peers):
            n = self.sm.nodes.get(p)
            out.append(n.addr if n and n.addr else (stored[i] if i < len(stored) else ""))
        return out

    def data_partition_views(self, vol_name: str) -> list[dict]:
        """Client-facing partition table (the ExtentClient refresh feed)."""
        vol = self.get_volume(vol_name)
        return [
            {"pid": dp.partition_id, "peers": list(dp.peers),
             "hosts": self._current_hosts(dp.peers, dp.hosts)}
            for dp in vol.data_partitions if dp.status == "rw"
        ]

    def refresh_dp_hosts(self) -> int:
        """Re-resolve stored dp.hosts from the registry (restart path)."""
        if not self.is_leader:
            return 0
        fixed = 0
        for vol in list(self.sm.volumes.values()):
            for dp in vol.data_partitions:
                hosts = self._current_hosts(dp.peers, dp.hosts)
                if hosts != dp.hosts:
                    self._apply("update_dp_hosts", vol_name=vol.name,
                                partition_id=dp.partition_id, hosts=hosts)
                    fixed += 1
        return fixed

    def get_volume(self, name: str) -> VolumeView:
        vol = self.sm.volumes.get(name)
        if vol is None:
            raise MasterError(f"unknown volume {name!r}")
        return vol

    def delete_volume(self, name: str) -> None:
        self._apply("delete_volume", name=name)

    # -- user admin (master/user.go analog) -----------------------------------

    def create_user(self, user_id: str, user_type: str = "normal",
                    access_key: str | None = None,
                    secret_key: str | None = None) -> UserInfo:
        import secrets
        import string

        alphabet = string.ascii_letters + string.digits
        # caller-supplied credentials are allowed (deterministic keys let
        # an operator declare them in a gateway's CFS_QOS_TENANTS before
        # the user exists); otherwise mint random ones
        ak = access_key or "".join(secrets.choice(alphabet)
                                   for _ in range(16))
        sk = secret_key or "".join(secrets.choice(alphabet)
                                   for _ in range(32))
        self._apply("create_user", user_id=user_id, access_key=ak,
                    secret_key=sk, user_type=user_type)
        return self.sm.users[user_id]

    def delete_user(self, user_id: str) -> None:
        self._apply("delete_user", user_id=user_id)

    def get_user(self, user_id: str) -> UserInfo:
        u = self.sm.users.get(user_id)
        if u is None:
            raise MasterError(f"unknown user {user_id!r}")
        return u

    def user_by_ak(self, access_key: str) -> UserInfo:
        uid = self.sm.ak_index.get(access_key)
        if uid is None:
            raise MasterError(f"unknown access key {access_key!r}")
        return self.sm.users[uid]

    def update_user_policy(self, user_id: str, vol_name: str,
                           actions: list[str], grant: bool = True) -> UserInfo:
        self._apply("update_user_policy", user_id=user_id, vol_name=vol_name,
                    actions=list(actions), grant=grant)
        return self.sm.users[user_id]

    def set_vol_owner(self, user_id: str, vol_name: str, add: bool = True) -> None:
        self._apply("user_own_vol", user_id=user_id, vol_name=vol_name, add=add)

    # -- decommission (master decommission APIs + migrate orchestration) -------
    #
    # The reference drains a node by re-homing every partition replica it
    # hosts (master decommission flows in cluster.go/vol.go). Per partition
    # the safe single-server dance is: create the group on the replacement
    # (it catches up via raft snapshot/appends) -> propose add(replacement)
    # -> propose remove(victim) -> drop state on the victim -> record the new
    # membership. Chain data (hot extents) back-fills through the extent
    # repair sweep once the replacement is in the hosts list.

    def decommission_metanode(self, node_id: int) -> int:
        if self.sm.nodes.get(node_id) is None:
            raise MasterError(f"unknown node {node_id}")
        self._apply("set_node_status", node_id=node_id, status="decommissioned")
        with self._decomm_lock:
            moved = self._migrate_metanode(node_id)
        from chubaofs_tpu.utils import events

        events.emit("node_decommissioned", events.SEV_WARNING,
                    entity=f"node{node_id}",
                    detail={"node_id": node_id, "kind": "meta",
                            "moved": moved})
        return moved

    def _move_mp_replica(self, vol, mp, node_id: int,
                         prefer_zone: str | None = None,
                         repl: int | None = None,
                         reason: str = "decommission") -> None:
        """Move one meta-partition replica off node_id (decommission,
        dead-node re-home and hot-partition rebalance all share this step):
        create the group on the replacement (it catches up via raft
        snapshot/appends) -> propose add(replacement) -> propose
        remove(victim) -> drop state on the victim -> record the new
        membership. An explicit `repl` (the rebalancer's load-ranked pick)
        skips the zone/domain-ranked _pick_addition. Emits `meta_migrate`
        at the add-peer and remove-peer transitions so cfs-events can
        reconstruct the move."""
        from chubaofs_tpu.utils import events

        survivors = [p for p in mp.peers if p != node_id]
        if repl is None:
            repl = self._pick_addition(
                "meta", survivors, exclude={node_id},
                prefer_zone=prefer_zone).node_id
        new_peers = survivors + [repl]
        if self.metanode_hook:
            # replacement-only create with the final membership — at the
            # GENESIS range: the new replica may catch up via appends from
            # index 1, and replaying under the post-split view range would
            # drop committed entries (the in-log range ops re-shrink it)
            self.metanode_hook(mp.partition_id, mp.start0, mp.end0,
                               new_peers, only=repl)
        events.emit("meta_migrate", entity=f"mp{mp.partition_id}",
                    detail={"partition": mp.partition_id, "vol": vol.name,
                            "victim": node_id, "replacement": repl,
                            "phase": "add_peer", "reason": reason})
        if self.raft_config_hook:
            self.raft_config_hook("meta", mp.partition_id, "add",
                                  repl, mp.peers)
            # contact set for the remove must still include the victim:
            # it is often the group's raft leader and must propose its
            # own removal (then step down on apply)
            self.raft_config_hook("meta", mp.partition_id, "remove",
                                  node_id, mp.peers + [repl])
        if self.remove_partition_hook:
            self.remove_partition_hook("meta", mp.partition_id, node_id)
        self._apply("update_mp_peers", vol_name=vol.name,
                    partition_id=mp.partition_id, peers=new_peers)
        events.emit("meta_migrate", entity=f"mp{mp.partition_id}",
                    detail={"partition": mp.partition_id, "vol": vol.name,
                            "victim": node_id, "replacement": repl,
                            "phase": "remove_peer", "reason": reason})

    def _migrate_metanode(self, node_id: int) -> int:
        moved = 0
        zone = self.sm.nodes[node_id].zone
        for vol in list(self.sm.volumes.values()):
            for mp in vol.meta_partitions:
                if node_id not in mp.peers:
                    continue
                self._move_mp_replica(vol, mp, node_id, prefer_zone=zone)
                moved += 1
        return moved

    def decommission_datanode(self, node_id: int) -> int:
        if self.sm.nodes.get(node_id) is None:
            raise MasterError(f"unknown node {node_id}")
        self._apply("set_node_status", node_id=node_id, status="decommissioned")
        with self._decomm_lock:
            moved = self._migrate_datanode(node_id)
        from chubaofs_tpu.utils import events

        events.emit("node_decommissioned", events.SEV_WARNING,
                    entity=f"node{node_id}",
                    detail={"node_id": node_id, "kind": "data",
                            "moved": moved})
        return moved

    def _move_dp_replica(self, vol, dp, node_id: int,
                         prefer_zone: str | None = None,
                         repl: NodeInfo | None = None,
                         reason: str = "decommission") -> None:
        """Move one dp replica off node_id (decommission, dead-node re-home,
        spread-repair and hot-volume rebalance all share this step). An
        explicit `repl` (the rebalancer's load-ranked pick) skips the
        zone/domain-ranked _pick_addition. `reason` tags the timeline event
        so a rebalance move and a decommission drain are distinguishable
        forensics."""
        if repl is None:
            repl = self._pick_addition(
                "data", [p for p in dp.peers if p != node_id],
                exclude={node_id},
                prefer_zone=prefer_zone)
        idx = dp.peers.index(node_id)
        new_peers = [p for p in dp.peers if p != node_id] + [repl.node_id]
        hosts = self._current_hosts(dp.peers, dp.hosts)
        new_hosts = [h for i, h in enumerate(hosts) if i != idx] + [repl.addr]
        if self.datanode_hook:
            self.datanode_hook(dp.partition_id, new_peers, new_hosts,
                               only=repl.node_id)
        if self.raft_config_hook:
            self.raft_config_hook("data", dp.partition_id, "add",
                                  repl.node_id, dp.peers)
            # include the victim in the contact set (see metanode path)
            self.raft_config_hook("data", dp.partition_id, "remove",
                                  node_id, dp.peers + [repl.node_id])
        if self.remove_partition_hook:
            self.remove_partition_hook("data", dp.partition_id, node_id)
        self._apply("update_dp_members", vol_name=vol.name,
                    partition_id=dp.partition_id, peers=new_peers,
                    hosts=new_hosts)
        if self.datanode_hook:
            # idempotent re-send refreshes peers/hosts on survivors
            # (their local meta still lists the victim)
            self.datanode_hook(dp.partition_id, new_peers, new_hosts)
        from chubaofs_tpu.utils import events

        events.emit("partition_moved", entity=f"dp{dp.partition_id}",
                    detail={"partition": dp.partition_id, "vol": vol.name,
                            "victim": node_id, "replacement": repl.node_id,
                            "reason": reason})

    def _migrate_datanode(self, node_id: int) -> int:
        moved = 0
        zone = self.sm.nodes[node_id].zone
        for vol in list(self.sm.volumes.values()):
            for dp in vol.data_partitions:
                if node_id not in dp.peers:
                    continue
                self._move_dp_replica(vol, dp, node_id, prefer_zone=zone,
                                      reason="decommission")
                moved += 1
        return moved

    def check_replica_spread(self) -> int:
        """Spread-repair sweep: a partition whose replicas CONCENTRATE in one
        fault domain — the residue of re-homing while several domains were
        dark — moves a doubled replica into an unrepresented healthy domain
        once one exists again (the reference's balance machinery applied to
        the domain axis). Data partitions only: mp moves are heavier
        (snapshot transfer) and the same residue heals on the next mp
        migration anyway."""
        if not self.is_leader:
            return 0
        moved = 0
        for vol in list(self.sm.volumes.values()):
            for dp in vol.data_partitions:
                by_dom: dict[str, list[int]] = {}
                for p in dp.peers:
                    n = self.sm.nodes.get(p)
                    if n is None or not n.schedulable:
                        continue  # dead peers are the re-home sweep's job
                    by_dom.setdefault(self.domain_of(n.zone), []).append(p)
                doubled = [ps for ps in by_dom.values() if len(ps) >= 2]
                if not doubled:
                    continue
                free_doms = {
                    self.domain_of(n.zone)
                    for n in self.sm.nodes.values()
                    if n.kind == "data" and n.schedulable
                    and n.node_id not in dp.peers
                } - set(by_dom)
                if not free_doms:
                    continue
                victim = max(
                    doubled[0],
                    key=lambda p: self.sm.nodes[p].partition_count)
                try:
                    self._move_dp_replica(vol, dp, victim,
                                          reason="spread_repair")
                    moved += 1
                except MasterError:
                    pass  # no capacity after all; retried next sweep
        return moved

    # -- hot-volume spreading (the capacity harness's actuator) -----------------

    def data_node_loads(self) -> dict[int, float]:
        """node_id -> total ops in the last heartbeat window, schedulable
        datanodes only — the per-node ops-spread view cfs-capacity's A/B
        measures (and rebalance_hot acts on)."""
        return {n.node_id: sum(n.loads.values())
                for n in self.sm.nodes.values()
                if n.kind == "data" and n.schedulable}

    def _find_dp(self, pid: int):
        for vol in self.sm.volumes.values():
            for dp in vol.data_partitions:
                if dp.partition_id == pid:
                    return vol, dp
        return None, None

    def rebalance_hot(self, factor: float = 1.5, max_moves: int = 2) -> int:
        """Hot-volume spreading under skewed load: any schedulable datanode
        whose heartbeat-window op load exceeds `factor` x the mean sheds its
        hottest data-partition replicas onto the coldest nodes not already
        hosting them, through the same create->raft-add->raft-remove->drop
        migration dance decommission uses (_move_dp_replica). Zipfian access
        concentrates leaders; this is the knob that actually fixes the
        hotspots the capacity harness finds. A move must strictly improve
        the pair (target load + partition load < source load) or it is
        skipped — the sweep converges instead of ping-ponging replicas.
        Bounded at `max_moves` per sweep so rebalancing traffic (replica
        catch-up rides the repair path) never dominates foreground IO.
        Domain concentration a load-ranked pick may introduce is healed by
        check_replica_spread, the same residue contract re-homing has."""
        if not self.is_leader:
            return 0
        with self._decomm_lock:
            datas = {n.node_id: n for n in self.sm.nodes.values()
                     if n.kind == "data" and n.schedulable}
            if len(datas) < 2:
                return 0
            # local bookkeeping copy: replicated NodeInfo.loads must only
            # mutate inside raft apply, but the sweep still needs to account
            # its own moves so one pass doesn't dogpile a single cold node
            loads = {nid: sum(n.loads.values()) for nid, n in datas.items()}
            total = sum(loads.values())
            if total <= 0:
                return 0
            mean = total / len(loads)
            moved = 0
            for nid in sorted(loads, key=loads.get, reverse=True):
                if moved >= max_moves:
                    break
                # snapshot ONCE: the raft apply thread REPLACES n.loads on
                # every heartbeat, and a double attribute read (iterable +
                # key fn) could straddle the swap — .get(old_pid) -> None
                # would crash the sort mid-sweep
                pid_loads = dict(datas[nid].loads)
                for pid in sorted(pid_loads, key=pid_loads.get, reverse=True):
                    if loads[nid] <= factor * mean:
                        break  # shed enough; next hot node
                    pid_load = pid_loads.get(pid, 0.0)
                    if pid_load <= 0:
                        break
                    vol, dp = self._find_dp(pid)
                    if dp is None or nid not in dp.peers:
                        continue  # meta pid, or a replica already moved
                    cands = [n for n in datas.values()
                             if n.node_id not in dp.peers]
                    if not cands:
                        continue
                    target = min(cands, key=lambda n: (loads[n.node_id],
                                                       n.partition_count))
                    if loads[target.node_id] + pid_load >= loads[nid]:
                        continue  # would not strictly improve the pair
                    try:
                        self._move_dp_replica(vol, dp, nid, repl=target,
                                              reason="rebalance_hot")
                    except MasterError:
                        continue  # no capacity after all; retried next sweep
                    loads[nid] -= pid_load
                    loads[target.node_id] += pid_load
                    moved += 1
                    if moved >= max_moves:
                        break
            return moved

    # -- metadata scale-out: load split + cross-metanode rebalance (ISSUE 15) --

    def meta_node_loads(self) -> dict[int, float]:
        """node_id -> total meta ops in the last heartbeat window,
        schedulable metanodes only (the rebalance/split accounting view)."""
        return {n.node_id: sum(n.loads.values())
                for n in self.sm.nodes.values()
                if n.kind == "meta" and n.schedulable}

    def _find_meta_mp(self, pid: int):
        for vol in self.sm.volumes.values():
            for mp in vol.meta_partitions:
                if mp.partition_id == pid:
                    return vol, mp
        return None, None

    def meta_partition_loads(self) -> dict[int, float]:
        """pid -> hottest replica's heartbeat-window op count (the leader
        serves every client op, so max-across-replicas IS the serving load).
        Inactive nodes are excluded: loads only refresh on a heartbeat, so
        a dead node's window is frozen at its last report — a ghost that
        would re-split the same partition every sweep."""
        out: dict[int, float] = {}
        for n in self.sm.nodes.values():
            if n.kind != "meta" or n.status != "active":
                continue
            for pid, load in n.loads.items():
                out[pid] = max(out.get(pid, 0.0), float(load))
        return out

    def rebalance_meta(self, factor: float = 1.5, max_moves: int = 1) -> int:
        """Cross-metanode migration of hot meta partitions: any schedulable
        metanode whose heartbeat-window op load exceeds `factor` x the mean
        sheds its hottest partition replicas onto the coldest metanodes not
        already in the peer set, through the same create -> raft-add ->
        raft-remove -> drop dance decommission uses (_move_mp_replica).
        The data plane got this in PR 11 (rebalance_hot); this is the meta
        plane's analog. Strict-improvement gated so the sweep converges,
        bounded at `max_moves` (mp moves ship a namespace snapshot — heavier
        than a dp replica, so the default is conservative)."""
        if not self.is_leader:
            return 0
        with self._decomm_lock:
            # active only: a dead node's load window is frozen at its last
            # heartbeat (a ghost shedder), and worse, its idle-looking
            # window makes it the coldest MOVE TARGET
            metas = {n.node_id: n for n in self.sm.nodes.values()
                     if n.kind == "meta" and n.schedulable
                     and n.status == "active"}
            if len(metas) < 2:
                return 0
            loads = {nid: sum(n.loads.values()) for nid, n in metas.items()}
            total = sum(loads.values())
            if total <= 0:
                return 0
            mean = total / len(loads)
            moved = 0
            for nid in sorted(loads, key=loads.get, reverse=True):
                if moved >= max_moves:
                    break
                # snapshot ONCE (rebalance_hot rationale): the raft applier
                # REPLACES n.loads on every heartbeat mid-sweep
                pid_loads = dict(metas[nid].loads)
                for pid in sorted(pid_loads, key=pid_loads.get, reverse=True):
                    if loads[nid] <= factor * mean:
                        break  # shed enough; next hot node
                    pid_load = pid_loads.get(pid, 0.0)
                    if pid_load <= 0:
                        break
                    vol, mp = self._find_meta_mp(pid)
                    if mp is None or nid not in mp.peers:
                        continue  # data pid, or a replica already moved
                    cands = [n for n in metas.values()
                             if n.node_id not in mp.peers]
                    if not cands:
                        continue
                    target = min(cands, key=lambda n: (loads[n.node_id],
                                                       n.partition_count))
                    if loads[target.node_id] + pid_load >= loads[nid]:
                        continue  # would not strictly improve the pair
                    try:
                        self._move_mp_replica(vol, mp, nid,
                                              repl=target.node_id,
                                              reason="rebalance_meta")
                    except MasterError:
                        continue  # no capacity after all; retried next sweep
                    loads[nid] -= pid_load
                    loads[target.node_id] += pid_load
                    moved += 1
                    if moved >= max_moves:
                        break
            return moved

    def split_meta_partition(self, vol_name: str, partition_id: int) -> int:
        """Operator/bench entry: load-split ONE named partition at its
        median live inode, now. Returns the sibling's pid (0 = partition
        declined: too few live inodes, or a 2PC txn in flight)."""
        vol = self.get_volume(vol_name)
        mp = next((m for m in vol.meta_partitions
                   if m.partition_id == partition_id), None)
        if mp is None:
            raise MasterError(f"unknown meta partition {partition_id}")
        with self._decomm_lock:
            try:
                return self._split_meta_partition(vol, mp)
            except Exception as e:
                if getattr(e, "code", None) == "ETXCONFLICT":
                    # the documented decline (prepared 2PC txns in flight,
                    # bounded by TX_TTL), not an error: retry shortly
                    return 0
                raise

    def resume_meta_splits(self) -> int:
        """Finish splits whose orchestrator died mid-flight: metanode
        heartbeats report frozen partitions (NodeInfo.splits), and every
        step of _split_meta_partition is idempotent, so re-driving from the
        replicated split_info converges. A frozen partition that already
        left the view is unfrozen (volume deleted mid-split)."""
        if not self.is_leader:
            return 0
        finished = 0
        seen: set[int] = set()
        for n in list(self.sm.nodes.values()):
            if n.kind != "meta":
                continue
            for pid, info in dict(n.splits).items():
                if pid in seen:
                    continue
                seen.add(pid)
                vol, mp = self._find_meta_mp(pid)
                if mp is None:
                    if self.meta_op_hook:
                        try:
                            self.meta_op_hook(pid, [n.node_id],
                                              "unfreeze_range", {})
                        except Exception:
                            pass  # node may be rebooting; retried next sweep
                    continue
                with self._decomm_lock:
                    try:
                        if self._split_meta_partition(vol, mp, resume=info):
                            finished += 1
                    except Exception:
                        # a mid-resume replica crash surfaces as a hook
                        # timeout/OpError: the partition stays frozen and
                        # the next sweep re-resumes — never kill the sweep
                        pass
        return finished

    def _split_meta_partition(self, vol, mp, resume: dict | None = None) -> int:
        """Drive one mid-range split end to end (caller holds _decomm_lock):
        freeze the upper half at the median -> snapshot-copy it into a
        sibling raft group -> atomically swap the volume view in one master
        commit -> drop the moved entries. Any failure leaves the partition
        FROZEN with a replicated resume record; resume_meta_splits finishes
        it. Returns the sibling pid, 0 when the partition declines."""
        from chubaofs_tpu.utils import events

        if self.meta_op_hook is None or self.metanode_hook is None:
            return 0
        old_end = mp.end
        if resume is None:
            split_at = self.meta_op_hook(mp.partition_id, mp.peers,
                                         "split_point", {}, read=True)
            if not split_at:
                return 0
            new_pid = self._apply("alloc_id")
            new_peers = self._pick_meta_peers()
            # the fence + the replicated resume record, in one raft commit
            # on the partition itself
            self.meta_op_hook(mp.partition_id, mp.peers, "freeze_range",
                              {"split_at": split_at, "new_pid": new_pid,
                               "new_peers": new_peers})
            events.emit("meta_split", entity=f"mp{mp.partition_id}",
                        detail={"partition": mp.partition_id, "vol": vol.name,
                                "split_at": split_at, "new_pid": new_pid,
                                "phase": "freeze"})
        else:
            split_at = int(resume["split_at"])
            new_pid = int(resume["new_pid"])
            new_peers = [int(p) for p in resume.get("new_peers", [])] \
                or self._pick_meta_peers()
            if any(m.partition_id == new_pid for m in vol.meta_partitions):
                # view already swapped: only the cleanup tail is missing
                self.meta_op_hook(mp.partition_id, mp.peers,
                                  "complete_split", {})
                events.emit("meta_split", entity=f"mp{mp.partition_id}",
                            detail={"partition": mp.partition_id,
                                    "vol": vol.name, "new_pid": new_pid,
                                    "phase": "complete", "resumed": True})
                # a resumed TAIL split still owes the chain: without it the
                # sibling keeps the open range and the volume settles at 2
                # partitions with the hotspot re-forming on the sibling
                self._chain_tail_split(vol, new_pid)
                return new_pid
        # sibling raft group on the chosen peers (idempotent: create skips
        # peers already hosting the pid), range [split_at, old_end)
        self.metanode_hook(new_pid, split_at, old_end, new_peers)
        # snapshot-copy the frozen sub-range, page by page (the freeze makes
        # paging consistent; import is a keyed upsert, so replays are safe)
        after = 0
        src_cursor = 0
        while True:
            page = self.meta_op_hook(mp.partition_id, mp.peers,
                                     "export_range", {"after": after},
                                     read=True)
            src_cursor = page.get("cursor") or src_cursor
            # the final page always ships (even empty): it carries the
            # final=True that triggers the sibling's one quota recount
            if page["inodes"] or page["dentries"] or not after or page["done"]:
                self.meta_op_hook(new_pid, new_peers, "import_entries",
                                  {"inodes": page["inodes"],
                                   "dentries": page["dentries"],
                                   "cursor": page.get("cursor"),
                                   "quotas": page.get("quotas"),
                                   "final": bool(page["done"])})
            if page["done"]:
                break
            after = page["next"]
        # THE atomic swap: one master-raft commit moves ownership of
        # [split_at, old_end) to the sibling — never zero or two owners
        self._apply("split_partition_mid", vol_name=vol.name,
                    partition_id=mp.partition_id, split_at=split_at,
                    new_partition_id=new_pid, peers=new_peers)
        events.emit("meta_split", entity=f"mp{mp.partition_id}",
                    detail={"partition": mp.partition_id, "vol": vol.name,
                            "split_at": split_at, "new_pid": new_pid,
                            "peers": list(new_peers), "phase": "commit"})
        # cleanup tail: drop the moved entries + shrink end + lift the fence
        self.meta_op_hook(mp.partition_id, mp.peers, "complete_split", {})
        events.emit("meta_split", entity=f"mp{mp.partition_id}",
                    detail={"partition": mp.partition_id, "vol": vol.name,
                            "new_pid": new_pid, "phase": "complete"})
        if old_end >= INF:
            self._chain_tail_split(vol, new_pid, src_cursor)
        return new_pid

    def _chain_tail_split(self, vol, new_pid: int,
                          src_cursor: int = 0) -> None:
        """A load split of the TAIL chains a cursor split of the sibling:
        the sibling inherited the open range, so every NEW create would
        land on it — the hot partition the split just relieved would
        re-form immediately. Capping it at cursor+headroom opens a fresh
        tail on (usually) other metanodes, and the capped sibling keeps
        serving its directories' combined creates from the headroom.
        Best-effort: failing the chain just leaves the sibling as the open
        tail (pre-chain behavior). The resume path has no export cursor, so
        it falls back to the sibling's heartbeat-reported cursor (resume is
        itself heartbeat-driven, so one is normally already on file)."""
        from chubaofs_tpu.utils import events

        sib = next((m for m in vol.meta_partitions
                    if m.partition_id == new_pid), None)
        if sib is None or sib.end < INF:
            return
        cursor = src_cursor or max(
            (n.cursors.get(new_pid, 0) for n in self.sm.nodes.values()),
            default=0)
        if not cursor:
            return
        try:
            self._cursor_split(vol, sib, cursor + SPLIT_HEADROOM)
            events.emit("meta_split", entity=f"mp{new_pid}",
                        detail={"partition": new_pid, "vol": vol.name,
                                "phase": "chain",
                                "split_at": cursor + SPLIT_HEADROOM})
        except Exception:
            pass

    def _cursor_split(self, vol, tail, split_at: int) -> int:
        """One cursor split of the tail: cap the old tail at split_at (its
        headroom keeps serving combined creates for directories it owns) and
        open a fresh tail. The SM's range end shrinks FIRST (set_range_end,
        a replicated op): without it the old SM keeps end=INF and its
        combined-create path would allocate inodes beyond the view range —
        unroutable files. Ordered so a failure between the two commits
        leaves behavior safe: a capped SM without the view swap just answers
        ERANGE at the cap until the next sweep retries the split. The SM
        answers with the cap it actually holds (an earlier failed attempt
        may have committed a LOWER one while the cursor kept advancing);
        the view swap must use that cap or the retry never converges."""
        if self.meta_op_hook is not None:
            got = self.meta_op_hook(tail.partition_id, tail.peers,
                                    "set_range_end", {"end": split_at})
            if got:
                split_at = int(got)
        new_pid = self._apply("alloc_id")
        peers = self._pick_meta_peers()
        self._apply(
            "split_partition", vol_name=vol.name,
            partition_id=tail.partition_id,
            split_at=split_at, new_partition_id=new_pid, peers=peers,
        )
        if self.metanode_hook:
            self.metanode_hook(new_pid, split_at, INF, peers)
        return 1

    def split_hot_meta_partitions(self, threshold: float,
                                  max_splits: int = 1) -> int:
        """The load path: split the hottest meta partition whose heartbeat-
        window op count reached `threshold` (a directory-heavy tenant pins
        one raft group without this — the skewed regimes of arxiv
        1709.05365). Bounded per sweep: a split ships half a namespace."""
        if not self.is_leader or threshold <= 0:
            return 0
        done = 0
        loads = self.meta_partition_loads()
        for pid in sorted(loads, key=loads.get, reverse=True):
            if done >= max_splits or loads[pid] < threshold:
                break
            vol, mp = self._find_meta_mp(pid)
            if mp is None:
                continue
            with self._decomm_lock:
                try:
                    if self._split_meta_partition(vol, mp):
                        done += 1
                except Exception:
                    continue  # frozen state + heartbeat reports resume it
        return done

    # -- background checks (scheduleTask loop analogs) --------------------------

    def check_meta_partitions(self) -> int:
        """Meta-partition growth sweep: (1) split tail partitions whose
        cursor nears the range end (cursor growth), (2) resume mid-range
        splits stranded by a crashed orchestrator, (3) load-split HOT
        mid-range partitions when CFS_META_SPLIT_OPS arms a threshold."""
        if not self.is_leader:
            return 0
        # resume FIRST: a stranded load split can leave the tail frozen, and
        # a frozen tail refuses set_range_end — cursor growth on it can only
        # succeed after the resume lifts the fence
        splits = self.resume_meta_splits()
        for vol in list(self.sm.volumes.values()):
            tail = vol.meta_partitions[-1]
            cursor = max(
                (n.cursors.get(tail.partition_id, 0) for n in self.sm.nodes.values()),
                default=0,
            )
            bound = tail.start + META_RANGE_STEP
            if cursor and cursor >= bound - SPLIT_HEADROOM:
                split_at = cursor + SPLIT_HEADROOM
                try:
                    splits += self._cursor_split(vol, tail, split_at)
                except Exception:
                    # one volume's refusal (e.g. ESPLIT on a tail whose
                    # resume is still owed) must not abort the sweep for
                    # the other volumes or the hot-split pass below
                    continue
        splits += self.split_hot_meta_partitions(self.meta_split_ops)
        return splits

    def check_node_liveness(self, timeout: float = 10.0,
                            now: float | None = None) -> list[int]:
        """Mark nodes whose heartbeat went stale as INACTIVE so placement and
        client views route around them; a returning heartbeat reactivates
        (master/cluster.go scheduleToCheckHeartbeat analog). Decommissioned
        nodes are left alone. Returns the node ids newly marked."""
        if not self.is_leader:
            return []
        now = time.time() if now is None else now
        out = []
        for n in list(self.sm.nodes.values()):
            if n.status != "active":
                continue
            if n.last_heartbeat and now - n.last_heartbeat > timeout:
                self._apply("set_node_status", node_id=n.node_id,
                            status="inactive")
                out.append(n.node_id)
        return out

    def check_data_partitions(self) -> int:
        """Demote data partitions with a non-schedulable replica to read-only
        and promote them back when every peer is healthy (the reference's
        checkDataPartitions loop marking partitions unavailable). Clients only
        see rw partitions (data_partition_views), so writes route around dead
        replicas while reads still work through the survivors."""
        if not self.is_leader:
            return 0
        changed = 0
        for vol in list(self.sm.volumes.values()):
            for dp in vol.data_partitions:
                healthy = all(
                    self.sm.nodes.get(p) is not None
                    and self.sm.nodes[p].status == "active"
                    for p in dp.peers)
                want = "rw" if healthy else "ro"
                if dp.status in ("rw", "ro") and dp.status != want:
                    self._apply("set_dp_status", vol_name=vol.name,
                                partition_id=dp.partition_id, status=want)
                    changed += 1
        return changed

    def _replica_count(self, node_id: int) -> int:
        """Partition replicas currently homed on node_id (any kind)."""
        c = 0
        for vol in list(self.sm.volumes.values()):
            for mp in vol.meta_partitions:
                if node_id in mp.peers:
                    c += 1
            for dp in vol.data_partitions:
                if node_id in dp.peers:
                    c += 1
        return c

    def check_dead_node_replicas(self, dead_after: float = 60.0,
                                 now: float | None = None) -> int:
        """Durable auto-repair for nodes that STAY dead (reference
        scheduleToCheckDataReplicas + the decommission flows, cluster.go:347):
        liveness marks a stale node inactive within seconds (writes route
        around it, dps demote to ro); once the outage exceeds ``dead_after``
        this loop re-homes every replica the node held onto healthy peers,
        reusing the decommission dance. The node record stays ``inactive`` —
        a returning node reactivates on its next heartbeat and simply hosts
        nothing (its stale raft groups reject it; the partitions were moved).
        Per-node failures (e.g. no spare peers yet) keep whatever progress
        was made and retry on the next sweep. Fully-drained nodes enter an
        in-memory skip set (cleared by a returning heartbeat) so a cluster
        with long-dead nodes doesn't rescan every partition each tick.
        Returns replicas actually moved (counted by before/after census, so
        partial drains are reported honestly)."""
        if not self.is_leader:
            return 0
        now = time.time() if now is None else now
        moved = 0
        for n in list(self.sm.nodes.values()):
            with self._drained_lock:
                drained = n.node_id in self._dead_drained
            if n.status != "inactive" or drained:
                continue
            if not n.last_heartbeat or now - n.last_heartbeat < dead_after:
                continue
            with self._decomm_lock:
                before = self._replica_count(n.node_id)
                if before == 0:
                    with self._drained_lock:
                        self._dead_drained.add(n.node_id)
                    continue
                try:
                    if n.kind == "meta":
                        self._migrate_metanode(n.node_id)
                    else:
                        self._migrate_datanode(n.node_id)
                except MasterError:
                    pass  # partial progress kept; retried next sweep
                remaining = self._replica_count(n.node_id)
                moved += before - remaining
                if remaining == 0:
                    with self._drained_lock:
                        self._dead_drained.add(n.node_id)
        return moved

    def update_volume(self, name: str, capacity: int | None = None,
                      follower_read: bool | None = None,
                      qos_read_mbps: int | None = None,
                      qos_write_mbps: int | None = None) -> VolumeView:
        """Vol expand/shrink + per-volume client QoS (master/vol.go
        updateVol; limits flow master -> client via the volume view)."""
        return self._apply(
            "update_volume", name=name, capacity=capacity,
            follower_read=follower_read, qos_read_mbps=qos_read_mbps,
            qos_write_mbps=qos_write_mbps)

    def ensure_replica_counts(self, target: int = 3) -> int:
        """Partition-replica-count checker (scheduleToCheckDataReplicas'
        under-replication half): any mp/dp below `target` peers gains a
        replacement via the migrate machinery. Partial migrations and
        operator surgery leave these behind; the sweep heals them."""
        if not self.is_leader:
            return 0
        added = 0
        for vol in list(self.sm.volumes.values()):
            for mp in vol.meta_partitions:
                while len(mp.peers) < target:
                    try:
                        repl = self._pick_addition("meta", mp.peers).node_id
                    except MasterError:
                        break  # not enough healthy nodes; retried next sweep
                    new_peers = mp.peers + [repl]
                    if self.metanode_hook:
                        # genesis range (see _move_mp_replica): the healed
                        # replica replays/catches up from the log start
                        self.metanode_hook(mp.partition_id, mp.start0,
                                           mp.end0, new_peers, only=repl)
                    if self.raft_config_hook:
                        self.raft_config_hook("meta", mp.partition_id, "add",
                                              repl, mp.peers)
                    self._apply("update_mp_peers", vol_name=vol.name,
                                partition_id=mp.partition_id, peers=new_peers)
                    mp = [m for m in self.sm.volumes[vol.name].meta_partitions
                          if m.partition_id == mp.partition_id][0]
                    added += 1
            for dp in vol.data_partitions:
                while len(dp.peers) < target:
                    try:
                        repl = self._pick_addition("data", dp.peers)
                    except MasterError:
                        break
                    new_peers = dp.peers + [repl.node_id]
                    new_hosts = self._current_hosts(dp.peers, dp.hosts) + [repl.addr]
                    if self.datanode_hook:
                        self.datanode_hook(dp.partition_id, new_peers,
                                           new_hosts, only=repl.node_id)
                    if self.raft_config_hook:
                        self.raft_config_hook("data", dp.partition_id, "add",
                                              repl.node_id, dp.peers)
                    self._apply("update_dp_members", vol_name=vol.name,
                                partition_id=dp.partition_id, peers=new_peers,
                                hosts=new_hosts)
                    dp = [d for d in self.sm.volumes[vol.name].data_partitions
                          if d.partition_id == dp.partition_id][0]
                    added += 1
        return added

    def prune_stale_nodes(self, stale_after: float = 3600.0,
                          now: float | None = None) -> list[int]:
        """Stale-node pruner: registry entries that are inactive or
        decommissioned, host NO partition replicas, and have been silent
        past `stale_after` are removed — a re-registration starts clean.
        (The reference's operator-driven node removal, automated for the
        already-drained case.)"""
        if not self.is_leader:
            return []
        now = time.time() if now is None else now
        pruned = []
        for n in list(self.sm.nodes.values()):
            if n.status == "active":
                continue
            if now - n.last_heartbeat < stale_after:
                continue
            if self._replica_count(n.node_id):
                continue
            try:
                self._apply("remove_node", node_id=n.node_id)
                with self._drained_lock:
                    self._dead_drained.discard(n.node_id)
                pruned.append(n.node_id)
            except MasterError:
                pass
        return pruned

    def orphan_partitions(self) -> dict[int, list[int]]:
        """node_id -> partition ids the node REPORTS (heartbeat cursors)
        but should not host: either no volume records the pid (failed
        volume delete) or the pid's recorded peer set no longer includes
        the node (a migration whose remove task never reached the then-dead
        victim). Per-NODE detection, so stale replicas left behind by
        re-homes are found, not just fully-deleted-volume leftovers. The
        daemon's sweep sends remove tasks for them (scheduleTask junk
        cleanup analog)."""
        peers_of: dict[int, set[int]] = {}
        for vol in self.sm.volumes.values():
            for mp in vol.meta_partitions:
                peers_of[mp.partition_id] = set(mp.peers)
            for dp in vol.data_partitions:
                peers_of[dp.partition_id] = set(dp.peers)
        out: dict[int, list[int]] = {}
        for n in self.sm.nodes.values():
            orphans = [pid for pid in n.cursors
                       if n.node_id not in peers_of.get(pid, frozenset())]
            if orphans:
                out[n.node_id] = sorted(orphans)
        return out

    def refresh_leaders(self, leader_of) -> None:
        """Record partition leaders into the view (client routing hint)."""
        if not self.is_leader:
            return
        for vol in list(self.sm.volumes.values()):
            for mp in vol.meta_partitions:
                lead = leader_of(mp.partition_id)
                if lead != mp.leader:
                    self._apply(
                        "set_partition_leader", vol_name=vol.name,
                        partition_id=mp.partition_id, leader=lead,
                    )
