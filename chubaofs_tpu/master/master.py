"""Master — the cluster's resource manager, one raft group over all masters.

Reference counterpart: master/ (Server.Start server.go:137-175, single raft
group ID 1, MetadataFsm, Cluster.scheduleTask's 16 background loops
cluster.go:329-3587, IDAllocator id_allocator.go:176-272, vol/meta-partition
management vol.go + meta_partition.go). Kept:

  * every mutation is a raft-applied op on MasterSM (the MetadataFsm analog);
  * volumes own a list of meta partitions, each an inode range [start, end)
    replicated across 3 metanodes; the last partition is unbounded and is SPLIT
    when its cursor approaches the range end (meta_partition splitting);
  * node registry with heartbeats; background check loops are explicit tick
    methods (check_meta_partitions) the deployment pumps, like scheduleTask.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from chubaofs_tpu.raft.server import MultiRaft, StateMachine
from chubaofs_tpu.utils.locks import SanitizedLock

MASTER_GROUP = 1
META_RANGE_STEP = 1 << 24  # inos per partition before splitting
SPLIT_HEADROOM = 1 << 20  # split when cursor is this close to the end
INF = 1 << 63
NODESET_CAPACITY = 18  # nodes per nodeset (master/topology.go default)


class MasterError(Exception):
    pass


@dataclass
class NodeInfo:
    node_id: int
    kind: str  # "meta" | "data"
    addr: str = ""
    raft_addr: str = ""  # TCP raft transport address (daemon mode)
    last_heartbeat: float = 0.0
    partition_count: int = 0
    cursors: dict[int, int] = field(default_factory=dict)  # pid -> cursor (meta)
    status: str = "active"  # active | decommissioned
    zone: str = ""  # fault domain (master/topology.go:43 zones)
    nodeset: int = 0  # zone-local nodeset index (bounded failure groups)
    total_space: int = 0  # bytes, node-reported via heartbeat (statinfo)
    used_space: int = 0
    # pid -> ops served in the node's last heartbeat window (datanode
    # take_loads() delta) — the hot-volume rebalancer's accounting feed
    loads: dict[int, float] = field(default_factory=dict)

    @property
    def schedulable(self) -> bool:
        return getattr(self, "status", "active") == "active"


@dataclass
class MetaPartitionView:
    partition_id: int
    start: int
    end: int  # exclusive; INF for the tail partition
    peers: list[int] = field(default_factory=list)
    leader: int | None = None


@dataclass
class DataPartitionView:
    """One replicated data partition (master/data_partition.go analog):
    peers are datanode ids (raft membership), hosts their repl addresses;
    hosts[0] is the chain-replication leader."""

    partition_id: int
    peers: list[int] = field(default_factory=list)
    hosts: list[str] = field(default_factory=list)
    status: str = "rw"  # rw | ro | unavail


@dataclass
class VolumeView:
    name: str
    vol_id: int
    owner: str = ""
    capacity: int = 0
    cold: bool = False  # cold volumes store data in the blobstore (EC tier)
    # reads may hit any replica (relaxed consistency — a follower can trail
    # the leader's latest random overwrite); ref proto/mount_options.go
    # FollowerRead + sdk/data/stream follower-read
    follower_read: bool = False
    # per-volume client QoS (MB/s, 0 = unlimited): the master owns the
    # limits and every client reads them from its volume view, so an
    # operator change flows master -> clients on the next view refresh
    # (ref master/limiter.go qos assignment flowing to clients)
    qos_read_mbps: int = 0
    qos_write_mbps: int = 0
    meta_partitions: list[MetaPartitionView] = field(default_factory=list)
    data_partitions: list[DataPartitionView] = field(default_factory=list)


@dataclass
class UserInfo:
    """master/user.go analog: an identity with S3 credentials + vol policy."""

    user_id: str
    access_key: str
    secret_key: str
    user_type: str = "normal"  # root | admin | normal
    own_vols: list[str] = field(default_factory=list)
    # vol -> granted actions, e.g. ["perm:readonly"] / ["perm:writable"]
    authorized_vols: dict[str, list[str]] = field(default_factory=dict)


class MasterSM(StateMachine):
    """Replicated master state (MetadataFsm + Cluster state analog)."""

    def __init__(self):
        self.nodes: dict[int, NodeInfo] = {}
        self.volumes: dict[str, VolumeView] = {}
        self.users: dict[str, UserInfo] = {}  # user_id -> info
        self.ak_index: dict[str, str] = {}  # access_key -> user_id
        # fault domains group zones (master/topology.go:43 + vol.go domain
        # placement): any assignment turns domain mode ON; unassigned zones
        # act as their own singleton domains
        self.zone_domains: dict[str, str] = {}
        self.next_id = 100  # shared id space for volumes + partitions

    # raft hooks -------------------------------------------------------------

    def apply(self, data, index: int):
        op, args = data
        try:
            return ("ok", getattr(self, "_op_" + op)(**args))
        except MasterError as e:
            return ("err", str(e))

    def snapshot(self) -> bytes:
        """Sectioned CRC-framed snapshot (raft.snapcodec) — the reference
        streams master state as typed RocksDB records (metadata_fsm), never
        as one opaque language-native blob."""
        from dataclasses import asdict

        from chubaofs_tpu.raft import snapcodec

        w = snapcodec.SnapshotWriter()
        w.add("meta", {"next_id": self.next_id,
                       "zone_domains": self.zone_domains})
        w.add_batched("nodes", (asdict(n) for n in self.nodes.values()))
        w.add_batched("volumes", (asdict(v) for v in self.volumes.values()))
        w.add_batched("users", (asdict(u) for u in self.users.values()))
        return w.getvalue()

    def restore(self, payload: bytes) -> None:
        from chubaofs_tpu.raft import snapcodec

        self.nodes, self.volumes, self.users, self.ak_index = {}, {}, {}, {}
        self.zone_domains = {}

        def load_nodes(batch):
            for d in batch:
                d["cursors"] = {int(k): v for k, v in d["cursors"].items()}
                # .get: snapshots from before load accounting existed
                d["loads"] = {int(k): float(v)
                              for k, v in d.get("loads", {}).items()}
                n = NodeInfo(**d)
                self.nodes[n.node_id] = n

        def load_volumes(batch):
            for d in batch:
                v = VolumeView(
                    name=d["name"], vol_id=d["vol_id"], owner=d["owner"],
                    capacity=d["capacity"], cold=d["cold"],
                    # .get: snapshots from before each option existed
                    follower_read=d.get("follower_read", False),
                    qos_read_mbps=d.get("qos_read_mbps", 0),
                    qos_write_mbps=d.get("qos_write_mbps", 0),
                    meta_partitions=[MetaPartitionView(**m)
                                     for m in d["meta_partitions"]],
                    data_partitions=[DataPartitionView(**p)
                                     for p in d["data_partitions"]],
                )
                self.volumes[v.name] = v

        def load_users(batch):
            for d in batch:
                u = UserInfo(**d)
                self.users[u.user_id] = u
                self.ak_index[u.access_key] = u.user_id

        def load_meta(m):
            self.next_id = m["next_id"]
            # older snapshots predate fault domains
            self.zone_domains = dict(m.get("zone_domains", {}))

        snapcodec.restore_sections(payload, {
            "meta": load_meta,
            "nodes": load_nodes,
            "volumes": load_volumes,
            "users": load_users,
        })

    # ops ---------------------------------------------------------------------

    def _op_alloc_id(self):
        self.next_id += 1
        return self.next_id

    def _op_register_node(self, node_id: int, kind: str, addr: str,
                          raft_addr: str = "", now: float = 0.0,
                          zone: str = ""):
        # `now` is stamped by the PROPOSER: calling time.time() inside apply
        # would make replicas and WAL replay record different values, so a
        # restarted master could trust dead nodes as freshly heartbeaten
        if node_id not in self.nodes:  # racelint: _op_* appliers are serialized by the raft drain pump
            self.nodes[node_id] = NodeInfo(
                node_id, kind, addr, zone=zone,
                nodeset=self._assign_nodeset(kind, zone),
            )
        n = self.nodes[node_id]
        if n.kind != kind:  # operator config error: one id, two roles
            raise MasterError(
                f"node id {node_id} already registered as {n.kind!r}")
        if addr:  # re-registration after restart carries the new address
            n.addr = addr
        if raft_addr:
            n.raft_addr = raft_addr
        if zone and zone != n.zone:
            # late-reported or operator-changed zone: re-home the nodeset too,
            # or the capacity bound would silently break in the new zone
            n.nodeset = self._assign_nodeset(kind, zone)
            n.zone = zone
        n.last_heartbeat = max(n.last_heartbeat, now)
        return node_id

    def _op_set_zone_domain(self, zone: str, domain: str):
        """Assign a zone to a fault domain (master/topology.go:43). An empty
        domain clears the assignment; clearing the last one turns domain
        mode off."""
        if domain:
            self.zone_domains[zone] = domain
        else:
            self.zone_domains.pop(zone, None)
        return dict(self.zone_domains)

    def _assign_nodeset(self, kind: str, zone: str) -> int:
        """Smallest zone-local nodeset with spare capacity — deterministic over
        replicated state, so every replica assigns identically
        (master/topology.go nodeset grouping, capacity-bounded)."""
        counts: dict[int, int] = {}
        for n in self.nodes.values():
            if n.kind == kind and n.zone == zone:
                counts[n.nodeset] = counts.get(n.nodeset, 0) + 1
        ns = 0
        while counts.get(ns, 0) >= NODESET_CAPACITY:
            ns += 1
        return ns

    def _op_heartbeat(self, node_id: int, partition_count: int = 0,
                      cursors: dict | None = None, now: float = 0.0,
                      total_space: int | None = None,
                      used_space: int | None = None,
                      loads: dict | None = None):
        n = self.nodes.get(node_id)
        if n is None:
            raise MasterError(f"unknown node {node_id}")
        n.last_heartbeat = max(n.last_heartbeat, now)
        if n.status == "inactive":
            n.status = "active"  # liveness recovery; decommissioned stays out
        n.partition_count = partition_count
        # space report (statinfo source, master/cluster.go UpdateStatInfo):
        # None = no report, leaves state alone
        if total_space is not None:
            n.total_space = int(total_space)
        if used_space is not None:
            n.used_space = int(used_space)
        # a dict REPLACES the cursor set (even when empty — a restarted node
        # reports no partitions, and the ensure sweep must see that to re-send
        # create tasks); None means "no report" and leaves state alone
        if cursors is not None:
            n.cursors = {int(k): v for k, v in cursors.items()}
        # per-partition op-load window (same replace-vs-no-report contract)
        if loads is not None:
            n.loads = {int(k): float(v) for k, v in loads.items()}
        return None

    def _op_create_volume(self, name: str, owner: str, capacity: int, cold: bool,
                          vol_id: int, partition_id: int, peers: list[int],
                          follower_read: bool = False):
        if name in self.volumes:
            raise MasterError(f"volume {name!r} exists")
        vol = VolumeView(name=name, vol_id=vol_id, owner=owner, capacity=capacity,
                         cold=cold, follower_read=follower_read)
        vol.meta_partitions.append(
            MetaPartitionView(partition_id, start=1, end=INF, peers=peers)
        )
        self.volumes[name] = vol
        for p in peers:
            if p in self.nodes:
                self.nodes[p].partition_count += 1
        return vol

    def _op_update_volume(self, name: str, capacity: int | None = None,
                          follower_read: bool | None = None,
                          qos_read_mbps: int | None = None,
                          qos_write_mbps: int | None = None):
        """Vol expand/shrink + option updates (master/vol.go updateVol).
        Capacity is an admin quota: usage enforcement stays with the
        write-time quota charges, so shrinking below current usage stops
        NEW growth rather than deleting data (the reference's semantics)."""
        vol = self.volumes.get(name)
        if vol is None:
            raise MasterError(f"unknown volume {name!r}")
        if capacity is not None:
            if capacity <= 0:
                raise MasterError("capacity must be positive")
            vol.capacity = int(capacity)
        if follower_read is not None:
            vol.follower_read = bool(follower_read)
        if qos_read_mbps is not None:
            vol.qos_read_mbps = max(0, int(qos_read_mbps))
        if qos_write_mbps is not None:
            vol.qos_write_mbps = max(0, int(qos_write_mbps))
        return vol

    def _op_remove_node(self, node_id: int):
        """Prune a registry entry (stale-node pruner); refuses while any
        partition still lists the node."""
        n = self.nodes.get(node_id)
        if n is None:
            return None
        for vol in self.volumes.values():
            for mp in vol.meta_partitions:
                if node_id in mp.peers:
                    raise MasterError(f"node {node_id} still hosts mp")
            for dp in vol.data_partitions:
                if node_id in dp.peers:
                    raise MasterError(f"node {node_id} still hosts dp")
        del self.nodes[node_id]
        return node_id

    def _op_split_partition(self, vol_name: str, partition_id: int, split_at: int,
                            new_partition_id: int, peers: list[int]):
        vol = self.volumes.get(vol_name)
        if vol is None:
            raise MasterError(f"unknown volume {vol_name!r}")
        tail = vol.meta_partitions[-1]
        if tail.partition_id != partition_id:
            raise MasterError("only the tail partition splits")
        tail.end = split_at
        vol.meta_partitions.append(
            MetaPartitionView(new_partition_id, start=split_at, end=INF, peers=peers)
        )
        return vol.meta_partitions[-1]

    def _op_set_partition_leader(self, vol_name: str, partition_id: int, leader: int | None):
        vol = self.volumes.get(vol_name)
        if vol is None:
            raise MasterError(f"unknown volume {vol_name!r}")
        for mp in vol.meta_partitions:
            if mp.partition_id == partition_id:
                mp.leader = leader
                return None
        raise MasterError(f"unknown partition {partition_id}")

    def _op_create_data_partition(self, vol_name: str, partition_id: int,
                                  peers: list[int], hosts: list[str]):
        vol = self.volumes.get(vol_name)
        if vol is None:
            raise MasterError(f"unknown volume {vol_name!r}")
        vol.data_partitions.append(
            DataPartitionView(partition_id, peers=peers, hosts=hosts))
        for p in peers:
            if p in self.nodes:
                self.nodes[p].partition_count += 1
        return vol.data_partitions[-1]

    def _op_update_dp_hosts(self, vol_name: str, partition_id: int, hosts: list[str]):
        vol = self.volumes.get(vol_name)
        if vol is None:
            raise MasterError(f"unknown volume {vol_name!r}")
        for dp in vol.data_partitions:
            if dp.partition_id == partition_id:
                dp.hosts = hosts
                return None
        raise MasterError(f"unknown data partition {partition_id}")

    def _op_set_dp_status(self, vol_name: str, partition_id: int, status: str):
        vol = self.volumes.get(vol_name)
        if vol is None:
            raise MasterError(f"unknown volume {vol_name!r}")
        for dp in vol.data_partitions:
            if dp.partition_id == partition_id:
                dp.status = status
                return None
        raise MasterError(f"unknown data partition {partition_id}")

    def _op_delete_volume(self, name: str):
        vol = self.volumes.pop(name, None)
        if vol is None:
            raise MasterError(f"unknown volume {name!r}")
        for u in self.users.values():
            if name in u.own_vols:
                u.own_vols.remove(name)
            u.authorized_vols.pop(name, None)
        return vol

    # -- decommission bookkeeping (master decommission APIs) -------------------

    def _op_set_node_status(self, node_id: int, status: str):
        n = self.nodes.get(node_id)
        if n is None:
            raise MasterError(f"unknown node {node_id}")
        n.status = status
        return None

    def _op_update_mp_peers(self, vol_name: str, partition_id: int,
                            peers: list[int]):
        vol = self.volumes.get(vol_name)
        if vol is None:
            raise MasterError(f"unknown volume {vol_name!r}")
        for mp in vol.meta_partitions:
            if mp.partition_id == partition_id:
                mp.peers = list(peers)
                return None
        raise MasterError(f"unknown partition {partition_id}")

    def _op_update_dp_members(self, vol_name: str, partition_id: int,
                              peers: list[int], hosts: list[str]):
        vol = self.volumes.get(vol_name)
        if vol is None:
            raise MasterError(f"unknown volume {vol_name!r}")
        for dp in vol.data_partitions:
            if dp.partition_id == partition_id:
                dp.peers = list(peers)
                dp.hosts = list(hosts)
                return None
        raise MasterError(f"unknown data partition {partition_id}")

    # -- user store (master/user.go analog) -----------------------------------

    def _op_create_user(self, user_id: str, access_key: str, secret_key: str,
                        user_type: str = "normal"):
        if user_id in self.users:
            raise MasterError(f"user {user_id!r} exists")
        if access_key in self.ak_index:
            raise MasterError("duplicate access key")
        u = UserInfo(user_id, access_key, secret_key, user_type)
        self.users[user_id] = u
        self.ak_index[access_key] = user_id
        return u

    def _op_delete_user(self, user_id: str):
        u = self.users.get(user_id)
        if u is None:
            raise MasterError(f"unknown user {user_id!r}")
        if u.own_vols:
            raise MasterError(f"user {user_id!r} still owns volumes {u.own_vols}")
        del self.users[user_id]
        self.ak_index.pop(u.access_key, None)
        return None

    def _op_user_own_vol(self, user_id: str, vol_name: str, add: bool):
        u = self.users.get(user_id)
        if u is None:
            raise MasterError(f"unknown user {user_id!r}")
        if add and vol_name not in u.own_vols:
            u.own_vols.append(vol_name)
        if not add and vol_name in u.own_vols:
            u.own_vols.remove(vol_name)
        return u

    def _op_update_user_policy(self, user_id: str, vol_name: str,
                               actions: list[str], grant: bool):
        u = self.users.get(user_id)
        if u is None:
            raise MasterError(f"unknown user {user_id!r}")
        if grant:
            u.authorized_vols[vol_name] = list(actions)
        else:
            u.authorized_vols.pop(vol_name, None)
        return u


class Master:
    """Leader-side service facade over the replicated MasterSM.

    The deployment wires `metanode_hook(partition_id, start, end, peers)` so
    partition creation reaches the metanodes (admin-task analog of
    master/cluster_task.go).
    """

    def __init__(self, raft: MultiRaft, sm: MasterSM):
        import threading

        self.raft = raft
        self.sm = sm
        # one migration at a time: an HTTP client retrying a slow decommission
        # must not start a second concurrent membership-change dance
        self._decomm_lock = threading.Lock()
        self.metanode_hook = None  # (pid, start, end, peers) -> None
        self.datanode_hook = None  # (pid, peers, hosts) -> None
        # decommission plumbing (deployment-wired, like the create hooks):
        # raft_config_hook(kind, pid, action, node_id, peers) proposes a
        # membership change on the partition's raft leader;
        # remove_partition_hook(kind, pid, node_id) drops the group+state on
        # the retired replica
        self.raft_config_hook = None
        self.remove_partition_hook = None
        # nodes already fully drained by the dead-node sweep; in-memory only
        # (rebuilt by one sweep after a restart), cleared on returning heartbeat.
        # Own micro-lock: heartbeat clears this set on its hot path and must
        # never wait out a migration-length _decomm_lock hold
        self._drained_lock = SanitizedLock(name="master.drained")
        self._dead_drained: set[int] = set()

    def _apply(self, op: str, **args):
        # rides raft group commit: concurrent admin/heartbeat handler threads
        # coalesce into shared WAL-flush + replication rounds on GroupID=1
        res = self.raft.propose(MASTER_GROUP, (op, args)).result(timeout=5)
        if res[0] == "err":
            raise MasterError(res[1])
        return res[1]

    def _apply_batch(self, ops: list[tuple[str, dict]], timeout: float = 5.0) -> list:
        """Propose many master ops as ONE drained raft batch (one WAL flush,
        one replication fan-out); results FIFO, each op failing alone."""
        futs = self.raft.propose_batch(MASTER_GROUP, [(op, args) for op, args in ops])
        out = []
        for fut in futs:
            res = fut.result(timeout=timeout)
            if res[0] == "err":
                raise MasterError(res[1])
            out.append(res[1])
        return out

    @property
    def is_leader(self) -> bool:
        return self.raft.is_leader(MASTER_GROUP)

    # -- node admin -----------------------------------------------------------

    def register_node(self, node_id: int, kind: str, addr: str = "",
                      raft_addr: str = "", zone: str = "") -> None:
        self._apply("register_node", node_id=node_id, kind=kind, addr=addr,
                    raft_addr=raft_addr, now=time.time(), zone=zone)

    def set_zone_domain(self, zone: str, domain: str) -> dict:
        """Assign/clear a zone's fault domain (replicated)."""
        return self._apply("set_zone_domain", zone=zone, domain=domain)

    def topology(self) -> dict:
        """zones -> nodesets -> node ids (master/topology.go view analog)."""
        out: dict[str, dict[int, list[int]]] = {}
        for n in self.sm.nodes.values():
            out.setdefault(n.zone, {}).setdefault(n.nodeset, []).append(n.node_id)
        for zone in out.values():
            for ids in zone.values():
                ids.sort()
        return out

    def heartbeat(self, node_id: int, partition_count: int = 0,
                  cursors: dict | None = None,
                  total_space: int | None = None,
                  used_space: int | None = None,
                  loads: dict | None = None):
        # a returning node may receive new placements again, so the dead-node
        # sweep must re-examine it if it dies a second time
        with self._drained_lock:
            self._dead_drained.discard(node_id)
        self._apply("heartbeat", node_id=node_id, partition_count=partition_count,
                    cursors=cursors, now=time.time(),
                    total_space=total_space, used_space=used_space,
                    loads=loads)

    def cluster_stat(self) -> dict:
        """Cluster/zone space + health rollup from node heartbeat reports.

        Reference: Cluster.scheduleToUpdateStatInfo (master/cluster.go:335)
        maintains this in a ticker; here the rollup derives on read — the
        node table is small and raft-replicated, so a loop would only add
        staleness."""
        def bucket():
            return {"total_space": 0, "used_space": 0, "nodes": 0, "active": 0}

        def kbucket():  # a rollup with nested per-kind sub-rollups
            return {**bucket(), "data": bucket(), "meta": bucket()}

        zones: dict[str, dict] = {}
        # per-kind rollups, like the reference's separate DataNodeStatInfo /
        # MetaNodeStatInfo (proto/model.go:162): metanode WAL-dir capacity
        # must not inflate storage capacity. The top-level total/used fields
        # remain the MERGED sum (all node kinds) for dashboard backward-compat.
        total = {**kbucket(), "meta_partitions": 0, "data_partitions": 0}
        for n in self.sm.nodes.values():
            z = zones.setdefault(n.zone, kbucket())
            for agg in (z, total, total[n.kind], z[n.kind]):
                agg["total_space"] += n.total_space
                agg["used_space"] += n.used_space
                agg["nodes"] += 1
                agg["active"] += 1 if n.status == "active" else 0
        for vol in self.sm.volumes.values():
            total["meta_partitions"] += len(vol.meta_partitions)
            total["data_partitions"] += len(vol.data_partitions)
        total["volumes"] = len(self.sm.volumes)
        total["zones"] = zones
        return total

    # -- volume admin -----------------------------------------------------------

    def domain_of(self, zone: str) -> str:
        """Fault domain owning a zone; unassigned zones are their own
        singleton domains (reference default-domain behavior)."""
        return self.sm.zone_domains.get(zone, zone)

    def _spread_by_zone(self, cands: list[NodeInfo], count: int,
                        kind: str) -> list[NodeInfo]:
        """Fault-domain- and zone-aware replica spread (master/topology.go
        placement contract + vol.go domain mode): with domain assignments
        present, replicas spread one-per-DOMAIN first — so a whole-domain
        loss (power/network failure of several co-dependent zones) leaves
        count-1 replicas when >= count domains exist — then per zone inside
        each domain; without assignments, domains degenerate to zones and
        the behavior is the plain zone spread. With fewer groups than
        `count`, round-robin so no group holds two replicas before every
        group holds one. (Decommission/dead-node replacements go through
        _pick_addition, which adds survivor-aware zone/domain bias.)"""
        if len(cands) < count:
            raise MasterError(f"need {count} {kind}nodes, have {len(cands)}")
        by_zone: dict[str, list[NodeInfo]] = {}
        for n in sorted(cands, key=lambda n: n.partition_count):
            by_zone.setdefault(n.zone, []).append(n)
        # group zones into domains; inside a domain, zones interleave so the
        # secondary spread (across zones within the picked domain) holds too
        by_domain: dict[str, list[NodeInfo]] = {}
        for zone, ns in by_zone.items():
            by_domain.setdefault(self.domain_of(zone), []).append(ns)
        groups = []
        for zone_lists in by_domain.values():
            zone_lists.sort(key=lambda ns: ns[0].partition_count)
            merged: list[NodeInfo] = []
            rank = 0
            while any(rank < len(ns) for ns in zone_lists):
                for ns in zone_lists:
                    if rank < len(ns):
                        merged.append(ns[rank])
                rank += 1
            groups.append(merged)
        groups.sort(key=lambda ns: ns[0].partition_count)
        picked: list[NodeInfo] = []
        if len(groups) >= count:
            for ns in groups[:count]:
                picked.append(ns[0])
        else:
            rank = 0
            while len(picked) < count:
                advanced = False
                for ns in groups:
                    if rank < len(ns):
                        picked.append(ns[rank])
                        advanced = True
                        if len(picked) == count:
                            break
                if not advanced:
                    raise MasterError(f"need {count} {kind}nodes, have {len(picked)}")
                rank += 1
        return picked

    def _pick_addition(self, kind: str, survivors: list[int],
                       prefer_zone: str | None = None,
                       exclude: set[int] = frozenset()) -> NodeInfo:
        """One extra replica for a partition that keeps `survivors`. With
        `prefer_zone` (a migration victim's zone) still healthy, stay there —
        the replacement preserves the existing spread by construction.
        Otherwise candidates rank by NOT sharing a fault domain with any
        survivor, then not sharing a zone, then emptiest — so whole-domain
        losses re-home (and under-replication heals) into a domain/zone that
        does not already hold a replica (vol.go domain placement on the
        repair path). `exclude` bars extra nodes (the migration VICTIM) from
        candidacy WITHOUT counting them in the spread ranking: the victim's
        domain is exactly where a replica is no longer held."""
        barred = set(survivors) | set(exclude)
        cands = [n for n in self.sm.nodes.values()
                 if n.kind == kind and n.schedulable
                 and n.node_id not in barred]
        if not cands:
            raise MasterError(f"need 1 {kind}node, have 0")
        if prefer_zone is not None:
            in_zone = [n for n in cands if n.zone == prefer_zone]
            if in_zone:
                return min(in_zone, key=lambda n: n.partition_count)
        surv_zones = {self.sm.nodes[p].zone for p in survivors
                      if p in self.sm.nodes}
        surv_doms = {self.domain_of(z) for z in surv_zones}
        return min(cands, key=lambda n: (
            self.domain_of(n.zone) in surv_doms,
            n.zone in surv_zones,
            n.partition_count,
        ))

    def _pick_meta_peers(self, count: int = 3,
                         exclude: set[int] = frozenset()) -> list[int]:
        metas = [n for n in self.sm.nodes.values()
                 if n.kind == "meta" and n.schedulable and n.node_id not in exclude]
        return [n.node_id
                for n in self._spread_by_zone(metas, count, "meta")]

    def _pick_data_peers(self, count: int = 3,
                         exclude: set[int] = frozenset()) -> list[NodeInfo]:
        datas = [n for n in self.sm.nodes.values()
                 if n.kind == "data" and n.schedulable and n.node_id not in exclude]
        return self._spread_by_zone(datas, count, "data")

    def create_volume(self, name: str, owner: str = "", capacity: int = 1 << 40,
                      cold: bool = False, data_partitions: int = 3,
                      follower_read: bool = False) -> VolumeView:
        # both ids in one drained raft batch: one commit round, not two
        vol_id, pid = self._apply_batch([("alloc_id", {}), ("alloc_id", {})])
        peers = self._pick_meta_peers()
        vol = self._apply(
            "create_volume", name=name, owner=owner, capacity=capacity, cold=cold,
            vol_id=vol_id, partition_id=pid, peers=peers,
            follower_read=follower_read,
        )
        if self.metanode_hook:
            self.metanode_hook(pid, 1, INF, peers)
        if not cold:
            for _ in range(data_partitions):
                self.create_data_partition(name)
        return self.sm.volumes[name]

    def create_data_partition(self, vol_name: str) -> DataPartitionView:
        """Place one 3-replica data partition on the emptiest datanodes
        (master/vol.go createDataPartition analog)."""
        dp_id = self._apply("alloc_id")
        nodes = self._pick_data_peers()
        view = self._apply(
            "create_data_partition", vol_name=vol_name, partition_id=dp_id,
            peers=[n.node_id for n in nodes], hosts=[n.addr for n in nodes],
        )
        if self.datanode_hook:
            self.datanode_hook(dp_id, view.peers, view.hosts)
        return view

    def _current_hosts(self, peers: list[int], stored: list[str]) -> list[str]:
        """Resolve replica addresses from the live node registry; datanode
        addresses change across restarts (ephemeral ports in tests)."""
        out = []
        for i, p in enumerate(peers):
            n = self.sm.nodes.get(p)
            out.append(n.addr if n and n.addr else (stored[i] if i < len(stored) else ""))
        return out

    def data_partition_views(self, vol_name: str) -> list[dict]:
        """Client-facing partition table (the ExtentClient refresh feed)."""
        vol = self.get_volume(vol_name)
        return [
            {"pid": dp.partition_id, "peers": list(dp.peers),
             "hosts": self._current_hosts(dp.peers, dp.hosts)}
            for dp in vol.data_partitions if dp.status == "rw"
        ]

    def refresh_dp_hosts(self) -> int:
        """Re-resolve stored dp.hosts from the registry (restart path)."""
        if not self.is_leader:
            return 0
        fixed = 0
        for vol in list(self.sm.volumes.values()):
            for dp in vol.data_partitions:
                hosts = self._current_hosts(dp.peers, dp.hosts)
                if hosts != dp.hosts:
                    self._apply("update_dp_hosts", vol_name=vol.name,
                                partition_id=dp.partition_id, hosts=hosts)
                    fixed += 1
        return fixed

    def get_volume(self, name: str) -> VolumeView:
        vol = self.sm.volumes.get(name)
        if vol is None:
            raise MasterError(f"unknown volume {name!r}")
        return vol

    def delete_volume(self, name: str) -> None:
        self._apply("delete_volume", name=name)

    # -- user admin (master/user.go analog) -----------------------------------

    def create_user(self, user_id: str, user_type: str = "normal",
                    access_key: str | None = None,
                    secret_key: str | None = None) -> UserInfo:
        import secrets
        import string

        alphabet = string.ascii_letters + string.digits
        # caller-supplied credentials are allowed (deterministic keys let
        # an operator declare them in a gateway's CFS_QOS_TENANTS before
        # the user exists); otherwise mint random ones
        ak = access_key or "".join(secrets.choice(alphabet)
                                   for _ in range(16))
        sk = secret_key or "".join(secrets.choice(alphabet)
                                   for _ in range(32))
        self._apply("create_user", user_id=user_id, access_key=ak,
                    secret_key=sk, user_type=user_type)
        return self.sm.users[user_id]

    def delete_user(self, user_id: str) -> None:
        self._apply("delete_user", user_id=user_id)

    def get_user(self, user_id: str) -> UserInfo:
        u = self.sm.users.get(user_id)
        if u is None:
            raise MasterError(f"unknown user {user_id!r}")
        return u

    def user_by_ak(self, access_key: str) -> UserInfo:
        uid = self.sm.ak_index.get(access_key)
        if uid is None:
            raise MasterError(f"unknown access key {access_key!r}")
        return self.sm.users[uid]

    def update_user_policy(self, user_id: str, vol_name: str,
                           actions: list[str], grant: bool = True) -> UserInfo:
        self._apply("update_user_policy", user_id=user_id, vol_name=vol_name,
                    actions=list(actions), grant=grant)
        return self.sm.users[user_id]

    def set_vol_owner(self, user_id: str, vol_name: str, add: bool = True) -> None:
        self._apply("user_own_vol", user_id=user_id, vol_name=vol_name, add=add)

    # -- decommission (master decommission APIs + migrate orchestration) -------
    #
    # The reference drains a node by re-homing every partition replica it
    # hosts (master decommission flows in cluster.go/vol.go). Per partition
    # the safe single-server dance is: create the group on the replacement
    # (it catches up via raft snapshot/appends) -> propose add(replacement)
    # -> propose remove(victim) -> drop state on the victim -> record the new
    # membership. Chain data (hot extents) back-fills through the extent
    # repair sweep once the replacement is in the hosts list.

    def decommission_metanode(self, node_id: int) -> int:
        if self.sm.nodes.get(node_id) is None:
            raise MasterError(f"unknown node {node_id}")
        self._apply("set_node_status", node_id=node_id, status="decommissioned")
        with self._decomm_lock:
            moved = self._migrate_metanode(node_id)
        from chubaofs_tpu.utils import events

        events.emit("node_decommissioned", events.SEV_WARNING,
                    entity=f"node{node_id}",
                    detail={"node_id": node_id, "kind": "meta",
                            "moved": moved})
        return moved

    def _migrate_metanode(self, node_id: int) -> int:
        moved = 0
        for vol in list(self.sm.volumes.values()):
            for mp in vol.meta_partitions:
                if node_id not in mp.peers:
                    continue
                survivors = [p for p in mp.peers if p != node_id]
                repl = self._pick_addition(
                    "meta", survivors, exclude={node_id},
                    prefer_zone=self.sm.nodes[node_id].zone).node_id
                new_peers = survivors + [repl]
                if self.metanode_hook:
                    # replacement-only create with the final membership
                    self.metanode_hook(mp.partition_id, mp.start, mp.end,
                                       new_peers, only=repl)
                if self.raft_config_hook:
                    self.raft_config_hook("meta", mp.partition_id, "add",
                                          repl, mp.peers)
                    # contact set for the remove must still include the victim:
                    # it is often the group's raft leader and must propose its
                    # own removal (then step down on apply)
                    self.raft_config_hook("meta", mp.partition_id, "remove",
                                          node_id, mp.peers + [repl])
                if self.remove_partition_hook:
                    self.remove_partition_hook("meta", mp.partition_id, node_id)
                self._apply("update_mp_peers", vol_name=vol.name,
                            partition_id=mp.partition_id, peers=new_peers)
                moved += 1
        return moved

    def decommission_datanode(self, node_id: int) -> int:
        if self.sm.nodes.get(node_id) is None:
            raise MasterError(f"unknown node {node_id}")
        self._apply("set_node_status", node_id=node_id, status="decommissioned")
        with self._decomm_lock:
            moved = self._migrate_datanode(node_id)
        from chubaofs_tpu.utils import events

        events.emit("node_decommissioned", events.SEV_WARNING,
                    entity=f"node{node_id}",
                    detail={"node_id": node_id, "kind": "data",
                            "moved": moved})
        return moved

    def _move_dp_replica(self, vol, dp, node_id: int,
                         prefer_zone: str | None = None,
                         repl: NodeInfo | None = None,
                         reason: str = "decommission") -> None:
        """Move one dp replica off node_id (decommission, dead-node re-home,
        spread-repair and hot-volume rebalance all share this step). An
        explicit `repl` (the rebalancer's load-ranked pick) skips the
        zone/domain-ranked _pick_addition. `reason` tags the timeline event
        so a rebalance move and a decommission drain are distinguishable
        forensics."""
        if repl is None:
            repl = self._pick_addition(
                "data", [p for p in dp.peers if p != node_id],
                exclude={node_id},
                prefer_zone=prefer_zone)
        idx = dp.peers.index(node_id)
        new_peers = [p for p in dp.peers if p != node_id] + [repl.node_id]
        hosts = self._current_hosts(dp.peers, dp.hosts)
        new_hosts = [h for i, h in enumerate(hosts) if i != idx] + [repl.addr]
        if self.datanode_hook:
            self.datanode_hook(dp.partition_id, new_peers, new_hosts,
                               only=repl.node_id)
        if self.raft_config_hook:
            self.raft_config_hook("data", dp.partition_id, "add",
                                  repl.node_id, dp.peers)
            # include the victim in the contact set (see metanode path)
            self.raft_config_hook("data", dp.partition_id, "remove",
                                  node_id, dp.peers + [repl.node_id])
        if self.remove_partition_hook:
            self.remove_partition_hook("data", dp.partition_id, node_id)
        self._apply("update_dp_members", vol_name=vol.name,
                    partition_id=dp.partition_id, peers=new_peers,
                    hosts=new_hosts)
        if self.datanode_hook:
            # idempotent re-send refreshes peers/hosts on survivors
            # (their local meta still lists the victim)
            self.datanode_hook(dp.partition_id, new_peers, new_hosts)
        from chubaofs_tpu.utils import events

        events.emit("partition_moved", entity=f"dp{dp.partition_id}",
                    detail={"partition": dp.partition_id, "vol": vol.name,
                            "victim": node_id, "replacement": repl.node_id,
                            "reason": reason})

    def _migrate_datanode(self, node_id: int) -> int:
        moved = 0
        zone = self.sm.nodes[node_id].zone
        for vol in list(self.sm.volumes.values()):
            for dp in vol.data_partitions:
                if node_id not in dp.peers:
                    continue
                self._move_dp_replica(vol, dp, node_id, prefer_zone=zone,
                                      reason="decommission")
                moved += 1
        return moved

    def check_replica_spread(self) -> int:
        """Spread-repair sweep: a partition whose replicas CONCENTRATE in one
        fault domain — the residue of re-homing while several domains were
        dark — moves a doubled replica into an unrepresented healthy domain
        once one exists again (the reference's balance machinery applied to
        the domain axis). Data partitions only: mp moves are heavier
        (snapshot transfer) and the same residue heals on the next mp
        migration anyway."""
        if not self.is_leader:
            return 0
        moved = 0
        for vol in list(self.sm.volumes.values()):
            for dp in vol.data_partitions:
                by_dom: dict[str, list[int]] = {}
                for p in dp.peers:
                    n = self.sm.nodes.get(p)
                    if n is None or not n.schedulable:
                        continue  # dead peers are the re-home sweep's job
                    by_dom.setdefault(self.domain_of(n.zone), []).append(p)
                doubled = [ps for ps in by_dom.values() if len(ps) >= 2]
                if not doubled:
                    continue
                free_doms = {
                    self.domain_of(n.zone)
                    for n in self.sm.nodes.values()
                    if n.kind == "data" and n.schedulable
                    and n.node_id not in dp.peers
                } - set(by_dom)
                if not free_doms:
                    continue
                victim = max(
                    doubled[0],
                    key=lambda p: self.sm.nodes[p].partition_count)
                try:
                    self._move_dp_replica(vol, dp, victim,
                                          reason="spread_repair")
                    moved += 1
                except MasterError:
                    pass  # no capacity after all; retried next sweep
        return moved

    # -- hot-volume spreading (the capacity harness's actuator) -----------------

    def data_node_loads(self) -> dict[int, float]:
        """node_id -> total ops in the last heartbeat window, schedulable
        datanodes only — the per-node ops-spread view cfs-capacity's A/B
        measures (and rebalance_hot acts on)."""
        return {n.node_id: sum(n.loads.values())
                for n in self.sm.nodes.values()
                if n.kind == "data" and n.schedulable}

    def _find_dp(self, pid: int):
        for vol in self.sm.volumes.values():
            for dp in vol.data_partitions:
                if dp.partition_id == pid:
                    return vol, dp
        return None, None

    def rebalance_hot(self, factor: float = 1.5, max_moves: int = 2) -> int:
        """Hot-volume spreading under skewed load: any schedulable datanode
        whose heartbeat-window op load exceeds `factor` x the mean sheds its
        hottest data-partition replicas onto the coldest nodes not already
        hosting them, through the same create->raft-add->raft-remove->drop
        migration dance decommission uses (_move_dp_replica). Zipfian access
        concentrates leaders; this is the knob that actually fixes the
        hotspots the capacity harness finds. A move must strictly improve
        the pair (target load + partition load < source load) or it is
        skipped — the sweep converges instead of ping-ponging replicas.
        Bounded at `max_moves` per sweep so rebalancing traffic (replica
        catch-up rides the repair path) never dominates foreground IO.
        Domain concentration a load-ranked pick may introduce is healed by
        check_replica_spread, the same residue contract re-homing has."""
        if not self.is_leader:
            return 0
        with self._decomm_lock:
            datas = {n.node_id: n for n in self.sm.nodes.values()
                     if n.kind == "data" and n.schedulable}
            if len(datas) < 2:
                return 0
            # local bookkeeping copy: replicated NodeInfo.loads must only
            # mutate inside raft apply, but the sweep still needs to account
            # its own moves so one pass doesn't dogpile a single cold node
            loads = {nid: sum(n.loads.values()) for nid, n in datas.items()}
            total = sum(loads.values())
            if total <= 0:
                return 0
            mean = total / len(loads)
            moved = 0
            for nid in sorted(loads, key=loads.get, reverse=True):
                if moved >= max_moves:
                    break
                # snapshot ONCE: the raft apply thread REPLACES n.loads on
                # every heartbeat, and a double attribute read (iterable +
                # key fn) could straddle the swap — .get(old_pid) -> None
                # would crash the sort mid-sweep
                pid_loads = dict(datas[nid].loads)
                for pid in sorted(pid_loads, key=pid_loads.get, reverse=True):
                    if loads[nid] <= factor * mean:
                        break  # shed enough; next hot node
                    pid_load = pid_loads.get(pid, 0.0)
                    if pid_load <= 0:
                        break
                    vol, dp = self._find_dp(pid)
                    if dp is None or nid not in dp.peers:
                        continue  # meta pid, or a replica already moved
                    cands = [n for n in datas.values()
                             if n.node_id not in dp.peers]
                    if not cands:
                        continue
                    target = min(cands, key=lambda n: (loads[n.node_id],
                                                       n.partition_count))
                    if loads[target.node_id] + pid_load >= loads[nid]:
                        continue  # would not strictly improve the pair
                    try:
                        self._move_dp_replica(vol, dp, nid, repl=target,
                                              reason="rebalance_hot")
                    except MasterError:
                        continue  # no capacity after all; retried next sweep
                    loads[nid] -= pid_load
                    loads[target.node_id] += pid_load
                    moved += 1
                    if moved >= max_moves:
                        break
            return moved

    # -- background checks (scheduleTask loop analogs) --------------------------

    def check_meta_partitions(self) -> int:
        """Split tail partitions whose cursor nears the end (cursor growth)."""
        if not self.is_leader:
            return 0
        splits = 0
        for vol in list(self.sm.volumes.values()):
            tail = vol.meta_partitions[-1]
            cursor = max(
                (n.cursors.get(tail.partition_id, 0) for n in self.sm.nodes.values()),
                default=0,
            )
            bound = tail.start + META_RANGE_STEP
            if cursor and cursor >= bound - SPLIT_HEADROOM:
                new_pid = self._apply("alloc_id")
                peers = self._pick_meta_peers()
                split_at = cursor + SPLIT_HEADROOM
                self._apply(
                    "split_partition", vol_name=vol.name, partition_id=tail.partition_id,
                    split_at=split_at, new_partition_id=new_pid, peers=peers,
                )
                if self.metanode_hook:
                    self.metanode_hook(new_pid, split_at, INF, peers)
                splits += 1
        return splits

    def check_node_liveness(self, timeout: float = 10.0,
                            now: float | None = None) -> list[int]:
        """Mark nodes whose heartbeat went stale as INACTIVE so placement and
        client views route around them; a returning heartbeat reactivates
        (master/cluster.go scheduleToCheckHeartbeat analog). Decommissioned
        nodes are left alone. Returns the node ids newly marked."""
        if not self.is_leader:
            return []
        now = time.time() if now is None else now
        out = []
        for n in list(self.sm.nodes.values()):
            if n.status != "active":
                continue
            if n.last_heartbeat and now - n.last_heartbeat > timeout:
                self._apply("set_node_status", node_id=n.node_id,
                            status="inactive")
                out.append(n.node_id)
        return out

    def check_data_partitions(self) -> int:
        """Demote data partitions with a non-schedulable replica to read-only
        and promote them back when every peer is healthy (the reference's
        checkDataPartitions loop marking partitions unavailable). Clients only
        see rw partitions (data_partition_views), so writes route around dead
        replicas while reads still work through the survivors."""
        if not self.is_leader:
            return 0
        changed = 0
        for vol in list(self.sm.volumes.values()):
            for dp in vol.data_partitions:
                healthy = all(
                    self.sm.nodes.get(p) is not None
                    and self.sm.nodes[p].status == "active"
                    for p in dp.peers)
                want = "rw" if healthy else "ro"
                if dp.status in ("rw", "ro") and dp.status != want:
                    self._apply("set_dp_status", vol_name=vol.name,
                                partition_id=dp.partition_id, status=want)
                    changed += 1
        return changed

    def _replica_count(self, node_id: int) -> int:
        """Partition replicas currently homed on node_id (any kind)."""
        c = 0
        for vol in list(self.sm.volumes.values()):
            for mp in vol.meta_partitions:
                if node_id in mp.peers:
                    c += 1
            for dp in vol.data_partitions:
                if node_id in dp.peers:
                    c += 1
        return c

    def check_dead_node_replicas(self, dead_after: float = 60.0,
                                 now: float | None = None) -> int:
        """Durable auto-repair for nodes that STAY dead (reference
        scheduleToCheckDataReplicas + the decommission flows, cluster.go:347):
        liveness marks a stale node inactive within seconds (writes route
        around it, dps demote to ro); once the outage exceeds ``dead_after``
        this loop re-homes every replica the node held onto healthy peers,
        reusing the decommission dance. The node record stays ``inactive`` —
        a returning node reactivates on its next heartbeat and simply hosts
        nothing (its stale raft groups reject it; the partitions were moved).
        Per-node failures (e.g. no spare peers yet) keep whatever progress
        was made and retry on the next sweep. Fully-drained nodes enter an
        in-memory skip set (cleared by a returning heartbeat) so a cluster
        with long-dead nodes doesn't rescan every partition each tick.
        Returns replicas actually moved (counted by before/after census, so
        partial drains are reported honestly)."""
        if not self.is_leader:
            return 0
        now = time.time() if now is None else now
        moved = 0
        for n in list(self.sm.nodes.values()):
            with self._drained_lock:
                drained = n.node_id in self._dead_drained
            if n.status != "inactive" or drained:
                continue
            if not n.last_heartbeat or now - n.last_heartbeat < dead_after:
                continue
            with self._decomm_lock:
                before = self._replica_count(n.node_id)
                if before == 0:
                    with self._drained_lock:
                        self._dead_drained.add(n.node_id)
                    continue
                try:
                    if n.kind == "meta":
                        self._migrate_metanode(n.node_id)
                    else:
                        self._migrate_datanode(n.node_id)
                except MasterError:
                    pass  # partial progress kept; retried next sweep
                remaining = self._replica_count(n.node_id)
                moved += before - remaining
                if remaining == 0:
                    with self._drained_lock:
                        self._dead_drained.add(n.node_id)
        return moved

    def update_volume(self, name: str, capacity: int | None = None,
                      follower_read: bool | None = None,
                      qos_read_mbps: int | None = None,
                      qos_write_mbps: int | None = None) -> VolumeView:
        """Vol expand/shrink + per-volume client QoS (master/vol.go
        updateVol; limits flow master -> client via the volume view)."""
        return self._apply(
            "update_volume", name=name, capacity=capacity,
            follower_read=follower_read, qos_read_mbps=qos_read_mbps,
            qos_write_mbps=qos_write_mbps)

    def ensure_replica_counts(self, target: int = 3) -> int:
        """Partition-replica-count checker (scheduleToCheckDataReplicas'
        under-replication half): any mp/dp below `target` peers gains a
        replacement via the migrate machinery. Partial migrations and
        operator surgery leave these behind; the sweep heals them."""
        if not self.is_leader:
            return 0
        added = 0
        for vol in list(self.sm.volumes.values()):
            for mp in vol.meta_partitions:
                while len(mp.peers) < target:
                    try:
                        repl = self._pick_addition("meta", mp.peers).node_id
                    except MasterError:
                        break  # not enough healthy nodes; retried next sweep
                    new_peers = mp.peers + [repl]
                    if self.metanode_hook:
                        self.metanode_hook(mp.partition_id, mp.start, mp.end,
                                           new_peers, only=repl)
                    if self.raft_config_hook:
                        self.raft_config_hook("meta", mp.partition_id, "add",
                                              repl, mp.peers)
                    self._apply("update_mp_peers", vol_name=vol.name,
                                partition_id=mp.partition_id, peers=new_peers)
                    mp = [m for m in self.sm.volumes[vol.name].meta_partitions
                          if m.partition_id == mp.partition_id][0]
                    added += 1
            for dp in vol.data_partitions:
                while len(dp.peers) < target:
                    try:
                        repl = self._pick_addition("data", dp.peers)
                    except MasterError:
                        break
                    new_peers = dp.peers + [repl.node_id]
                    new_hosts = self._current_hosts(dp.peers, dp.hosts) + [repl.addr]
                    if self.datanode_hook:
                        self.datanode_hook(dp.partition_id, new_peers,
                                           new_hosts, only=repl.node_id)
                    if self.raft_config_hook:
                        self.raft_config_hook("data", dp.partition_id, "add",
                                              repl.node_id, dp.peers)
                    self._apply("update_dp_members", vol_name=vol.name,
                                partition_id=dp.partition_id, peers=new_peers,
                                hosts=new_hosts)
                    dp = [d for d in self.sm.volumes[vol.name].data_partitions
                          if d.partition_id == dp.partition_id][0]
                    added += 1
        return added

    def prune_stale_nodes(self, stale_after: float = 3600.0,
                          now: float | None = None) -> list[int]:
        """Stale-node pruner: registry entries that are inactive or
        decommissioned, host NO partition replicas, and have been silent
        past `stale_after` are removed — a re-registration starts clean.
        (The reference's operator-driven node removal, automated for the
        already-drained case.)"""
        if not self.is_leader:
            return []
        now = time.time() if now is None else now
        pruned = []
        for n in list(self.sm.nodes.values()):
            if n.status == "active":
                continue
            if now - n.last_heartbeat < stale_after:
                continue
            if self._replica_count(n.node_id):
                continue
            try:
                self._apply("remove_node", node_id=n.node_id)
                with self._drained_lock:
                    self._dead_drained.discard(n.node_id)
                pruned.append(n.node_id)
            except MasterError:
                pass
        return pruned

    def orphan_partitions(self) -> dict[int, list[int]]:
        """node_id -> partition ids the node REPORTS (heartbeat cursors)
        but should not host: either no volume records the pid (failed
        volume delete) or the pid's recorded peer set no longer includes
        the node (a migration whose remove task never reached the then-dead
        victim). Per-NODE detection, so stale replicas left behind by
        re-homes are found, not just fully-deleted-volume leftovers. The
        daemon's sweep sends remove tasks for them (scheduleTask junk
        cleanup analog)."""
        peers_of: dict[int, set[int]] = {}
        for vol in self.sm.volumes.values():
            for mp in vol.meta_partitions:
                peers_of[mp.partition_id] = set(mp.peers)
            for dp in vol.data_partitions:
                peers_of[dp.partition_id] = set(dp.peers)
        out: dict[int, list[int]] = {}
        for n in self.sm.nodes.values():
            orphans = [pid for pid in n.cursors
                       if n.node_id not in peers_of.get(pid, frozenset())]
            if orphans:
                out[n.node_id] = sorted(orphans)
        return out

    def refresh_leaders(self, leader_of) -> None:
        """Record partition leaders into the view (client routing hint)."""
        if not self.is_leader:
            return
        for vol in list(self.sm.volumes.values()):
            for mp in vol.meta_partitions:
                lead = leader_of(mp.partition_id)
                if lead != mp.leader:
                    self._apply(
                        "set_partition_leader", vol_name=vol.name,
                        partition_id=mp.partition_id, leader=lead,
                    )
