"""Master HTTP admin API — the operator/client face of the resource manager.

Reference counterpart: master/http_server.go:246,417 + master/api_service.go
(5,186 LoC of HTTP/JSON handlers). Kept: the reference's URL namespace
(/admin/*, /client/*, /dataNode/*, /metaNode/*, /user/*), its JSON envelope
{"code": 0, "msg": "success", "data": ...}, and its leader-proxy behavior —
a follower master answers with the leader's address so clients re-aim
(master/http_server.go's proxy; our RPCClient follows the hint). Changed:
handlers are thin wrappers over the Master facade; the reference's ~180
endpoints collapse to the set the CLI/console/objectnode/SDK actually use.
"""

from __future__ import annotations

from dataclasses import asdict

from chubaofs_tpu.master.master import MASTER_GROUP, Master, MasterError
from chubaofs_tpu.rpc.client import RPCClient
from chubaofs_tpu.rpc.errors import HTTPError
from chubaofs_tpu.rpc.router import Request, Response, Router
from chubaofs_tpu.rpc.server import RPCServer

CODE_OK = 0
CODE_ERR = 1
CODE_NOT_LEADER = 2
CODE_BUSY = 3  # QoS limit hit; clients back off and retry (master/limiter.go)
CODE_DENIED = 4  # missing/invalid capability ticket (authnode-gated admin op)


def envelope(data=None, code: int = CODE_OK, msg: str = "success") -> dict:
    return {"code": code, "msg": msg, "data": data}


class MasterAPI:
    """HTTP service bound to one master replica."""

    def __init__(self, master: Master, leader_addr_of=None,
                 service_secret: bytes | None = None, qos=None,
                 admin_ticket_key: bytes | None = None):
        """leader_addr_of: node_id -> admin-API address, for leader redirects.
        service_secret gates the credential-bearing /user/akInfo endpoint
        (objectnode signs with it); without one, akInfo only answers loopback
        clients — S3 secrets must never be harvestable off the open admin API
        (round-1 advisory). qos: a utils.ratelimit.KeyedLimiter with per-route
        op limits (master/limiter.go analog); None = unlimited.
        admin_ticket_key: the master's authnode SERVICE key — when set,
        mutating admin routes demand an x-cfs-ticket header carrying the
        master:admin capability (authnode/api_service.go:37 gating); None
        keeps the shared-secret-only deployment mode."""
        from chubaofs_tpu.utils.ratelimit import KeyedLimiter

        self.master = master
        self.leader_addr_of = leader_addr_of or (lambda node_id: "")
        self.service_secret = service_secret
        self.qos = qos if qos is not None else KeyedLimiter()
        self.admin_ticket_key = admin_ticket_key
        self.router = self._build()

    # -- plumbing -------------------------------------------------------------

    def _build(self) -> Router:
        r = Router()
        g = r.get
        g("/metrics", self.metrics)  # raw text/plain, no JSON envelope
        g("/admin/getCluster", self._w(self.get_cluster, leader=False))
        g("/admin/getClusterStat", self._w(self.get_cluster_stat, leader=False))
        g("/admin/getTopology", self._w(self.get_topology, leader=False))
        g("/admin/getZoneDomains", self._w(self.get_zone_domains, leader=False))
        g("/admin/setZoneDomain", self._w(self.set_zone_domain, admin=True))
        g("/admin/getIp", self._w(self.get_ip, leader=False))
        g("/admin/createVol", self._w(self.create_vol, admin=True))
        g("/admin/updateVol", self._w(self.update_vol, admin=True))
        g("/admin/deleteVol", self._w(self.delete_vol, admin=True))
        g("/admin/getVol", self._w(self.get_vol, leader=False))
        g("/admin/listVols", self._w(self.list_vols, leader=False))
        g("/admin/createDataPartition", self._w(self.create_dp, admin=True))
        g("/client/partitions", self._w(self.client_partitions, leader=False))
        g("/client/metaPartitions", self._w(self.client_meta_partitions, leader=False))
        g("/client/vol", self._w(self.get_vol, leader=False))
        # topology mutations are gated too, but under the NODE capability:
        # a datanode's on-disk credential must let it register/heartbeat
        # without also granting deleteVol-class admin power (least privilege)
        g("/dataNode/add", self._w(self.add_node_data, admin=True, cap="node"))
        g("/metaNode/add", self._w(self.add_node_meta, admin=True, cap="node"))
        g("/node/heartbeat", self._w(self.node_heartbeat, admin=True, cap="node"))
        g("/dataNode/decommission", self._w(self.decommission_data, admin=True))
        g("/metaNode/decommission", self._w(self.decommission_meta, admin=True))
        g("/dataNode/rebalanceHot", self._w(self.rebalance_hot, admin=True))
        g("/metaPartition/rebalance", self._w(self.rebalance_meta, admin=True))
        g("/metaPartition/split", self._w(self.split_meta, admin=True))
        g("/user/create", self._w(self.user_create, admin=True))
        g("/user/delete", self._w(self.user_delete, admin=True))
        g("/user/info", self._w(self.user_info, leader=False))
        g("/user/akInfo", self._w(self.user_ak_info, leader=False))
        g("/user/updatePolicy", self._w(self.user_update_policy, admin=True))
        g("/user/list", self._w(self.user_list, leader=False))
        # recent slow-op audit of THIS master process (the RPCServer mounts
        # the same data at /slowops on every daemon; this alias keeps the
        # master's ops surface under its /api namespace for cfs-stat)
        g("/api/slowops", self.slowops)
        from chubaofs_tpu.master.gapi import GraphQLAPI

        r.post("/graphql", GraphQLAPI(self.master).handle)
        return r

    def slowops(self, req: Request):
        from chubaofs_tpu.utils.auditlog import recent_slowops

        # QoS-gated like every /api route (each request re-reads the slowop
        # rotor from disk — a polling loop must not hammer the master
        # unthrottled), but WITHOUT the envelope: the response shape matches
        # the daemon-side /slowops side-door so cfs-stat and the console
        # rollup parse both identically
        if not self.qos.allow(req.path):
            return Response.json({"slowops": [],
                                  "error": "rate limit exceeded"}, status=429)
        return Response.json({"slowops": recent_slowops(req.q_int("n", 100))})

    def _w(self, fn, leader: bool = True, admin: bool = False,
           cap: str = "admin"):
        """Wrap a handler: QoS gate + ticket gate + leader gate + MasterError
        → envelope. `cap` names the capability the ticket must carry
        ("master:admin" for destructive ops, "master:node" for node
        registration/heartbeat — node credentials never hold admin power)."""

        def handler(req: Request):
            if not self.qos.allow(req.path):
                return Response.json(
                    envelope(None, CODE_BUSY, "rate limit exceeded"), status=200)
            if admin and self.admin_ticket_key is not None:
                from chubaofs_tpu.authnode.server import verify_ticket

                try:
                    verify_ticket("master", self.admin_ticket_key,
                                  req.header("x-cfs-ticket"), action=cap)
                except Exception as e:  # TicketError, malformed b64, ...
                    return Response.json(
                        envelope(None, CODE_DENIED,
                                 f"master:{cap} ticket required: {e}"),
                        status=200)
            if leader and not self.master.is_leader:
                lead = self.master.raft.leader_of(MASTER_GROUP)
                addr = self.leader_addr_of(lead) if lead is not None else ""
                return Response.json(
                    envelope({"leader": addr}, CODE_NOT_LEADER, "not leader"),
                    status=200)
            try:
                return Response.json(envelope(fn(req)))
            except MasterError as e:
                return Response.json(envelope(None, CODE_ERR, str(e)))

        return handler

    # -- handlers -------------------------------------------------------------

    def get_cluster(self, req: Request):
        sm = self.master.sm
        return {
            "leader_id": self.master.raft.leader_of(MASTER_GROUP),
            "nodes": [asdict(n) for n in sm.nodes.values()],
            "volumes": sorted(sm.volumes),
            "users": sorted(sm.users),
        }

    def get_cluster_stat(self, req: Request):
        """Space/health rollup (ref /admin/getClusterStat, statinfo loop)."""
        return self.master.cluster_stat()

    def get_topology(self, req: Request):
        """zones -> nodesets -> node ids (master/topology.go view); the ONE
        grouping implementation (Master.topology), never re-derived by clients."""
        return {zone: {str(ns): ids for ns, ids in sets.items()}
                for zone, sets in self.master.topology().items()}

    def get_ip(self, req: Request):
        return {"cluster": "chubaofs-tpu", "ip": req.remote}

    def metrics(self, req: Request) -> Response:
        """Prometheus exposition of the cluster rollups — the
        master/monitor_metrics.go analog, derived on scrape from the same
        replicated state the stat endpoints read (no ticker staleness).
        Served by every master (leader=False scrape-ability)."""
        from chubaofs_tpu.utils.exporter import Registry

        reg = Registry(cluster="", module="master")  # namespace cfs_master
        st = self.master.cluster_stat()
        for kind in ("data", "meta"):
            reg.gauge("total_space_bytes", {"kind": kind}).set(
                st[kind]["total_space"])
            reg.gauge("used_space_bytes", {"kind": kind}).set(
                st[kind]["used_space"])
            reg.gauge("nodes", {"kind": kind}).set(st[kind]["nodes"])
            reg.gauge("nodes_active", {"kind": kind}).set(st[kind]["active"])
        reg.gauge("volumes").set(st["volumes"])
        reg.gauge("meta_partitions").set(st["meta_partitions"])
        reg.gauge("data_partitions").set(st["data_partitions"])
        reg.gauge("is_leader").set(1 if self.master.is_leader else 0)
        for vol in self.master.sm.volumes.values():
            lv = {"volume": vol.name}
            reg.gauge("vol_capacity_bytes", lv).set(vol.capacity)
            reg.gauge("vol_meta_partitions", lv).set(len(vol.meta_partitions))
            reg.gauge("vol_data_partitions", lv).set(len(vol.data_partitions))
            reg.gauge("vol_dp_rw", lv).set(
                sum(1 for dp in vol.data_partitions if dp.status == "rw"))
        # the cluster rollups plus this PROCESS's role registries (raft drain
        # counters etc.) — one scrape covers both views of a master daemon
        from chubaofs_tpu.utils import exporter

        return Response(200, {"Content-Type": "text/plain; version=0.0.4"},
                        (reg.render() + exporter.render_all()).encode())

    def get_zone_domains(self, req: Request):
        """zone -> fault domain map (master/topology.go:43 domain mode)."""
        return dict(self.master.sm.zone_domains)

    def set_zone_domain(self, req: Request):
        zone = req.q("zone")
        if not zone:
            raise MasterError("missing ?zone")
        # absent != blank: only an EXPLICIT domain= clears the assignment
        # (a typo'd param name must not silently strip domain protection)
        if not req.has_q("domain"):
            raise MasterError("missing ?domain (pass domain= to clear)")
        doms = self.master.set_zone_domain(zone, req.q("domain"))
        known = {n.zone for n in self.master.sm.nodes.values()}
        return {"domains": doms,
                # a typo'd zone matches no node: report it so the operator
                # doesn't walk away believing domain tolerance is on
                "warning": ("" if zone in known else
                            f"zone {zone!r} matches no registered node")}

    def create_vol(self, req: Request):
        name = req.q("name")
        if not name:
            raise MasterError("missing ?name")
        owner = req.q("owner")
        vol = self.master.create_volume(
            name, owner=owner,
            capacity=int(req.q("capacity", str(1 << 40))),
            cold=req.q("volType") == "cold" or req.q("cold") == "true",
            data_partitions=int(req.q("dpCount", "3")),
            follower_read=req.q("followerRead") == "true",
        )
        if owner and owner in self.master.sm.users:
            self.master.set_vol_owner(owner, name, add=True)
        return self._vol_view(vol)

    def update_vol(self, req: Request):
        """Vol expand/shrink + option/QoS updates (ref /vol/update)."""
        name = req.q("name")
        if not name:
            raise MasterError("missing ?name")

        def opt_int(key):
            return int(req.q(key)) if req.has_q(key) else None

        fr = None
        if req.has_q("followerRead"):
            fr = req.q("followerRead") == "true"
        vol = self.master.update_volume(
            name, capacity=opt_int("capacity"), follower_read=fr,
            qos_read_mbps=opt_int("qosReadMbps"),
            qos_write_mbps=opt_int("qosWriteMbps"))
        return self._vol_view(vol)

    def delete_vol(self, req: Request):
        self.master.delete_volume(req.q("name"))
        return None

    def _vol_view(self, vol) -> dict:
        d = asdict(vol)
        # JSON has no int64 sentinel; surface the tail range end as -1
        for mp in d["meta_partitions"]:
            if mp["end"] >= (1 << 62):
                mp["end"] = -1
            if mp.get("end0", 0) >= (1 << 62):
                mp["end0"] = -1
        return d

    def get_vol(self, req: Request):
        return self._vol_view(self.master.get_volume(req.q("name")))

    def list_vols(self, req: Request):
        return [
            {"name": v.name, "owner": v.owner, "capacity": v.capacity,
             "cold": v.cold, "mp_count": len(v.meta_partitions),
             "dp_count": len(v.data_partitions)}
            for v in self.master.sm.volumes.values()
        ]

    def create_dp(self, req: Request):
        return asdict(self.master.create_data_partition(req.q("name")))

    def client_partitions(self, req: Request):
        return self.master.data_partition_views(req.q("name"))

    def client_meta_partitions(self, req: Request):
        vol = self.master.get_volume(req.q("name"))
        return self._vol_view(vol)["meta_partitions"]

    def _add_node(self, req: Request, kind: str):
        node_id = int(req.q("id"))
        self.master.register_node(node_id, kind, req.q("addr"),
                                  raft_addr=req.q("raftAddr"),
                                  zone=req.q("zone"))
        return {"id": node_id}

    def add_node_data(self, req: Request):
        return self._add_node(req, "data")

    def add_node_meta(self, req: Request):
        return self._add_node(req, "meta")

    def node_heartbeat(self, req: Request):
        import json

        # absent param = "no cursor report" (leaves master state alone);
        # "{}" = an explicit empty report that WIPES the node's cursor set
        raw = req.q("cursors", "")
        cursors = json.loads(raw) if raw else None
        raw_loads = req.q("loads", "")
        raw_splits = req.q("splits", "")
        total = req.q("total_space", "")
        used = req.q("used_space", "")
        self.master.heartbeat(int(req.q("id")),
                              partition_count=int(req.q("partitions", "0")),
                              cursors=cursors,
                              total_space=int(total) if total else None,
                              used_space=int(used) if used else None,
                              loads=json.loads(raw_loads) if raw_loads else None,
                              splits=json.loads(raw_splits) if raw_splits
                              else None)
        return None

    def decommission_meta(self, req: Request):
        return {"migrated": self.master.decommission_metanode(int(req.q("id")))}

    def decommission_data(self, req: Request):
        return {"migrated": self.master.decommission_datanode(int(req.q("id")))}

    def rebalance_hot(self, req: Request):
        """One hot-volume spreading sweep (the capacity harness's knob);
        returns the moves made plus the per-node load view it acted on."""
        moved = self.master.rebalance_hot(
            factor=float(req.q("factor", "1.5")),
            max_moves=int(req.q("maxMoves", "2")))
        return {"moved": moved,
                "loads": {str(k): v
                          for k, v in self.master.data_node_loads().items()}}

    def rebalance_meta(self, req: Request):
        """One meta-partition migration sweep (hot metanodes shed their
        hottest partition replicas onto cold metanodes — ISSUE 15); returns
        the moves made plus the per-metanode load view it acted on."""
        moved = self.master.rebalance_meta(
            factor=float(req.q("factor", "1.5")),
            max_moves=int(req.q("maxMoves", "1")))
        return {"moved": moved,
                "loads": {str(k): v
                          for k, v in self.master.meta_node_loads().items()}}

    def split_meta(self, req: Request):
        """Load-split one named meta partition at its median live inode now
        (the bench/operator trigger; the CFS_META_SPLIT_OPS path drives the
        same machinery from heartbeat loads). Returns the sibling pid, 0
        when the partition declines (too few inodes / txns in flight)."""
        name = req.q("name")
        if not name:
            raise MasterError("missing ?name")
        try:
            pid = int(req.q("id"))
        except (TypeError, ValueError):
            raise MasterError("missing/bad ?id") from None
        return {"new_pid": self.master.split_meta_partition(name, pid)}

    @staticmethod
    def _user_view(u) -> dict:
        """Public user record: the secret key is returned ONLY at create time
        and over the gated akInfo path — list/info must not leak S3
        credentials through the unauthenticated admin API."""
        d = asdict(u)
        d.pop("secret_key", None)
        return d

    def user_create(self, req: Request):
        # create-time is the one moment the caller gets the secret back.
        # ak/sk may be caller-supplied (deterministic credentials, so an
        # operator can put the access keys in a gateway's CFS_QOS_TENANTS
        # BEFORE the user exists — cfs-capacity --s3 relies on it)
        return asdict(self.master.create_user(
            req.q("user"), req.q("type", "normal"),
            access_key=req.q("ak") or None,
            secret_key=req.q("sk") or None))

    def user_delete(self, req: Request):
        self.master.delete_user(req.q("user"))
        return None

    def user_info(self, req: Request):
        return self._user_view(self.master.get_user(req.q("user")))

    def user_ak_info(self, req: Request):
        from chubaofs_tpu.rpc.server import AUTH_HEADER, sign_path

        if self.service_secret is not None:
            import hmac as _hmac

            want = sign_path(self.service_secret, "/user/akInfo")
            if not _hmac.compare_digest(req.header(AUTH_HEADER), want):
                raise MasterError("akInfo requires the service secret")
        elif req.remote not in ("-", "127.0.0.1", "::1", "localhost"):
            raise MasterError(
                "akInfo without a configured serviceSecret answers loopback "
                "clients only")
        return asdict(self.master.user_by_ak(req.q("ak")))

    def user_update_policy(self, req: Request):
        actions = [a for a in req.q("actions").split(",") if a]
        u = self.master.update_user_policy(
            req.q("user"), req.q("vol"), actions,
            grant=req.q("grant", "true") != "false")
        return self._user_view(u)

    def user_list(self, req: Request):
        return [self._user_view(u) for u in self.master.sm.users.values()]

    def serve(self, addr: str) -> RPCServer:
        host, port = addr.rsplit(":", 1)
        srv = RPCServer(self.router, host=host, port=int(port))
        srv.start()
        return srv


class MasterClient:
    """sdk/master analog: follows the not-leader hint across replicas."""

    def __init__(self, hosts: list[str], retries: int = 4,
                 auth_secret: bytes | None = None,
                 admin_ticket=None):
        """admin_ticket: authnode capability ticket — a static b64 string, or
        a CALLABLE returning one (authnode.server.RenewingTicket) so daemons
        outlive TICKET_TTL; a callable with .refresh() gets one re-acquire
        attempt when the master answers CODE_DENIED."""
        self.auth_secret = auth_secret
        self.admin_ticket = admin_ticket
        self.rpc = RPCClient(hosts, retries=retries, auth_secret=auth_secret)
        self.leader_hint: str | None = None

    def _headers(self) -> dict:
        t = self.admin_ticket
        if t is None:
            return {}
        return {"x-cfs-ticket": t() if callable(t) else t}

    @staticmethod
    def _path(route: str, **params) -> str:
        """Build a query string with every value URL-encoded — volume/user
        names must not be able to smuggle extra parameters."""
        import urllib.parse

        q = urllib.parse.urlencode(
            {k: v for k, v in params.items() if v is not None})
        return f"{route}?{q}" if q else route

    def call(self, path: str) -> object:
        last_msg = "no reply"
        denied_retried = False
        for _ in range(4):
            if self.leader_hint:
                rpc = RPCClient([self.leader_hint], retries=1,
                                auth_secret=self.auth_secret)
                try:
                    out = rpc.get(path, headers=self._headers())
                except (HTTPError, OSError):
                    self.leader_hint = None
                    continue
            else:
                out = self.rpc.get(path, headers=self._headers())
            code = out.get("code")
            if code == CODE_OK:
                return out.get("data")
            if code == CODE_NOT_LEADER:
                hint = (out.get("data") or {}).get("leader") or None
                if hint and hint != self.leader_hint:
                    self.leader_hint = hint
                    continue
                self.leader_hint = None
                import time

                time.sleep(0.1)
                continue
            if code == CODE_BUSY:
                # QoS throttle, not a hard failure: back off and retry
                import time

                last_msg = out.get("msg", "rate limited")
                time.sleep(0.2)
                continue
            if code == CODE_DENIED and callable(self.admin_ticket) \
                    and not denied_retried:
                # expired/stale ticket with a renewing provider: one
                # re-acquire, then retry the call
                denied_retried = True
                refresh = getattr(self.admin_ticket, "refresh", None)
                if refresh is not None:
                    refresh()
                continue
            last_msg = out.get("msg", "error")
            raise MasterError(last_msg)
        raise MasterError(f"master unavailable: {last_msg}")

    # typed helpers the CLI/SDK/objectnode use ---------------------------------

    def get_cluster(self):
        return self.call("/admin/getCluster")

    def get_topology(self):
        return self.call("/admin/getTopology")

    def get_zone_domains(self):
        return self.call("/admin/getZoneDomains")

    def set_zone_domain(self, zone: str, domain: str):
        return self.call(self._path("/admin/setZoneDomain", zone=zone,
                                    domain=domain))

    def create_volume(self, name: str, owner: str = "", cold: bool = False,
                      capacity: int = 1 << 40, dp_count: int = 3,
                      follower_read: bool = False):
        return self.call(self._path(
            "/admin/createVol", name=name, owner=owner,
            cold="true" if cold else "false", capacity=capacity,
            dpCount=dp_count,
            followerRead="true" if follower_read else "false"))

    def update_volume(self, name: str, capacity: int | None = None,
                      follower_read: bool | None = None,
                      qos_read_mbps: int | None = None,
                      qos_write_mbps: int | None = None):
        args = {"name": name}
        if capacity is not None:
            args["capacity"] = capacity
        if follower_read is not None:
            args["followerRead"] = "true" if follower_read else "false"
        if qos_read_mbps is not None:
            args["qosReadMbps"] = qos_read_mbps
        if qos_write_mbps is not None:
            args["qosWriteMbps"] = qos_write_mbps
        return self.call(self._path("/admin/updateVol", **args))

    def delete_volume(self, name: str):
        return self.call(self._path("/admin/deleteVol", name=name))

    def get_volume(self, name: str):
        return self.call(self._path("/admin/getVol", name=name))

    def list_volumes(self):
        return self.call("/admin/listVols")

    def data_partitions(self, name: str):
        return self.call(self._path("/client/partitions", name=name))

    def create_data_partition(self, name: str):
        return self.call(self._path("/admin/createDataPartition", name=name))

    def decommission_node(self, node_id: int, kind: str):
        which = "dataNode" if kind == "data" else "metaNode"
        return self.call(self._path(f"/{which}/decommission", id=node_id))

    def meta_partitions(self, name: str):
        return self.call(self._path("/client/metaPartitions", name=name))

    def add_node(self, node_id: int, kind: str, addr: str, raft_addr: str = "",
                 zone: str = ""):
        which = "dataNode" if kind == "data" else "metaNode"
        return self.call(self._path(f"/{which}/add", id=node_id, addr=addr,
                                    raftAddr=raft_addr, zone=zone))

    def heartbeat(self, node_id: int, partitions: int = 0,
                  cursors: dict | None = None,
                  total_space: int | None = None,
                  used_space: int | None = None,
                  loads: dict | None = None,
                  splits: dict | None = None):
        import json

        return self.call(self._path(
            "/node/heartbeat", id=node_id, partitions=partitions,
            cursors=None if cursors is None else json.dumps(cursors),
            total_space=total_space, used_space=used_space,
            loads=None if loads is None else json.dumps(loads),
            splits=None if splits is None else json.dumps(splits)))

    def rebalance_meta(self, factor: float = 1.5, max_moves: int = 1):
        return self.call(self._path("/metaPartition/rebalance", factor=factor,
                                    maxMoves=max_moves))

    def split_meta_partition(self, name: str, pid: int):
        return self.call(self._path("/metaPartition/split", name=name,
                                    id=pid))

    def rebalance_hot(self, factor: float = 1.5, max_moves: int = 2):
        return self.call(self._path("/dataNode/rebalanceHot", factor=factor,
                                    maxMoves=max_moves))

    def cluster_stat(self):
        return self.call("/admin/getClusterStat")

    def create_user(self, user: str, user_type: str = "normal",
                    ak: str | None = None, sk: str | None = None):
        kw = {"user": user, "type": user_type}
        if ak:
            kw["ak"], kw["sk"] = ak, sk or ""
        return self.call(self._path("/user/create", **kw))

    def delete_user(self, user: str):
        return self.call(self._path("/user/delete", user=user))

    def user_info(self, user: str):
        return self.call(self._path("/user/info", user=user))

    def user_by_ak(self, ak: str):
        return self.call(self._path("/user/akInfo", ak=ak))

    def update_user_policy(self, user: str, vol: str, actions: list[str],
                           grant: bool = True):
        return self.call(self._path(
            "/user/updatePolicy", user=user, vol=vol,
            actions=",".join(actions), grant="true" if grant else "false"))

    def list_users(self):
        return self.call("/user/list")
