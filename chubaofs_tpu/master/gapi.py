"""GraphQL endpoint on the master (master/gapi_*.go analog).

Reference counterpart: master/gapi_cluster.go, gapi_volume.go, gapi_user.go —
the console's query surface. Kept: a POST /graphql endpoint taking
{"query": "...", "variables": {...}} and the reference's root fields
(clusterView, clusterStat, volumeList, volume(name), userList,
userInfo(userID)).
Changed: a purpose-built micro-parser for the query subset the console
emits — field selection with scalar arguments and nested selection sets —
instead of a full GraphQL implementation; unknown syntax is rejected.
"""

from __future__ import annotations

import re
from dataclasses import asdict

TOKEN = re.compile(r"""
    (?P<name>[_A-Za-z][_0-9A-Za-z]*)
  | (?P<string>"(?:[^"\\]|\\.)*")
  | (?P<number>-?\d+(?:\.\d+)?)
  | (?P<punct>[{}():,$!\[\]=@])
  | (?P<ws>[\s]+)
""", re.VERBOSE)


class GQLError(Exception):
    pass


def _tokenize(src: str):
    pos = 0
    out = []
    while pos < len(src):
        m = TOKEN.match(src, pos)
        if not m:
            raise GQLError(f"bad character at {pos}: {src[pos:pos+10]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        out.append((kind, m.group()))
    return out


class _Parser:
    """query ::= ['query' name? varDefs?] selectionSet
    selectionSet ::= '{' field+ '}'
    field ::= name args? selectionSet?
    args ::= '(' (name ':' value),* ')'"""

    def __init__(self, tokens, variables):
        self.toks = tokens
        self.i = 0
        self.vars = variables or {}

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else (None, None)

    def take(self, want_val=None):
        kind, val = self.peek()
        if kind is None or (want_val is not None and val != want_val):
            raise GQLError(f"expected {want_val!r}, got {val!r}")
        self.i += 1
        return kind, val

    def parse(self):
        kind, val = self.peek()
        if kind == "name" and val in ("query", "mutation"):
            if val == "mutation":
                raise GQLError("mutations not supported")
            self.take()
            if self.peek()[0] == "name":  # operation name
                self.take()
            if self.peek()[1] == "(":  # variable defs: skip to matching ')'
                depth = 0
                while True:
                    _, v = self.take()
                    if v == "(":
                        depth += 1
                    elif v == ")":
                        depth -= 1
                        if depth == 0:
                            break
        return self.selection_set()

    def selection_set(self):
        self.take("{")
        fields = []
        while self.peek()[1] != "}":
            fields.append(self.field())
        self.take("}")
        return fields

    def field(self):
        _, name = self.take()
        args = {}
        if self.peek()[1] == "(":
            self.take("(")
            while self.peek()[1] != ")":
                _, argname = self.take()
                self.take(":")
                args[argname] = self.value()
                if self.peek()[1] == ",":
                    self.take(",")
            self.take(")")
        sub = None
        if self.peek()[1] == "{":
            sub = self.selection_set()
        return {"name": name, "args": args, "fields": sub}

    def value(self):
        import json as _json

        kind, val = self.take()
        if kind == "string":
            # GraphQL string escapes are JSON's; json.loads keeps UTF-8 intact
            # (unicode_escape would mojibake non-ASCII)
            return _json.loads(val)
        if kind == "number":
            return float(val) if "." in val else int(val)
        if val == "$":
            _, var = self.take()
            if var not in self.vars:
                raise GQLError(f"variable ${var} not provided")
            return self.vars[var]
        if kind == "name":  # true/false/null/enums
            return {"true": True, "false": False, "null": None}.get(val, val)
        raise GQLError(f"bad value {val!r}")


def _project(obj, fields):
    """Apply a selection set to a dict/list-of-dicts value."""
    if fields is None:
        return obj
    if isinstance(obj, list):
        return [_project(o, fields) for o in obj]
    if obj is None:
        return None
    out = {}
    for f in fields:
        if f["name"] not in obj:
            raise GQLError(f"unknown field {f['name']!r}")
        out[f["name"]] = _project(obj[f["name"]], f["fields"])
    return out


class GraphQLAPI:
    """Root resolvers over the Master facade (gapi_* analog)."""

    def __init__(self, master):
        self.master = master

    # -- root fields -----------------------------------------------------------

    def _cluster_view(self, args):
        sm = self.master.sm
        from chubaofs_tpu.master.master import MASTER_GROUP

        return {
            "leaderID": self.master.raft.leader_of(MASTER_GROUP),
            "volumeCount": len(sm.volumes),
            "nodes": [
                {"id": n.node_id, "kind": n.kind, "addr": n.addr,
                 "raftAddr": n.raft_addr, "partitions": n.partition_count,
                 "lastHeartbeat": n.last_heartbeat}
                for n in sm.nodes.values()
            ],
        }

    def _vol_dict(self, v):
        d = asdict(v)
        return {
            "name": d["name"], "owner": d["owner"], "capacity": d["capacity"],
            "cold": d["cold"],
            "metaPartitions": [
                {"partitionID": mp["partition_id"], "start": mp["start"],
                 "end": -1 if mp["end"] >= (1 << 62) else mp["end"],
                 "peers": mp["peers"], "leader": mp["leader"]}
                for mp in d["meta_partitions"]
            ],
            "dataPartitions": [
                {"partitionID": dp["partition_id"], "peers": dp["peers"],
                 "hosts": dp["hosts"], "status": dp["status"]}
                for dp in d["data_partitions"]
            ],
        }

    def _volume_list(self, args):
        return [self._vol_dict(v) for v in self.master.sm.volumes.values()]

    @staticmethod
    def _arg(args, name):
        if name not in args:
            raise GQLError(f"missing required argument {name!r}")
        return args[name]

    def _volume(self, args):
        return self._vol_dict(self.master.get_volume(self._arg(args, "name")))

    def _user_dict(self, u):
        # no secretKey: the console proxies GraphQL to any browser, and S3
        # credentials must not be harvestable there (round-1 advisory)
        return {"userID": u.user_id, "accessKey": u.access_key,
                "userType": u.user_type,
                "ownVols": list(u.own_vols),
                "authorizedVols": dict(u.authorized_vols)}

    def _user_list(self, args):
        return [self._user_dict(u) for u in self.master.sm.users.values()]

    def _user_info(self, args):
        return self._user_dict(self.master.get_user(self._arg(args, "userID")))

    def _cluster_stat(self, args):
        """Space/health rollup (the dashboard's capacity tiles; ref
        /admin/getClusterStat) — camelCased like every other root field,
        zones as a selectable list."""
        st = self.master.cluster_stat()
        return {
            "totalSpace": st["total_space"], "usedSpace": st["used_space"],
            "dataTotalSpace": st["data"]["total_space"],
            "dataUsedSpace": st["data"]["used_space"],
            "metaTotalSpace": st["meta"]["total_space"],
            "metaUsedSpace": st["meta"]["used_space"],
            "nodes": st["nodes"], "active": st["active"],
            "volumes": st["volumes"],
            "metaPartitions": st["meta_partitions"],
            "dataPartitions": st["data_partitions"],
            "zones": [
                {"name": z, "totalSpace": v["total_space"],
                 "usedSpace": v["used_space"], "nodes": v["nodes"],
                 "active": v["active"]}
                for z, v in sorted(st["zones"].items())
            ],
        }

    ROOTS = {
        "clusterView": _cluster_view,
        "volumeList": _volume_list,
        "volume": _volume,
        "userList": _user_list,
        "userInfo": _user_info,
        "clusterStat": _cluster_stat,
    }

    def execute(self, query: str, variables: dict | None = None) -> dict:
        fields = _Parser(_tokenize(query), variables).parse()
        data = {}
        for f in fields:
            resolver = self.ROOTS.get(f["name"])
            if resolver is None:
                raise GQLError(f"unknown root field {f['name']!r}")
            data[f["name"]] = _project(resolver(self, f["args"]), f["fields"])
        return data

    def handle(self, req):
        """POST /graphql handler (mount on the MasterAPI router)."""
        import json

        from chubaofs_tpu.master.master import MasterError
        from chubaofs_tpu.rpc.router import Response

        try:
            body = req.json() or {}
            data = self.execute(body.get("query", ""), body.get("variables"))
            return Response.json({"data": data})
        except (GQLError, MasterError, ValueError) as e:
            return Response.json({"errors": [{"message": str(e)}]}, status=400)
