"""Single-entry daemon with role dispatch — `python -m chubaofs_tpu.cmd -c cfg.json`.

Reference counterpart: cmd/cmd.go:125-321 — one binary, a JSON config with a
`role` field, and a switch that boots master/metanode/datanode/objectnode/
authnode (cmd/cmd.go:175-199); blobstore/cmd/cmd.go's RegisterModule plays
the same part for the blobstore services. Kept: JSON config file, role
dispatch, everything network-reachable (raft rides TcpNet, metadata ops ride
MetaService's packet TCP, admin rides the master HTTP API). Changed: no
daemonize/fork — process supervision belongs to the operator (systemd,
docker, a test harness); the reference's graceful-restart fd dance is covered
by the fdstore tool instead.

Self-healing placement: the master re-sends partition-create admin tasks to
any replica whose heartbeat doesn't list the partition yet (the reference
does the same through loadMetaPartition/checkDataPartitions sweeps,
master/cluster.go:329-3587) — so node restarts and missed hooks converge.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

from chubaofs_tpu.master.api_service import MasterAPI, MasterClient
from chubaofs_tpu.master.master import MASTER_GROUP, Master, MasterSM
from chubaofs_tpu.raft.server import MultiRaft, TickLoop
from chubaofs_tpu.raft.transport import TcpNet
from chubaofs_tpu.rpc.server import RPCServer

HEARTBEAT_INTERVAL = 1.0
ENSURE_INTERVAL = 2.0


def _addr_split(addr: str) -> tuple[str, int]:
    host, port = addr.rsplit(":", 1)
    return host, int(port)


def _advertise(addr: str, cfg: dict) -> str:
    """Rewrite a wildcard bind host into a peer-dialable address. Binding
    0.0.0.0 is how multi-host deployments listen; registering it verbatim
    would make every peer dial its own loopback. `advertiseHost` in config
    wins; otherwise the hostname's resolved address."""
    host, port = addr.rsplit(":", 1)
    if host not in ("0.0.0.0", "::", ""):
        return addr
    adv = cfg.get("advertiseHost")
    if not adv:
        import socket

        try:
            adv = socket.gethostbyname(socket.gethostname())
        except OSError:
            adv = "127.0.0.1"
    return f"{adv}:{port}"


def _log(daemon: str, msg: str) -> None:
    # stderr IS this process's log transport: supervisors and the harness
    # redirect it to the daemon's .log file, which log collectors tail
    print(f"[{daemon}] {msg}",  # obslint: stderr is the captured daemon log
          file=sys.stderr, flush=True)


def _stats_server(cfg: dict, module: str) -> RPCServer:
    """Tiny HTTP side-door for daemons whose primary wire is packet TCP
    (metanode, datanode): mounts /metrics (the process's whole registry set,
    role-namespaced) so EVERY role is scrapeable. `statsListen` in config;
    port 0 (default) binds an ephemeral port, "off" disables."""
    from chubaofs_tpu.rpc.router import Router

    listen = cfg.get("statsListen", "127.0.0.1:0")
    if listen == "off":
        return None
    host, port = _addr_split(listen)
    return RPCServer(Router(), host=host, port=port, module=module).start()


def _admin_ticket(cfg: dict):
    """Ticket credential for ticket-gated masters. Preferred: authnode client
    credentials (authAddrs + authClientId + authClientKey b64) — a renewing
    provider that outlives TICKET_TTL. Fallback: a static `adminTicket`
    string (expires after the TTL; fine for tooling, wrong for daemons)."""
    if cfg.get("authAddrs") and cfg.get("authClientId") and cfg.get("authClientKey"):
        import base64

        from chubaofs_tpu.authnode.api import RemoteAuthNode
        from chubaofs_tpu.authnode.server import AuthClient, RenewingTicket

        client = AuthClient(RemoteAuthNode(cfg["authAddrs"]),
                            cfg["authClientId"],
                            base64.b64decode(cfg["authClientKey"]))
        return RenewingTicket(client, "master")
    return cfg.get("adminTicket")


def _make_net(node_id: int, peers: dict[int, str], cfg: dict) -> TcpNet:
    """TcpNet with the cluster secret from config. Deployments binding raft
    off-loopback MUST set `raftSecret` (TcpNet refuses the well-known default
    off-loopback); frames decode through the safe raft.codec either way."""
    secret = cfg.get("raftSecret")
    if secret:
        return TcpNet(node_id, peers, secret=secret.encode())
    return TcpNet(node_id, peers)


def _resolve_raft_peers(mc: MasterClient, net: TcpNet) -> None:
    """Refresh peer raft addresses from the registry (raftstore/resolver.go
    analog) so restarted nodes with new ports stay dialable."""
    try:
        for n in mc.get_cluster()["nodes"]:
            if n.get("raft_addr") and n["node_id"] != net.node_id:
                net.set_peer(n["node_id"], n["raft_addr"])
    except Exception:
        pass


def _space_report(paths) -> dict:
    """Disk usage of the daemon's data roots, reported with heartbeats into
    the master's statinfo rollup (ref scheduleToUpdateStatInfo source).

    Accepts one path or a list; filesystems are deduplicated by st_dev so two
    data dirs on one mount don't double-count. No paths -> no report ({})."""
    if not paths:
        return {}
    if isinstance(paths, str):
        paths = [paths]
    import os as _os
    import shutil

    total = used = 0
    seen: set[int] = set()
    for p in paths:
        try:
            dev = _os.stat(p).st_dev
            if dev in seen:
                continue
            du = shutil.disk_usage(p)
        except OSError:
            continue
        seen.add(dev)  # only after BOTH calls succeed: a stat-ok but
        # statvfs-failing mount must not turn the report into zeros
        total += du.total
        used += du.used
    return {"total_space": total, "used_space": used} if seen else {}


class _Daemon:
    """Common lifecycle: background threads registered for stop()."""

    def __init__(self):
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def _spawn(self, fn, name: str):
        t = threading.Thread(target=fn, name=name, daemon=True)
        t.start()
        self._threads.append(t)

    def _every(self, interval: float, fn, name: str):
        def loop():
            last_err = ""
            while not self._stop.wait(interval):
                try:
                    fn()
                    last_err = ""
                except Exception as e:
                    # sweeps never kill the daemon, but persistent faults must
                    # be visible — log each distinct error once
                    msg = f"{type(e).__name__}: {e}"
                    if msg != last_err:
                        _log(name, msg)
                        last_err = msg

        self._spawn(loop, name)

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)


class MasterDaemon(_Daemon):
    """Role master (master/server.go:137 Start analog)."""

    def __init__(self, cfg: dict):
        super().__init__()
        self.node_id = int(cfg["id"])
        raft_peers = {int(k): v for k, v in cfg["raftPeers"].items()}
        self.peer_apis = {int(k): v for k, v in cfg.get("peerApis", {}).items()}
        # how long a node must stay dead before its replicas auto-re-home
        # (deadNodeSecs in config; tests compress it)
        self.dead_node_secs = float(cfg.get("deadNodeSecs",
                                            60 * HEARTBEAT_INTERVAL))
        # hot-volume spreading: rebalanceHotSecs > 0 runs a rebalance_hot
        # sweep on its own cadence (0/absent = off — the operator or the
        # capacity harness triggers it via /dataNode/rebalanceHot instead)
        self.rebalance_hot_secs = float(cfg.get("rebalanceHotSecs", 0))
        self.rebalance_hot_factor = float(cfg.get("rebalanceHotFactor", 1.5))
        # metadata scale-out knobs (ISSUE 15): rebalanceMetaSecs > 0 runs a
        # rebalance_meta sweep on its own cadence (0/absent = off; the
        # operator triggers /metaPartition/rebalance instead); metaSplitOps
        # overrides the CFS_META_SPLIT_OPS load-split threshold
        self.rebalance_meta_secs = float(cfg.get("rebalanceMetaSecs", 0))
        self.rebalance_meta_factor = float(cfg.get("rebalanceMetaFactor", 1.5))
        self.net = _make_net(self.node_id, raft_peers, cfg)
        self.raft = MultiRaft(self.node_id, self.net, wal_dir=cfg.get("walDir"),
                              snapshot_every=512)
        self.sm = MasterSM()
        self.raft.create_group(MASTER_GROUP, sorted(raft_peers), self.sm)
        self.master = Master(self.raft, self.sm)
        self.master.metanode_hook = self._meta_hook
        self.master.datanode_hook = self._data_hook
        self.master.raft_config_hook = self._raft_config_hook
        self.master.remove_partition_hook = self._remove_partition_hook
        self.master.meta_op_hook = self._meta_op_hook
        if "metaSplitOps" in cfg:
            self.master.meta_split_ops = float(cfg["metaSplitOps"] or 0)
        svc_secret = cfg.get("serviceSecret")
        ticket_key = cfg.get("adminTicketKey")  # b64 authnode service key
        if ticket_key:
            import base64

            ticket_key = base64.b64decode(ticket_key)
        self.api = MasterAPI(self.master,
                             leader_addr_of=lambda nid: self.peer_apis.get(nid, ""),
                             service_secret=svc_secret.encode() if svc_secret else None,
                             admin_ticket_key=ticket_key or None)
        host, port = _addr_split(cfg.get("listen", "127.0.0.1:0"))
        self.server = RPCServer(self.api.router, host=host, port=port,
                                module="master").start()
        self.addr = self.server.addr
        self.ticker = TickLoop([self.raft], interval=cfg.get("tickInterval", 0.02))
        self.ticker.start()
        self._meta_handles: dict[int, object] = {}  # node_id -> RemoteMetaNode
        self._every(ENSURE_INTERVAL, self._ensure, f"master{self.node_id}-ensure")
        if self.rebalance_hot_secs > 0:
            self._every(self.rebalance_hot_secs, self._rebalance_hot,
                        f"master{self.node_id}-rebalance")
        if self.rebalance_meta_secs > 0:
            self._every(self.rebalance_meta_secs, self._rebalance_meta,
                        f"master{self.node_id}-metarebalance")
        # autopilot (ISSUE 20): when CFS_AUTOPILOT armed the controller
        # at RPCServer boot, hand it the master's sweep actuators — the
        # hot-partition alert → rebalance closed loop
        from chubaofs_tpu import autopilot as _ap

        if _ap.enabled_from_env():
            ctl = _ap.default_controller()
            for act in _ap.master_actuators(
                    self.master, factor=self.rebalance_hot_factor):
                ctl.register(act)

    def _rebalance_hot(self):
        if self.master.is_leader:
            moved = self.master.rebalance_hot(factor=self.rebalance_hot_factor)
            if moved:
                _log(f"master{self.node_id}",
                     f"rebalance_hot moved {moved} replica(s)")

    def _rebalance_meta(self):
        if self.master.is_leader:
            moved = self.master.rebalance_meta(
                factor=self.rebalance_meta_factor)
            if moved:
                _log(f"master{self.node_id}",
                     f"rebalance_meta moved {moved} replica(s)")

    # -- admin tasks to nodes (master/cluster_task.go analog) ------------------

    def _meta_handle(self, node_id: int, addr: str):
        from chubaofs_tpu.meta.service import RemoteMetaNode

        h = self._meta_handles.get(node_id)
        if h is None or h.addr != addr:  # restarted node: close + re-dial
            if h is not None:
                h.close()
            h = self._meta_handles[node_id] = RemoteMetaNode(addr)
        return h

    def _raft_addrs(self, peers: list[int]) -> dict[int, str]:
        return {p: self.sm.nodes[p].raft_addr
                for p in peers if p in self.sm.nodes and self.sm.nodes[p].raft_addr}

    def _meta_hook(self, pid: int, start: int, end: int, peers: list[int],
                   only: int | None = None):
        raft_addrs = self._raft_addrs(peers)
        for peer in peers:
            if only is not None and peer != only:
                continue
            node = self.sm.nodes.get(peer)
            if node is None or not node.addr:
                continue
            try:
                self._meta_handle(peer, node.addr)._call(
                    pid, "admin_create_partition", start=start, end=end,
                    peers=peers, raft_addrs=raft_addrs)
            except Exception as e:
                _log(f"master{self.node_id}",
                     f"create mp {pid} on node {peer}: {e} (sweep retries)")

    def _data_hook(self, pid: int, peers: list[int], hosts: list[str],
                   only: int | None = None):
        from chubaofs_tpu.proto.packet import (
            OP_CREATE_PARTITION, Packet, RES_OK, recv_packet, send_packet)
        import socket

        raft_addrs = self._raft_addrs(peers)
        for i, peer in enumerate(peers):
            if only is not None and peer != only:
                continue
            node = self.sm.nodes.get(peer)
            addr = node.addr if node and node.addr else (
                hosts[i] if i < len(hosts) else "")
            if not addr:
                continue
            try:
                host, port = _addr_split(addr)
                with socket.create_connection((host, port), timeout=3) as sock:
                    send_packet(sock, Packet(
                        OP_CREATE_PARTITION, partition_id=pid,
                        arg={"peers": peers, "hosts": hosts,
                             "raft_addrs": raft_addrs}))
                    recv_packet(sock)
            except Exception:
                pass

    def _send_data_packet(self, addr: str, pkt):
        """One admin packet round-trip to a datanode."""
        import socket

        from chubaofs_tpu.proto.packet import recv_packet, send_packet

        host, port = _addr_split(addr)
        with socket.create_connection((host, port), timeout=10) as sock:
            send_packet(sock, pkt)
            return recv_packet(sock)

    def _raft_config_hook(self, kind: str, pid: int, action: str,
                          node_id: int, peers: list[int]) -> None:
        """Membership change for a decommission: find the partition's raft
        leader among the candidate peers and propose there, FOLLOWING the
        not-leader hint. The candidate list must include every node that can
        currently be leader — for a remove that includes the node being
        removed (a raft leader may propose its own removal and step down on
        apply; the reference's removeMetaPartitionRaftMember does the same
        leader-first dance)."""
        import time

        from chubaofs_tpu.proto.packet import (
            OP_RAFT_CONFIG, Packet, RES_NOT_LEADER, RES_OK)
        from chubaofs_tpu.raft.server import NotLeaderError

        candidates = list(dict.fromkeys(peers))
        raft_addrs = self._raft_addrs(list(set(peers) | {node_id}))
        deadline = time.monotonic() + 20
        last = "no peers reachable"

        def note_hint(hint):
            if isinstance(hint, int) and hint not in candidates:
                candidates.append(hint)

        while time.monotonic() < deadline:
            for peer in list(candidates):
                node = self.sm.nodes.get(peer)
                if node is None or not node.addr:
                    continue
                try:
                    if kind == "meta":
                        self._meta_handle(peer, node.addr)._call(
                            pid, "admin_raft_config", action=action,
                            node_id=node_id, raft_addrs=raft_addrs)
                        return
                    rep = self._send_data_packet(node.addr, Packet(
                        OP_RAFT_CONFIG, partition_id=pid,
                        arg={"action": action, "node_id": node_id,
                             "raft_addrs": raft_addrs}))
                    if rep.result == RES_OK:
                        return
                    if rep.result == RES_NOT_LEADER:
                        note_hint(rep.arg.get("leader"))
                        last = f"not leader (hint {rep.arg.get('leader')})"
                    else:
                        last = rep.error()
                except NotLeaderError as e:
                    note_hint(e.leader)
                    last = f"not leader (hint {e.leader})"
                except Exception as e:
                    last = str(e)
            time.sleep(0.3)
        raise RuntimeError(f"raft config {action}({node_id}) on {pid}: {last}")

    def _meta_op_hook(self, pid: int, peers: list[int], op: str, args: dict,
                      read: bool = False):
        """Run one metanode op on a partition's raft leader over the wire
        (the split orchestrator's plumbing): walk the candidate peers
        following not-leader hints, skipping replicas that are down or not
        yet hosting the group — the same dance as _raft_config_hook, but
        returning the op's RESULT. `read` is advisory here: MetaService
        routes read vs raft ops by op name."""
        import time

        from chubaofs_tpu.meta.metanode import OpError
        from chubaofs_tpu.raft.server import NotLeaderError

        del read  # the wire handler dispatches by op name
        candidates = list(dict.fromkeys(peers))
        deadline = time.monotonic() + 20
        last = "no peers reachable"
        while time.monotonic() < deadline:
            for peer in list(candidates):
                node = self.sm.nodes.get(peer)
                if node is None or not node.addr:
                    continue
                try:
                    return self._meta_handle(peer, node.addr)._call(
                        pid, op, **args)
                except NotLeaderError as e:
                    if isinstance(e.leader, int) and e.leader not in candidates:
                        candidates.append(e.leader)
                    last = f"not leader (hint {e.leader})"
                except OpError as e:
                    if e.code not in ("ECONN", "EIO", "ENOPARTITION"):
                        raise  # a real op error (frozen conflict, ...) is
                        # the ORCHESTRATOR's to handle, not a retry case
                    last = str(e)
                except Exception as e:
                    last = str(e)
            time.sleep(0.3)
        raise RuntimeError(f"meta op {op} on mp {pid}: {last}")

    def _remove_partition_hook(self, kind: str, pid: int, node_id: int) -> None:
        from chubaofs_tpu.proto.packet import OP_REMOVE_PARTITION, Packet

        node = self.sm.nodes.get(node_id)
        if node is None or not node.addr:
            return  # node gone; nothing to clean
        try:
            if kind == "meta":
                self._meta_handle(node_id, node.addr)._call(
                    pid, "admin_remove_partition")
            else:
                self._send_data_packet(node.addr, Packet(
                    OP_REMOVE_PARTITION, partition_id=pid))
        except Exception as e:
            _log(f"master{self.node_id}",
                 f"remove {kind} partition {pid} on node {node_id}: {e}")

    def _ensure(self):
        """Re-send create tasks to replicas whose heartbeats miss a partition."""
        if not self.master.is_leader:
            return
        self.master.check_meta_partitions()
        self.master.refresh_dp_hosts()
        # liveness sweep: stale-heartbeat nodes go inactive, their data
        # partitions demote to read-only until they come back
        self.master.check_node_liveness(timeout=10 * HEARTBEAT_INTERVAL)
        self.master.check_data_partitions()
        # durable repair: replicas on long-dead nodes re-home to healthy peers
        self.master.check_dead_node_replicas(dead_after=self.dead_node_secs)
        # under-replicated partitions (partial migrations) gain replacements
        self.master.ensure_replica_counts()
        # domain-concentrated partitions (multi-domain-outage residue)
        # re-spread once a free healthy domain exists
        self.master.check_replica_spread()
        # long-silent drained nodes leave the registry
        self.master.prune_stale_nodes(stale_after=60 * self.dead_node_secs)
        # partitions a node reports but no volume records: failed deletes/
        # migrations — send remove tasks (junk-task cleanup analog)
        for node_id, pids in self.master.orphan_partitions().items():
            n = self.sm.nodes.get(node_id)
            kind = n.kind if n else "data"
            for pid in pids:
                self._remove_partition_hook(kind, pid, node_id)
        now = time.time()
        for vol in list(self.sm.volumes.values()):
            for mp in vol.meta_partitions:
                for peer in mp.peers:
                    n = self.sm.nodes.get(peer)
                    if (n and n.addr and now - n.last_heartbeat < 10
                            and mp.partition_id not in n.cursors):
                        # GENESIS range, not the live view range: the
                        # respawned node replays its WAL from index 1 into
                        # this SM, and entries recorded before an in-log
                        # range shrink (complete_split/set_range_end) only
                        # replay under the range they were applied under —
                        # a view-range SM silently drops them (data loss,
                        # caught by the --meta-split soak)
                        self._meta_hook(mp.partition_id, mp.start0, mp.end0,
                                        mp.peers, only=peer)
            for dp in vol.data_partitions:
                for peer in dp.peers:
                    n = self.sm.nodes.get(peer)
                    if (n and n.addr and now - n.last_heartbeat < 10
                            and dp.partition_id not in n.cursors):
                        self._data_hook(dp.partition_id, dp.peers, dp.hosts,
                                        only=peer)

    def stop(self):
        super().stop()
        self.ticker.stop()
        self.server.stop()
        self.net.close()


class MetaNodeDaemon(_Daemon):
    """Role metanode (metanode/metanode.go analog)."""

    def __init__(self, cfg: dict):
        super().__init__()
        from chubaofs_tpu.meta.metanode import MetaNode
        from chubaofs_tpu.meta.service import MetaService

        self.node_id = int(cfg["id"])
        self.net = _make_net(
            self.node_id, {self.node_id: cfg.get("raftListen", "127.0.0.1:0")},
            cfg)
        self._raft_addr = _advertise(self.net.listen_addr, cfg)
        self.raft = MultiRaft(self.node_id, self.net, wal_dir=cfg.get("walDir"),
                              snapshot_every=512)
        self.metanode = MetaNode(self.node_id, self.raft)
        self.zone = cfg.get("zone", "")
        self.data_dir = cfg.get("walDir")  # None = no space report
        host, port = _addr_split(cfg.get("listen", "127.0.0.1:0"))
        self.service = MetaService(self.metanode, host=host, port=port)
        self.addr = _advertise(self.service.addr, cfg)
        self.mc = MasterClient(cfg["masterAddrs"],
                               admin_ticket=_admin_ticket(cfg))
        self.stats_server = _stats_server(cfg, "metanode")
        self.stats_addr = self.stats_server.addr if self.stats_server else ""
        self.ticker = TickLoop([self.raft], interval=cfg.get("tickInterval", 0.02))
        self.ticker.start()
        try:
            self._register()
        except Exception as e:
            _log(f"node{self.node_id}",
                 f"register failed: {e} (heartbeat loop retries)")
        self._every(HEARTBEAT_INTERVAL, self._heartbeat,
                    f"metanode{self.node_id}-hb")
        self._wire_purge(cfg)
        self.metanode.tx_resolver_hook = self._resolve_tx
        self._every(5.0, self.metanode.drain_freelists,
                    f"metanode{self.node_id}-freelist")
        self._every(5.0, self.metanode.sweep_transactions,
                    f"metanode{self.node_id}-txsweep")
        self._every(5.0, self._push_quota_flags,
                    f"metanode{self.node_id}-quota")

    def _remote_metanodes(self):
        from chubaofs_tpu.meta.service import RemoteMetaNode

        handles = {}
        for n in self.mc.get_cluster()["nodes"]:
            if n["kind"] == "meta" and n["addr"]:
                handles[n["node_id"]] = RemoteMetaNode(n["addr"])
        return handles

    def _resolve_tx(self, tm_pid: int, tx_id: str) -> str:
        """Participant-sweep hook over the wire: find the TM partition's
        peers in the master view, ask each for the decision."""
        from chubaofs_tpu.meta.metanode import OpError
        from chubaofs_tpu.raft.server import NotLeaderError

        handles = self._remote_metanodes()
        for v in self.mc.list_volumes():
            for mp in self.mc.meta_partitions(v["name"]):
                if mp["partition_id"] != tm_pid:
                    continue
                for peer in mp["peers"]:
                    h = handles.get(peer)
                    if h is None:
                        continue
                    try:
                        return h.tx_status(tm_pid, tx_id)
                    except (NotLeaderError, OpError):
                        continue
                raise RuntimeError(f"tm partition {tm_pid}: no leader reachable")
        return "unknown"  # partition no longer exists: nothing can commit it

    def _push_quota_flags(self):
        """One quota aggregation round per volume; only the node leading the
        volume's FIRST partition pushes, so the cluster does it once."""
        from chubaofs_tpu.sdk.cluster import _MasterAdapter
        from chubaofs_tpu.sdk.meta_wrapper import MetaWrapper

        adapter = _MasterAdapter(self.mc)
        handles = None
        for v in self.mc.list_volumes():
            mps = self.mc.meta_partitions(v["name"])
            if not mps or not self.metanode.is_leader(mps[0]["partition_id"]):
                continue
            if handles is None:
                handles = self._remote_metanodes()
            MetaWrapper(adapter, handles, v["name"]).push_quota_flags()

    def _register(self):
        self.mc.add_node(self.node_id, "meta", self.addr,
                         raft_addr=self._raft_addr, zone=self.zone)

    def _heartbeat(self):
        from chubaofs_tpu.master.master import MasterError

        cursors = {pid: sm.cursor
                   for pid, sm in list(self.metanode.partitions.items())}
        # per-partition op-load window + frozen-split reports ride the beat:
        # the master's load splitter, meta rebalancer, and split-resume
        # sweep all read them (ISSUE 15)
        loads = self.metanode.take_loads()
        try:
            self.mc.heartbeat(self.node_id, partitions=len(cursors),
                              cursors=cursors, loads=loads,
                              splits=self.metanode.split_reports(),
                              **_space_report(self.data_dir))
        except MasterError:  # "unknown node": master lost state → re-register
            self.metanode.refund_loads(loads)
            self._register()
        except Exception:
            # transport failure: a master hiccup must not erase an observed
            # load window (the datanode heartbeat's same contract)
            self.metanode.refund_loads(loads)
            raise
        _resolve_raft_peers(self.mc, self.net)

    def _wire_purge(self, cfg: dict):
        """Orphan purge hooks over the wire (partition_free_list.go analog)."""
        from chubaofs_tpu.sdk.stream import ExtentClient

        access_addrs = cfg.get("accessAddrs") or []
        ac = None
        if access_addrs:
            from chubaofs_tpu.blobstore.gateway import AccessClient

            ac = AccessClient(access_addrs)

        def all_views():
            views = []
            for v in self.mc.list_volumes():
                views += self.mc.data_partitions(v["name"])
            return views

        ec = ExtentClient(all_views)

        def purge_inode(inode):
            for ext in getattr(inode, "obj_extents", []):
                if ac is not None:
                    ac.delete(ext["loc"])
            keys = getattr(inode, "extents", [])
            if keys:
                ec.refresh()
                ec.delete_extents(keys)

        def purge_entry(entry):
            for ext in entry.get("obj_extents", []):
                if ac is not None:
                    ac.delete(ext["loc"])
            keys = entry.get("extents", [])
            if keys:
                ec.refresh()
                ec.delete_extents(keys)

        self.metanode.data_purge_hook = purge_inode
        self.metanode.extent_purge_hook = purge_entry

    def stop(self):
        super().stop()
        self.ticker.stop()
        self.service.close()
        if self.stats_server is not None:
            self.stats_server.stop()
        self.net.close()


class DataNodeDaemon(_Daemon):
    """Role datanode (datanode/server.go doStart analog)."""

    def __init__(self, cfg: dict):
        super().__init__()
        from chubaofs_tpu.data.datanode import DataNode

        self.node_id = int(cfg["id"])
        self.net = _make_net(
            self.node_id, {self.node_id: cfg.get("raftListen", "127.0.0.1:0")},
            cfg)
        self._raft_addr = _advertise(self.net.listen_addr, cfg)
        self.raft = MultiRaft(self.node_id, self.net, wal_dir=cfg.get("walDir"),
                              snapshot_every=512)
        self.datanode = DataNode(self.node_id, cfg.get("listen", "127.0.0.1:0"),
                                 cfg["disks"], raft=self.raft)
        self.zone = cfg.get("zone", "")
        self.data_dir = list(cfg["disks"])  # all roots, deduped by fs
        self.datanode.start()
        self.addr = _advertise(self.datanode.addr, cfg)
        self.mc = MasterClient(cfg["masterAddrs"],
                               admin_ticket=_admin_ticket(cfg))
        self.stats_server = _stats_server(cfg, "datanode")
        self.stats_addr = self.stats_server.addr if self.stats_server else ""
        self.ticker = TickLoop([self.raft], interval=cfg.get("tickInterval", 0.02))
        self.ticker.start()
        try:
            self._register()
        except Exception as e:
            _log(f"node{self.node_id}",
                 f"register failed: {e} (heartbeat loop retries)")
        self._every(HEARTBEAT_INTERVAL, self._heartbeat,
                    f"datanode{self.node_id}-hb")

    def _register(self):
        self.mc.add_node(self.node_id, "data", self.addr,
                         raft_addr=self._raft_addr, zone=self.zone)

    def _heartbeat(self):
        from chubaofs_tpu.master.master import MasterError

        pids = {pid: 0 for pid in list(self.datanode.space.partitions)}
        loads = self.datanode.take_loads()
        try:
            self.mc.heartbeat(self.node_id, partitions=len(pids), cursors=pids,
                              loads=loads, **_space_report(self.data_dir))
        except MasterError:
            # the master lost this node's record ("unknown node"): the
            # report never landed, so fold the consumed window back in
            self.datanode.refund_loads(loads)
            self._register()
        except Exception:
            # same for transport failures: a master hiccup must not erase
            # an observed load window
            self.datanode.refund_loads(loads)
            raise
        _resolve_raft_peers(self.mc, self.net)

    def stop(self):
        super().stop()
        self.ticker.stop()
        self.datanode.stop()
        if self.stats_server is not None:
            self.stats_server.stop()
        self.net.close()


class BlobstoreDaemon(_Daemon):
    """Role blobstore: the whole EC mini-cluster + access HTTP gateway.

    The reference runs access/clustermgr/proxy/blobnode/scheduler as separate
    processes under blobstore/cmd; the rebuilt services compose in one daemon
    here (they already talk through interfaces), fronted by the gateway."""

    def __init__(self, cfg: dict):
        super().__init__()
        from chubaofs_tpu.blobstore.cluster import MiniCluster
        from chubaofs_tpu.blobstore.cmd import ModuleRunner, add_admin_routes
        from chubaofs_tpu.blobstore.gateway import AccessGateway

        runner = ModuleRunner(cfg=dict(cfg))

        def up_cluster(c, handles):
            return MiniCluster(c["root"], n_nodes=int(c.get("nodes", 6)),
                               disks_per_node=int(c.get("disksPerNode", 2)),
                               azs=int(c.get("azs", 1)))

        def up_gateway(c, handles):
            host, port = _addr_split(c.get("listen", "127.0.0.1:0"))
            gw = AccessGateway(
                handles["cluster"].access, host=host, port=port,
                router_hook=lambda r: add_admin_routes(r, handles["cluster"],
                                                       runner))
            c["listen"] = gw.addr  # graceful reloads rebind the SAME address
            return gw

        runner.register("cluster", up_cluster, lambda h: h.close())
        runner.register("gateway", up_gateway, lambda h: h.stop())
        runner.start()
        self.runner = runner
        self.addr = runner.handles["gateway"].addr
        self._every(1.0, self._bg_tick, "blobstore-bg")

    def _bg_tick(self):
        # under the runner lock, so a tick can never race a concurrent
        # reload's teardown of the cluster it is sweeping
        self.runner.call_with("cluster", lambda c: c.run_background_once())

    def stop(self):
        super().stop()
        self.runner.stop()


class _MasterUserStore:
    """Mapping face over /user/akInfo for ObjectNode authentication.

    Entries expire so credential revocation at the master propagates
    (objectnode's userInfoStore keeps the same short TTL discipline);
    misses are negative-cached briefly to keep bad-AK floods off the master."""

    TTL = 30.0
    NEG_TTL = 5.0
    MAX_ENTRIES = 4096  # bad-AK floods must not grow memory unboundedly

    def __init__(self, mc: MasterClient):
        self.mc = mc
        self._cache: dict[str, tuple[float, dict | None]] = {}

    def get(self, ak: str):
        now = time.monotonic()  # TTL math, never a cross-process timestamp
        hit = self._cache.get(ak)
        if hit is not None and now < hit[0]:
            return hit[1]
        if len(self._cache) >= self.MAX_ENTRIES:
            self._cache = {k: v for k, v in self._cache.items() if now < v[0]}
            while len(self._cache) >= self.MAX_ENTRIES:  # all still live: drop oldest
                self._cache.pop(next(iter(self._cache)))
        try:
            u = self.mc.user_by_ak(ak)
        except Exception:
            self._cache[ak] = (now + self.NEG_TTL, None)
            return None
        entry = {"secret_key": u["secret_key"], "uid": u["user_id"]}
        self._cache[ak] = (now + self.TTL, entry)
        return entry


class ObjectNodeDaemon(_Daemon):
    """Role objectnode (objectnode/server.go analog) over RemoteCluster."""

    def __init__(self, cfg: dict):
        super().__init__()
        from chubaofs_tpu.objectnode.server import ObjectNode
        from chubaofs_tpu.sdk.cluster import RemoteCluster

        self.cluster = RemoteCluster(cfg["masterAddrs"],
                                     access_addrs=cfg.get("accessAddrs"),
                                     admin_ticket=_admin_ticket(cfg))
        users = cfg.get("users")
        if users is None:
            svc_secret = cfg.get("serviceSecret")
            if svc_secret:
                users = _MasterUserStore(MasterClient(
                    cfg["masterAddrs"], auth_secret=svc_secret.encode()))
            else:
                if any(not a.startswith(("127.0.0.1", "localhost", "[::1]"))
                       for a in cfg["masterAddrs"]):
                    _log("objectnode",
                         "no serviceSecret configured and masters are "
                         "non-loopback: the master will refuse /user/akInfo, "
                         "so ALL S3 authentication will fail — set the same "
                         "serviceSecret on masters and this objectnode")
                users = _MasterUserStore(self.cluster.mc)
        self.objectnode = ObjectNode(self.cluster, users=users,
                                     region=cfg.get("region", "cfs"))
        host, port = _addr_split(cfg.get("listen", "127.0.0.1:0"))
        # metrics=False: /metrics on the S3 surface would shadow the
        # auth-wrapped GET /:bucket listing for a bucket named "metrics"
        # and serve process internals unauthenticated — scrape the
        # statsListen side-door instead
        self.server = RPCServer(self.objectnode.router, host=host,
                                port=port, module="objectnode",
                                metrics=False).start()
        self.addr = self.server.addr
        self.stats_server = _stats_server(cfg, "objectnode")
        self.stats_addr = self.stats_server.addr if self.stats_server else ""

    def stop(self):
        super().stop()
        self.server.stop()
        if self.stats_server is not None:
            self.stats_server.stop()


class AuthNodeDaemon(_Daemon):
    """Role authnode (authnode/api_service.go analog)."""

    def __init__(self, cfg: dict):
        super().__init__()
        from chubaofs_tpu.authnode import AUTH_GROUP, AuthNode, KeystoreSM
        from chubaofs_tpu.authnode.api import build_router

        self.node_id = int(cfg["id"])
        raft_peers = {int(k): v for k, v in cfg["raftPeers"].items()}
        self.net = _make_net(self.node_id, raft_peers, cfg)
        self.raft = MultiRaft(self.node_id, self.net, wal_dir=cfg.get("walDir"),
                              snapshot_every=512)
        self.sm = KeystoreSM()
        self.raft.create_group(AUTH_GROUP, sorted(raft_peers), self.sm)
        self.authnode = AuthNode(self.raft, self.sm)
        secret = cfg.get("adminSecret")
        router = build_router(self.authnode,
                              secret.encode() if secret else None)
        host, port = _addr_split(cfg.get("listen", "127.0.0.1:0"))
        self.server = RPCServer(router, host=host, port=port,
                                module="authnode").start()
        self.addr = self.server.addr
        self.ticker = TickLoop([self.raft], interval=cfg.get("tickInterval", 0.02))
        self.ticker.start()

    def stop(self):
        super().stop()
        self.ticker.stop()
        self.server.stop()
        self.net.close()


class ConsoleDaemon(_Daemon):
    """Role console (console/server.go analog)."""

    def __init__(self, cfg: dict):
        super().__init__()
        from chubaofs_tpu.console import Console

        host, port = _addr_split(cfg.get("listen", "127.0.0.1:0"))
        self.console = Console(cfg["masterAddrs"], host=host, port=port,
                               metrics_addrs=cfg.get("metricsAddrs"))
        self.addr = self.console.addr

    def stop(self):
        super().stop()
        self.console.stop()


class ClientDaemon(_Daemon):
    """Role client (client/fuse.go analog): kernel-mount a volume.

    Config: mountPoint, volName, masterAddrs, optional accessAddrs (cold
    volumes). Requires /dev/fuse; fails fast with a clear error otherwise."""

    def __init__(self, cfg: dict):
        super().__init__()
        from chubaofs_tpu.client.fuse_ll import fuse_available, mount_volume

        if not fuse_available():
            raise SystemExit("role client needs /dev/fuse (and privilege)")
        self.fuse = mount_volume(cfg["masterAddrs"], cfg["volName"],
                                 cfg["mountPoint"],
                                 access_addrs=cfg.get("accessAddrs"))
        self.addr = cfg["mountPoint"]

    def stop(self):
        super().stop()
        self.fuse.unmount()


ROLES = {
    "master": MasterDaemon,
    "metanode": MetaNodeDaemon,
    "datanode": DataNodeDaemon,
    "blobstore": BlobstoreDaemon,
    "objectnode": ObjectNodeDaemon,
    "authnode": AuthNodeDaemon,
    "console": ConsoleDaemon,
    "client": ClientDaemon,
}


def start_role(cfg: dict):
    role = cfg.get("role")
    ctor = ROLES.get(role)
    if ctor is None:
        raise SystemExit(f"unknown role {role!r}; valid: {sorted(ROLES)}")
    return ctor(cfg)


def main(argv: list[str] | None = None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="chubaofs-tpu",
                                description="chubaofs-tpu server daemon")
    p.add_argument("-c", "--config", required=True, help="JSON config file")
    args = p.parse_args(argv)
    with open(args.config) as f:
        cfg = json.load(f)
    # honor an explicit JAX_PLATFORMS request even when a sitecustomize-
    # registered accelerator plugin overrides the env var: a daemon told to
    # run on CPU must never silently depend on a proxied TPU's health
    plat = cfg.get("jaxPlatform") or os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    daemon = start_role(cfg)
    addr = getattr(daemon, "addr", "")
    boot = {"role": cfg["role"], "addr": addr}
    stats_addr = getattr(daemon, "stats_addr", "")
    if stats_addr:
        boot["stats_addr"] = stats_addr  # /metrics side-door (statsListen)
    print(json.dumps(boot), flush=True)  # obslint: boot line IS the stdout protocol (harness parses it)
    # SIGTERM (supervisors, ProcCluster.close) must run the same graceful
    # stop as ^C: the client role in particular holds a KERNEL MOUNT that
    # outlives the process unless unmounted here
    from chubaofs_tpu.utils.shutdown import await_shutdown, shutdown_event

    await_shutdown(shutdown_event())
    daemon.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
