"""chubaofs_tpu — a TPU-native distributed storage framework.

A brand-new framework with the capabilities of CubeFS (reference: /root/reference,
CubeFS v3.2.1): a distributed filesystem + S3-compatible object store with two
redundancy engines — replicated hot storage and an erasure-coded blob store — whose
erasure-coding math (GF(2^8) Reed-Solomon / LRC) runs on TPU as batched GF(2)
bit-matrix products on the MXU via jax.lax.dot_general and Pallas kernels.

Layout:
    ops/       TPU compute primitives: GF(2^8) tables, bit-matrix RS kernels, CRC
    codec/     the ec.Encoder-equivalent API: codemodes, RS + LRC encoders, buffers
    parallel/  device meshes, sharding specs, multi-chip codec dispatch
    models/    flagship codec pipeline configs (the "model zoo" of EC layouts)
    utils/     config, logging, byte utilities
    blobstore/ access gateway, clustermgr, blobnode, proxy, scheduler
    meta/      range-sharded metadata plane (metanode equivalent)
    data/      extent storage engine + replication (datanode equivalent)
    master/    cluster resource manager
    raft/      consensus
    rpc/       wire protocol + HTTP rpc framework
    sdk/       client SDKs
"""

__version__ = "0.1.0"
