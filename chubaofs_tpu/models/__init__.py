"""Flagship codec pipeline configs — the framework's "model zoo".

Each entry pairs a CodeMode with the stripe geometry used by a benchmark config in
BASELINE.md. The flagship is EC(12,4) at 8 MiB stripes (the v5e-1 encode /
reconstruct target); the archive config is EC(20,4)+LRC-style wide stripes for
multi-chip meshes.
"""

from __future__ import annotations

from dataclasses import dataclass

from chubaofs_tpu.codec.codemode import CodeMode, Tactic, get_tactic


def _align_up(x: int, a: int) -> int:
    return -(-x // a) * a


@dataclass(frozen=True)
class CodecModel:
    """A benchmarkable codec configuration: layout + stripe geometry."""

    name: str
    mode: CodeMode
    stripe_bytes: int  # total data bytes per stripe

    @property
    def tactic(self) -> Tactic:
        return get_tactic(self.mode)

    @property
    def shard_len(self) -> int:
        """Per-shard bytes, 128-aligned for TPU lane tiling. (Kernel tiling is
        the kernel's concern: it splits any 128-aligned length evenly.)"""
        return _align_up(-(-self.stripe_bytes // self.tactic.N), 128)


MiB = 1 << 20

EC4P2_1M = CodecModel("ec4p2-1mib", CodeMode.EC4P2, 1 * MiB)  # unit-bench scale
EC6P3_4M = CodecModel("ec6p3-4mib", CodeMode.EC6P3, 4 * MiB)  # access PUT streaming
EC12P4_8M = CodecModel("ec12p4-8mib", CodeMode.EC12P4, 8 * MiB)  # flagship
EC16P20L2_16M = CodecModel("ec16p20l2-16mib", CodeMode.EC16P20L2, 16 * MiB)  # wide-parity LRC
EC20P4L2_16M = CodecModel("ec20p4l2-16mib", CodeMode.EC20P4L2, 16 * MiB)  # BASELINE archive

FLAGSHIP = EC12P4_8M
ARCHIVE = EC20P4L2_16M  # the bench + multichip-dryrun LRC config

REGISTRY = {m.name: m for m in [
    EC4P2_1M, EC6P3_4M, EC12P4_8M, EC16P20L2_16M, EC20P4L2_16M,
]}
