"""FsCluster — a full in-process deployment: masters + metanodes + datanodes
+ blobstore.

Reference analog: docker/docker-compose.yml's 3-master/4-metanode/4-datanode
bring-up (SURVEY §4), collapsed into one process for tests and embedded use.
Node layout: raft nodes 1..N each host the master group (GROUP 1) and any meta
partition groups placed on them; datanodes (ids 101..) run real TCP packet
servers (chain replication + per-partition raft for random writes) for hot
volumes; cold volumes ride the erasure-coded blobstore (TPU codec service).
"""

from __future__ import annotations

import os

from chubaofs_tpu.blobstore.cluster import MiniCluster
from chubaofs_tpu.data.datanode import DataNode
from chubaofs_tpu.master.master import Master, MasterSM, MASTER_GROUP, MasterError
from chubaofs_tpu.meta.metanode import MetaNode
from chubaofs_tpu.proto.packet import (
    OP_CREATE_PARTITION, Packet, RES_OK, recv_packet, send_packet,
)
from chubaofs_tpu.raft.server import InProcNet, MultiRaft, NotLeaderError, run_until
from chubaofs_tpu.sdk.fs import FsClient
from chubaofs_tpu.sdk.meta_wrapper import MetaWrapper
from chubaofs_tpu.sdk.stream import ExtentClient, HotBackend
from chubaofs_tpu.utils.conn_pool import ConnPool

DATANODE_ID_BASE = 100


class BlobstoreBackend:
    """FsClient data backend over the blobstore access gateway."""

    def __init__(self, blobstore: MiniCluster):
        self.bs = blobstore

    def write(self, data: bytes) -> str:
        return self.bs.access.put(data).to_json()

    def read(self, loc: str, offset: int, size: int) -> bytes:
        return self.bs.access.get(loc, offset, size)

    def delete(self, loc: str) -> None:
        self.bs.access.delete(loc)


class FsCluster:
    def __init__(self, root: str, n_nodes: int = 3, blob_nodes: int = 9,
                 data_nodes: int = 4, disks_per_datanode: int = 2):
        self.root = root
        self.net = InProcNet()
        self.rafts: dict[int, MultiRaft] = {}
        self.master_sms: dict[int, MasterSM] = {}
        self.masters: dict[int, Master] = {}
        self.metanodes: dict[int, MetaNode] = {}
        self.datanodes: dict[int, DataNode] = {}
        self.admin_pool = ConnPool()

        from chubaofs_tpu.authnode import AUTH_GROUP, AuthNode, KeystoreSM

        self.keystore_sms: dict[int, KeystoreSM] = {}
        self.authnodes: dict[int, AuthNode] = {}
        for i in range(1, n_nodes + 1):
            raft = MultiRaft(i, self.net, wal_dir=os.path.join(root, f"raft{i}"),
                             snapshot_every=512)
            self.rafts[i] = raft
            sm = MasterSM()
            self.master_sms[i] = sm
            raft.create_group(MASTER_GROUP, list(range(1, n_nodes + 1)), sm)
            self.masters[i] = Master(raft, sm)
            self.metanodes[i] = MetaNode(i, raft)
            ksm = KeystoreSM()
            raft.create_group(AUTH_GROUP, list(range(1, n_nodes + 1)), ksm)
            self.keystore_sms[i] = ksm
            self.authnodes[i] = AuthNode(raft, ksm)

        for i, m in self.masters.items():
            m.metanode_hook = self._create_meta_partition
            m.datanode_hook = self._create_data_partition
            m.raft_config_hook = self._raft_config
            m.remove_partition_hook = self._remove_partition
            m.meta_op_hook = self._meta_op

        for j in range(1, data_nodes + 1):
            nid = DATANODE_ID_BASE + j
            draft = MultiRaft(nid, self.net,
                              wal_dir=os.path.join(root, f"raft{nid}"),
                              snapshot_every=512)
            self.rafts[nid] = draft
            disks = [os.path.join(root, f"dn{nid}", f"disk{k}")
                     for k in range(disks_per_datanode)]
            dn = DataNode(nid, "127.0.0.1:0", disks, raft=draft)
            dn.start()
            self.datanodes[nid] = dn

        self.blobstore = MiniCluster(os.path.join(root, "blob"), n_nodes=blob_nodes,
                                     disks_per_node=2)
        self.data_backend = BlobstoreBackend(self.blobstore)

        self.settle()
        lead = self.master()
        for i in self.metanodes:
            lead.register_node(i, "meta")
        for nid, dn in self.datanodes.items():
            lead.register_node(nid, "data", addr=dn.addr)
        # restart path: re-host every partition recorded in the recovered
        # master state; datanode addresses changed, so re-resolve dp hosts
        # from the fresh registry before reconnecting
        lead.refresh_dp_hosts()
        for vol in list(lead.sm.volumes.values()):
            for mp in vol.meta_partitions:
                # genesis range: WAL replay re-applies any in-log range
                # shrink (complete_split/set_range_end); a view-range SM
                # would silently drop pre-shrink entries
                self._create_meta_partition(mp.partition_id, mp.start0,
                                            mp.end0, mp.peers)
            for dp in vol.data_partitions:
                self._create_data_partition(dp.partition_id, dp.peers, dp.hosts)
        self._purge_ec = None
        for mn in self.metanodes.values():
            mn.data_purge_hook = self._purge_inode_data
            mn.extent_purge_hook = self._purge_extent_entry
            mn.tx_resolver_hook = self._resolve_tx

    # -- pumping -----------------------------------------------------------------

    def settle(self, cond=None, max_ticks: int = 600) -> bool:
        """Pump raft clocks until cond (default: master leader elected)."""
        cond = cond or (lambda: any(m.is_leader for m in self.masters.values()))
        return run_until(self.net, cond, max_ticks=max_ticks)

    def heartbeat_metanodes(self):
        """One metanode heartbeat round: cursors + op-load window + frozen-
        split reports into the master (the daemon's 1s loop, pumped
        explicitly in-process). Refunds the load window on failure so a
        mid-election master never erases observed load."""
        for mn in self.metanodes.values():
            cursors = {pid: sm.cursor
                       for pid, sm in list(mn.partitions.items())}
            loads = mn.take_loads()
            try:
                self.master().heartbeat(
                    mn.node_id, partition_count=len(cursors),
                    cursors=cursors, loads=loads,
                    splits=mn.split_reports())
            except Exception:
                # mid-election this raises NotLeaderError, not just
                # MasterError — either way keep the window for later
                # (the daemon heartbeat's same refund-on-any-failure
                # contract in cmd.py)
                mn.refund_loads(loads)

    def tick_background(self):
        """One pass of the master's background loops + metanode freelists."""
        self.heartbeat_metanodes()
        lead = self.master()
        lead.check_meta_partitions()
        lead.refresh_leaders(lambda pid: next(
            (r.leader_of(pid) for r in self.rafts.values() if r.leader_of(pid)), None
        ))
        for mn in self.metanodes.values():
            mn.drain_freelists()
            mn.sweep_transactions()
        for vol_name in self.volume_names():
            try:
                MetaWrapper(lead, self.metanodes, vol_name).push_quota_flags()
            except Exception:
                pass  # a mid-election partition: next tick retries
        self.blobstore.run_background_once()

    def repair_data_partitions(self) -> int:
        """Leader-driven extent repair sweep (the 60s loop of
        datanode/data_partition_repair.go:80); returns bytes streamed."""
        moved = 0
        for vol in self.master().sm.volumes.values():
            for dp in vol.data_partitions:
                leader = self._datanode_at(dp.hosts[0])
                if leader is not None:
                    moved += leader.repair_partition(dp.partition_id)
        return moved

    # -- components ----------------------------------------------------------------

    def master(self) -> Master:
        for m in self.masters.values():
            if m.is_leader:
                return m
        raise MasterError("no master leader")

    def authnode(self):
        from chubaofs_tpu.authnode import AUTH_GROUP

        for i, node in self.authnodes.items():
            if self.rafts[i].is_leader(AUTH_GROUP):
                return node
        raise MasterError("no authnode leader")

    def _raft_config(self, kind: str, pid: int, action: str, node_id: int,
                     peers: list[int]) -> None:
        """Propose a membership change on the partition's raft leader and
        pump ticks until it commits (decommission hook). The proposal is
        async — blocking on the future while also being the tick pump would
        deadlock the in-proc cluster."""
        del kind, peers  # in-proc: every group lives on self.rafts
        fut = None

        def try_once():
            nonlocal fut
            if fut is not None and fut.done():
                return True
            if fut is None or (fut.done() and fut.exception()):
                for raft in self.rafts.values():
                    if pid in raft.groups and raft.is_leader(pid):
                        try:
                            fut = raft.propose_config(pid, action, node_id)
                        except NotLeaderError:
                            fut = None
                        break
            return fut is not None and fut.done() and fut.exception() is None

        assert self.settle(try_once, max_ticks=1200), \
            f"membership change {action}({node_id}) on {pid} did not commit"

    def _meta_op(self, pid: int, peers: list[int], op: str, args: dict,
                 read: bool = False):
        """Run one metanode op on the partition's raft leader (the master's
        split-orchestration hook): find the leader among the hosting
        metanodes, pumping raft clocks through elections, and retry
        leadership races until a bounded deadline."""
        import time as _time

        from chubaofs_tpu.meta.metanode import OpError

        deadline = _time.monotonic() + 30.0
        last: Exception | None = None
        while _time.monotonic() < deadline:
            for mn in self.metanodes.values():
                if pid not in mn.partitions or not mn.raft.is_leader(pid):
                    continue
                try:
                    if read:
                        return getattr(mn, op)(pid, **args)
                    return mn.submit_sync(pid, op, **args)
                except (NotLeaderError, OpError) as e:
                    if isinstance(e, OpError) and e.code not in (
                            "ECONN", "ENOPARTITION", "EIO"):
                        raise
                    last = e
            # no leader found (fresh group / mid-election): pump the clocks
            self.settle(lambda: any(
                pid in mn.partitions and mn.raft.is_leader(pid)
                for mn in self.metanodes.values()), max_ticks=200)
        raise MasterError(f"meta op {op} on {pid}: no leader ({last})")

    def _remove_partition(self, kind: str, pid: int, node_id: int) -> None:
        from chubaofs_tpu.proto.packet import OP_REMOVE_PARTITION

        if kind == "meta":
            mn = self.metanodes.get(node_id)
            if mn is not None:
                mn.remove_partition(pid)
            return
        node = self.master().sm.nodes.get(node_id)
        dn = self._datanode_at(node.addr) if node else None
        if dn is None:
            return
        sock = self.admin_pool.get(dn.addr)
        try:
            send_packet(sock, Packet(OP_REMOVE_PARTITION, partition_id=pid))
            recv_packet(sock)
        finally:
            self.admin_pool.put(dn.addr, sock)

    def _resolve_tx(self, tm_pid: int, tx_id: str) -> str:
        """Participant-sweep hook: ask the TM partition's leader for the
        txn decision (metanode tx RM->TM status query analog)."""
        for mn in self.metanodes.values():
            if tm_pid in mn.partitions and mn.raft.is_leader(tm_pid):
                return mn.tx_status(tm_pid, tx_id)
        raise RuntimeError(f"no leader for tm partition {tm_pid}")

    def _datanode_at(self, addr: str) -> DataNode | None:
        return next((d for d in self.datanodes.values() if d.addr == addr), None)

    def _create_meta_partition(self, pid: int, start: int, end: int,
                               peers: list[int], only: int | None = None):
        for peer in peers:
            if only is not None and peer != only:
                continue
            if pid not in self.metanodes[peer].partitions:
                self.metanodes[peer].create_partition(pid, start, end, peers)
        if only is None:
            self.settle(lambda: any(self.rafts[p].is_leader(pid) for p in peers))

    def _create_data_partition(self, pid: int, peers: list[int],
                               hosts: list[str], only: int | None = None):
        """Admin task to every replica host (master/cluster_task.go analog),
        over the real wire."""
        for peer, addr in zip(peers, hosts):
            if only is not None and peer != only:
                continue
            sock = self.admin_pool.get(addr)
            try:
                send_packet(sock, Packet(OP_CREATE_PARTITION, partition_id=pid,
                                         arg={"peers": peers, "hosts": hosts}))
                rep = recv_packet(sock)
            except (OSError, ConnectionError):
                self.admin_pool.put(addr, sock, ok=False)
                raise
            self.admin_pool.put(addr, sock)
            if rep.result != RES_OK:
                raise MasterError(f"create dp {pid} on {addr}: {rep.error()}")
        if only is None:
            self.settle(lambda: any(self.rafts[p].is_leader(pid) for p in peers))

    def _purge_client(self) -> ExtentClient:
        """One ExtentClient over every volume's partition table (purge path)."""
        if self._purge_ec is None:
            def all_views():
                views = []
                for vol_name in list(self.master().sm.volumes):
                    views += self.master().data_partition_views(vol_name)
                return views

            self._purge_ec = ExtentClient(all_views)
        self._purge_ec.refresh()
        return self._purge_ec

    def _purge_inode_data(self, inode) -> None:
        """Freelist purge: blobstore locations + replica extents. Raises on
        failure — the metanode keeps the orphan queued and retries."""
        for ext in getattr(inode, "obj_extents", []):
            self.data_backend.delete(ext["loc"])
        keys = getattr(inode, "extents", [])
        if keys:
            self._purge_client().delete_extents(keys)

    def _purge_extent_entry(self, entry: dict) -> None:
        """Truncate-dropped spans (the metanode EXTENT_DEL drain)."""
        for ext in entry.get("obj_extents", []):
            self.data_backend.delete(ext["loc"])
        keys = entry.get("extents", [])
        if keys:
            self._purge_client().delete_extents(keys)

    # -- volumes ---------------------------------------------------------------------

    def create_volume(self, name: str, cold: bool = True,
                      follower_read: bool = False) -> None:
        self.master().create_volume(name, cold=cold,
                                    follower_read=follower_read)

    def volume_names(self) -> list[str]:
        return sorted(self.master().sm.volumes)

    def delete_volume(self, name: str) -> None:
        self.master().delete_volume(name)

    def client(self, volume: str) -> FsClient:
        from chubaofs_tpu.sdk.fs import VolQos

        meta = MetaWrapper(self.master(), self.metanodes, volume)
        vol = self.master().get_volume(volume)

        def fetch_limits():
            v = self.master().get_volume(volume)
            return v.qos_read_mbps, v.qos_write_mbps

        qos = VolQos.from_view(vol, fetch=fetch_limits)
        if vol.cold:
            return FsClient(meta, self.data_backend, cold=True, qos=qos)
        ec = ExtentClient(lambda: self.master().data_partition_views(volume),
                          follower_read=vol.follower_read)
        return FsClient(meta, self.data_backend, hot_backend=HotBackend(ec, meta),
                        cold=False, qos=qos)

    def close(self):
        for dn in self.datanodes.values():
            dn.stop()
        self.admin_pool.close()
        self.blobstore.close()
