"""FsCluster — a full in-process deployment: masters + metanodes + blobstore.

Reference analog: docker/docker-compose.yml's 3-master/4-metanode/4-datanode
bring-up (SURVEY §4), collapsed into one process for tests and embedded use.
Node layout: raft nodes 1..N each host the master group (GROUP 1) and any meta
partition groups placed on them; file data rides the erasure-coded blobstore
(cold-tier path) through the TPU codec service.
"""

from __future__ import annotations

import os

from chubaofs_tpu.blobstore.cluster import MiniCluster
from chubaofs_tpu.master.master import Master, MasterSM, MASTER_GROUP, MasterError
from chubaofs_tpu.meta.metanode import MetaNode
from chubaofs_tpu.raft.server import InProcNet, MultiRaft, NotLeaderError, run_until
from chubaofs_tpu.sdk.fs import FsClient
from chubaofs_tpu.sdk.meta_wrapper import MetaWrapper


class BlobstoreBackend:
    """FsClient data backend over the blobstore access gateway."""

    def __init__(self, blobstore: MiniCluster):
        self.bs = blobstore

    def write(self, data: bytes) -> str:
        return self.bs.access.put(data).to_json()

    def read(self, loc: str, offset: int, size: int) -> bytes:
        return self.bs.access.get(loc, offset, size)

    def delete(self, loc: str) -> None:
        self.bs.access.delete(loc)


class FsCluster:
    def __init__(self, root: str, n_nodes: int = 3, blob_nodes: int = 9):
        self.root = root
        self.net = InProcNet()
        self.rafts: dict[int, MultiRaft] = {}
        self.master_sms: dict[int, MasterSM] = {}
        self.masters: dict[int, Master] = {}
        self.metanodes: dict[int, MetaNode] = {}

        for i in range(1, n_nodes + 1):
            raft = MultiRaft(i, self.net, wal_dir=os.path.join(root, f"raft{i}"),
                             snapshot_every=512)
            self.rafts[i] = raft
            sm = MasterSM()
            self.master_sms[i] = sm
            raft.create_group(MASTER_GROUP, list(range(1, n_nodes + 1)), sm)
            self.masters[i] = Master(raft, sm)
            self.metanodes[i] = MetaNode(i, raft)

        for i, m in self.masters.items():
            m.metanode_hook = self._create_meta_partition

        self.blobstore = MiniCluster(os.path.join(root, "blob"), n_nodes=blob_nodes,
                                     disks_per_node=2)
        self.data_backend = BlobstoreBackend(self.blobstore)

        self.settle()
        lead = self.master()
        for i in self.metanodes:
            lead.register_node(i, "meta")
        # restart path: re-host every meta partition recorded in the recovered
        # master state; each group's WAL/snapshot replays its namespace
        for vol in list(lead.sm.volumes.values()):
            for mp in vol.meta_partitions:
                self._create_meta_partition(mp.partition_id, mp.start, mp.end, mp.peers)

    # -- pumping -----------------------------------------------------------------

    def settle(self, cond=None, max_ticks: int = 600) -> bool:
        """Pump raft clocks until cond (default: master leader elected)."""
        cond = cond or (lambda: any(m.is_leader for m in self.masters.values()))
        return run_until(self.net, cond, max_ticks=max_ticks)

    def tick_background(self):
        """One pass of the master's background loops + metanode freelists."""
        lead = self.master()
        lead.check_meta_partitions()
        lead.refresh_leaders(lambda pid: next(
            (r.leader_of(pid) for r in self.rafts.values() if r.leader_of(pid)), None
        ))
        for mn in self.metanodes.values():
            mn.drain_freelists()
        self.blobstore.run_background_once()

    # -- components ----------------------------------------------------------------

    def master(self) -> Master:
        for m in self.masters.values():
            if m.is_leader:
                return m
        raise MasterError("no master leader")

    def _create_meta_partition(self, pid: int, start: int, end: int, peers: list[int]):
        for peer in peers:
            self.metanodes[peer].create_partition(pid, start, end, peers)
        self.settle(lambda: any(self.rafts[p].is_leader(pid) for p in peers))

    # -- volumes ---------------------------------------------------------------------

    def create_volume(self, name: str, cold: bool = True) -> None:
        self.master().create_volume(name, cold=cold)

    def client(self, volume: str) -> FsClient:
        meta = MetaWrapper(self.master(), self.metanodes, volume)
        return FsClient(meta, self.data_backend)

    def close(self):
        self.blobstore.close()
