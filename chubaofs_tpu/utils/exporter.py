"""Metrics exporter — Prometheus-style registry + text exposition + TP timers.

Reference counterpart: util/exporter/exporter.go:31-42,100 (Prometheus registry
with namespace `cfs_{cluster}_{module}`, Counter/Gauge/TP metric kinds,
optional Consul self-registration via util/exporter/consul_register.go) and the
UMP-style TP counters wrapped by exporter.NewTPCnt (metanode/manager.go:109).
Design kept: a process-global registry, metrics keyed by (name, sorted labels),
`NewTPCnt`-style timers that record both a count and latency; the render format
is the Prometheus text format so any scraper can consume it. Consul
registration is represented by a registration record (host/port/path) the
deployment can act on — no live agent in this environment.
"""

from __future__ import annotations

import threading
import time


def _key(name: str, labels: dict[str, str] | None) -> tuple:
    return (name, tuple(sorted((labels or {}).items())))


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def add(self, v: float = 1.0):
        with self._lock:
            self.value += v


class Gauge:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float):
        with self._lock:
            self.value = float(v)


class Summary:
    """Latency summary: count, sum, max — the shape UMP TP logs report
    (util/ump/ump.go:76-92 logs elapsed micros per key; aggregation happens
    downstream, so count/sum/max is the lossless per-process reduction)."""

    __slots__ = ("count", "sum", "max", "_lock")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.max = 0.0
        self._lock = threading.Lock()

    def observe(self, seconds: float):
        with self._lock:
            self.count += 1
            self.sum += seconds
            if seconds > self.max:
                self.max = seconds


class TPObject:
    """exporter.NewTPCnt analog: time an op, count it, flag errors."""

    def __init__(self, registry: "Registry", name: str, labels: dict | None):
        self.registry = registry
        self.name = name
        self.labels = labels
        self.start = time.perf_counter()

    def set(self, err: Exception | None = None):
        elapsed = time.perf_counter() - self.start
        self.registry.summary(self.name, self.labels).observe(elapsed)
        if err is not None:
            self.registry.counter(self.name + "_errors", self.labels).add()

    def __enter__(self):
        return self

    def __exit__(self, et, ev, tb):
        self.set(ev if isinstance(ev, Exception) else None)
        return False


class Registry:
    def __init__(self, cluster: str = "cfs", module: str = ""):
        self.namespace = "_".join(x for x in ("cfs", cluster, module) if x)
        self._metrics: dict[tuple, object] = {}
        self._kinds: dict[str, str] = {}
        self._lock = threading.Lock()
        self.consul_registration: dict | None = None

    def _get(self, kind: str, name: str, labels, factory):
        k = _key(name, labels)
        with self._lock:
            m = self._metrics.get(k)
            if m is None:
                m = self._metrics[k] = factory()
                self._kinds[name] = kind
            return m

    def counter(self, name: str, labels: dict | None = None) -> Counter:
        return self._get("counter", name, labels, Counter)

    def gauge(self, name: str, labels: dict | None = None) -> Gauge:
        return self._get("gauge", name, labels, Gauge)

    def summary(self, name: str, labels: dict | None = None) -> Summary:
        return self._get("summary", name, labels, Summary)

    def tp(self, name: str, labels: dict | None = None) -> TPObject:
        """Start a TP timer; call .set(err) or use as a context manager."""
        return TPObject(self, name, labels)

    def register_consul(self, addr: str, port: int, path: str = "/metrics"):
        """util/exporter/consul_register.go analog — record the registration."""
        self.consul_registration = {"addr": addr, "port": port, "path": path}

    def render(self) -> str:
        """Prometheus text exposition of every metric in the registry."""

        def esc(v) -> str:
            # label-value escaping per the text format: one hostile value
            # (e.g. a volume named 'a"b') must not invalidate the whole
            # scrape for every other metric
            return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                    .replace("\n", "\\n"))

        lines = []
        with self._lock:
            items = sorted(self._metrics.items())
        for (name, labels), m in items:
            full = f"{self.namespace}_{name}"
            lab = ("{" + ",".join(f'{k}="{esc(v)}"' for k, v in labels) + "}") if labels else ""
            if isinstance(m, Counter):
                lines.append(f"{full}{lab} {m.value}")
            elif isinstance(m, Gauge):
                lines.append(f"{full}{lab} {m.value}")
            elif isinstance(m, Summary):
                lines.append(f"{full}_count{lab} {m.count}")
                lines.append(f"{full}_sum{lab} {m.sum}")
                lines.append(f"{full}_max{lab} {m.max}")
        return "\n".join(lines) + "\n"


_default = Registry()


def default_registry() -> Registry:
    return _default


def init(cluster: str, module: str) -> Registry:
    """Re-namespace the process-global registry (exporter.Init analog)."""
    global _default
    _default = Registry(cluster, module)
    return _default
