"""Metrics exporter — Prometheus-style registry + text exposition + TP timers.

Reference counterpart: util/exporter/exporter.go:31-42,100 (Prometheus registry
with namespace `cfs_{cluster}_{module}`, Counter/Gauge/TP metric kinds,
optional Consul self-registration via util/exporter/consul_register.go) and the
UMP-style TP counters wrapped by exporter.NewTPCnt (metanode/manager.go:109).
Design kept: a process-global registry, metrics keyed by (name, sorted labels),
`NewTPCnt`-style timers that record both a count and latency; the render format
is the Prometheus text format so any scraper can consume it. Consul
registration is represented by a registration record (host/port/path) the
deployment can act on — no live agent in this environment.

Role registries: every daemon subsystem owns a module registry obtained via
`registry("raft")`, `registry("codec")`, ... — namespaced `cfs_<module>_` so
one scrape of a daemon's /metrics (which renders `render_all()`) tells which
role each sample came from. Summaries carry fixed histogram buckets so p50/p99
are renderable downstream (the UMP TP logs' aggregation, done in-process).
"""

from __future__ import annotations

import bisect
import threading
import time

# fixed latency buckets (seconds): sub-ms to 10s, the span client ops cover
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
# size/count buckets for batch-occupancy summaries (raft drain, codec batches)
BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)
# buckets for values in [0, 1] (overlap/occupancy ratios) — count buckets
# would dump every ratio into the first bucket and flatten the histogram
RATIO_BUCKETS = (0.1, 0.25, 0.5, 0.75, 0.9, 1.0)


def _key(name: str, labels: dict[str, str] | None) -> tuple:
    return (name, tuple(sorted((labels or {}).items())))


# -- bounded label values (the runtime half of obslint rule 1) -----------------
#
# A label like `tenant` is legitimate ONLY while its value set is closed: one
# request-derived string per series turns /metrics into a memory leak. A
# subsystem that mints per-tenant families declares the closed set up front
# (declare_label_values); any metric call carrying that key with an
# undeclared value then fails loudly instead of silently growing the registry.

_BOUNDED_LABELS: dict[str, frozenset] = {}
_bounded_lock = threading.Lock()


def declare_label_values(key: str, values) -> None:
    """Register the closed value set for a label key (e.g. the configured
    tenant ids). Re-declaring replaces the set; `values=None` removes the
    restriction (test teardown)."""
    with _bounded_lock:
        if values is None:
            _BOUNDED_LABELS.pop(key, None)
        else:
            _BOUNDED_LABELS[key] = frozenset(str(v) for v in values)


def _check_bounded(labels: dict | None) -> None:
    if not labels or not _BOUNDED_LABELS:
        return  # the common daemon: nothing declared, zero overhead
    for k, v in labels.items():
        allowed = _BOUNDED_LABELS.get(k)
        if allowed is not None and str(v) not in allowed:
            raise ValueError(
                f"label {k}={v!r} is outside its declared bounded set "
                f"({len(allowed)} values) — an unbounded {k} string would "
                "mint a fresh series per value (high-cardinality guard); "
                "declare it via exporter.declare_label_values or use a "
                "bounded id")


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def add(self, v: float = 1.0):
        with self._lock:
            self.value += v


class Gauge:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float):
        with self._lock:
            self.value = float(v)


class Summary:
    """Latency summary: count, sum, max — the shape UMP TP logs report
    (util/ump/ump.go:76-92 logs elapsed micros per key) — PLUS fixed
    histogram buckets so a scraper can render p50/p99 without raw samples."""

    __slots__ = ("count", "sum", "max", "buckets", "bucket_counts", "_lock")

    def __init__(self, buckets: tuple = DEFAULT_BUCKETS):
        self.count = 0
        self.sum = 0.0
        self.max = 0.0
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.bucket_counts = [0] * len(self.buckets)
        self._lock = threading.Lock()

    def observe(self, value: float):
        with self._lock:
            self.count += 1
            self.sum += value
            if value > self.max:
                self.max = value
            i = bisect.bisect_left(self.buckets, value)
            if i < len(self.bucket_counts):
                self.bucket_counts[i] += 1

    def snapshot(self) -> dict:
        """Consistent copy (no torn reads across count/sum/buckets)."""
        with self._lock:
            return {"count": self.count, "sum": self.sum, "max": self.max,
                    "buckets": dict(zip(self.buckets, self.bucket_counts))}

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile (upper bound of the bucket holding the
        q-th sample); inf-bucket samples report the observed max."""
        with self._lock:
            if self.count == 0:
                return 0.0
            rank = q * self.count
            seen = 0
            for b, c in zip(self.buckets, self.bucket_counts):
                seen += c
                if seen >= rank:
                    return b
            return self.max


class TPObject:
    """exporter.NewTPCnt analog: time an op, count it, flag errors."""

    def __init__(self, registry: "Registry", name: str, labels: dict | None):
        self.registry = registry
        self.name = name
        self.labels = labels
        self.start = time.perf_counter()

    def set(self, err: Exception | None = None):
        elapsed = time.perf_counter() - self.start
        self.registry.summary(self.name, self.labels).observe(elapsed)
        if err is not None:
            self.registry.counter(self.name + "_errors", self.labels).add()

    def __enter__(self):
        return self

    def __exit__(self, et, ev, tb):
        self.set(ev if isinstance(ev, Exception) else None)
        return False


class Registry:
    def __init__(self, cluster: str = "cfs", module: str = ""):
        self.namespace = "_".join(x for x in ("cfs", cluster, module) if x)
        self._metrics: dict[tuple, object] = {}
        # metric-family kind, keyed per NAME and set for every family (not
        # just the first label set) — and conflict-checked, so one name can
        # never render half counter / half histogram
        self._kinds: dict[str, str] = {}
        self._lock = threading.Lock()
        self.consul_registration: dict | None = None

    def _get(self, kind: str, name: str, labels, factory):
        _check_bounded(labels)
        k = _key(name, labels)
        with self._lock:
            have = self._kinds.get(name)
            if have is not None and have != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {have}, not {kind}")
            m = self._metrics.get(k)
            if m is None:
                m = self._metrics[k] = factory()
                self._kinds[name] = kind
            return m

    def counter(self, name: str, labels: dict | None = None) -> Counter:
        return self._get("counter", name, labels, Counter)

    def gauge(self, name: str, labels: dict | None = None) -> Gauge:
        return self._get("gauge", name, labels, Gauge)

    def summary(self, name: str, labels: dict | None = None,
                buckets: tuple | None = None) -> Summary:
        m = self._get("summary", name, labels,
                      lambda: Summary(buckets or DEFAULT_BUCKETS))
        if buckets is not None:
            want = tuple(sorted(float(b) for b in buckets))
            if m.buckets != want:
                # same family, different bucket spec: the earlier creator
                # (possibly a bucket-less reader that minted the defaults)
                # fixed the layout — mis-bucketing silently would render a
                # wrong histogram, so fail loudly instead
                raise ValueError(
                    f"summary {name!r} exists with buckets {m.buckets}, "
                    f"caller wants {want}")
        return m

    def tp(self, name: str, labels: dict | None = None) -> TPObject:
        """Start a TP timer; call .set(err) or use as a context manager."""
        return TPObject(self, name, labels)

    def unregister(self, name: str, labels: dict | None = None) -> None:
        """Drop one metric (a closed component's series must not render as
        a live idle one forever). The family kind stays reserved."""
        with self._lock:
            self._metrics.pop(_key(name, labels), None)

    def register_consul(self, addr: str, port: int, path: str = "/metrics"):
        """util/exporter/consul_register.go analog — record the registration."""
        self.consul_registration = {"addr": addr, "port": port, "path": path}

    def render(self) -> str:
        """Prometheus text exposition of every metric in the registry:
        one `# TYPE` header per family (counter/gauge/histogram), histogram
        buckets cumulative with an explicit +Inf, `_sum`/`_count`, and the
        UMP-style `_max` as its own gauge family."""

        def esc(v) -> str:
            # label-value escaping per the text format: one hostile value
            # (e.g. a volume named 'a"b') must not invalidate the whole
            # scrape for every other metric
            return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                    .replace("\n", "\\n"))

        def lab_str(labels, extra: list[tuple[str, str]] = ()) -> str:
            pairs = list(labels) + list(extra)
            if not pairs:
                return ""
            return "{" + ",".join(f'{k}="{esc(v)}"' for k, v in pairs) + "}"

        with self._lock:
            items = sorted(self._metrics.items())
            kinds = dict(self._kinds)
        lines: list[str] = []
        max_lines: dict[str, list[str]] = {}  # histogram family -> _max gauges
        typed: set[str] = set()
        for (name, labels), m in items:
            full = f"{self.namespace}_{name}"
            kind = kinds.get(name, "gauge")
            if name not in typed:
                typed.add(name)
                lines.append(f"# TYPE {full} "
                             f"{'histogram' if kind == 'summary' else kind}")
            lab = lab_str(labels)
            if isinstance(m, Summary):
                snap = m.snapshot()
                cum = 0
                for b, c in snap["buckets"].items():
                    cum += c
                    lines.append(
                        f"{full}_bucket{lab_str(labels, [('le', repr(b))])} {cum}")
                lines.append(
                    f"{full}_bucket{lab_str(labels, [('le', '+Inf')])} "
                    f"{snap['count']}")
                lines.append(f"{full}_sum{lab} {snap['sum']}")
                lines.append(f"{full}_count{lab} {snap['count']}")
                max_lines.setdefault(full, []).append(
                    f"{full}_max{lab} {snap['max']}")
            else:
                lines.append(f"{full}{lab} {m.value}")
        for full, mlines in max_lines.items():
            lines.append(f"# TYPE {full}_max gauge")
            lines.extend(mlines)
        return "\n".join(lines) + "\n" if lines else ""


_default = Registry()
_registries: dict[str, Registry] = {}
_reg_lock = threading.Lock()


def default_registry() -> Registry:
    return _default


def registry(module: str) -> Registry:
    """The role/module registry (namespace `cfs_<module>_`), shared
    process-wide — raft, codec, access, blobnode, metanode, datanode, ...
    each own one, and every daemon's /metrics renders them all."""
    with _reg_lock:
        r = _registries.get(module)
        if r is None:
            r = _registries[module] = Registry(cluster="", module=module)
        return r


def render_all() -> str:
    """Every registry in the process: the default one plus each module's —
    what a daemon's /metrics endpoint serves."""
    with _reg_lock:
        regs = [_default] + [_registries[m] for m in sorted(_registries)]
    return "".join(r.render() for r in regs)


def dump(path: str) -> str:
    """Write the full exposition snapshot to `path` (bench/perfbench drop
    one next to their BENCH_*.json lines); returns the rendered text."""
    text = render_all()
    with open(path, "w", encoding="utf-8") as f:
        f.write(text)
    return text


def init(cluster: str, module: str) -> Registry:
    """Re-namespace the process-global registry (exporter.Init analog)."""
    global _default
    _default = Registry(cluster, module)
    return _default
