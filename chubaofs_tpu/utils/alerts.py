"""Alert plane — declarative rules that FIRE and RESOLVE instead of gauges
an operator must watch.

utils/slo.py answers "is this daemon healthy NOW"; this module turns that
(plus the metric-history burn windows and the event journal) into stateful
alerts with the lifecycle a pager expects:

    rule evaluates true  ->  instance FIRING   (alert_firing event, counter)
    rule evaluates false ->  instance RESOLVED (alert_resolved event)

Instances are deduped by FINGERPRINT (rule name + its labels), so a broken
disk flapping through three evaluations is one alert, not three pages.
Silences suppress the firing notification (the instance still evaluates and
reports, marked silenced) — the ack knob for known work.

Rule kinds, all evaluated over the same `utils/metrichist.py` snapshot ring
the SLO evaluator reads (one implementation of "what does a window mean"):

  * `slo_failing`    — an SLO reporting FAILING for N consecutive
                       evaluations (one instance per SLO name);
  * `counter_rate`   — a counter family's restart-clamped window rate above
                       threshold (lease expiries/s);
  * `gauge_sum`      — a gauge family's current sum above threshold, with
                       the SLO evaluator's label_in restriction (broken
                       disks, repair backlog);
  * `event_seen`     — events of a type appeared since the last evaluation
                       (lock inversions); resolves after `consecutive`
                       quiet evaluations.

Surfaced per-daemon at `/alerts` (rpc/server.py mounts it next to /health),
merged at the console `/api/alerts`, rendered by `cfs-events --alerts` and
cfs-top's ALERTS column (`cfs_alerts_firing`). Evaluation cadence:
CFS_ALERT_EVAL_S arms a periodic thread at daemon boot (the metrichist
discipline — unset means zero threads); either way `/alerts` evaluates on
demand when the thread isn't armed, so polling /alerts IS the cadence.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

from chubaofs_tpu.utils import events
from chubaofs_tpu.utils.locks import SanitizedLock
from chubaofs_tpu.utils.slo import FAILING, SLO, _env_f, _eval_window

_ENV_PERIOD = "CFS_ALERT_EVAL_S"

STATE_FIRING, STATE_RESOLVED = "firing", "resolved"


@dataclass(frozen=True)
class AlertRule:
    name: str
    # "slo_failing" | "counter_rate" | "gauge_sum" | "event_seen"
    kind: str
    severity: str = events.SEV_CRITICAL
    description: str = ""
    # slo_failing: consecutive FAILING evaluations before firing; also the
    # event_seen quiet-evaluation count before resolving
    consecutive: int = 3
    # counter_rate / gauge_sum: metric family + breach threshold (+ the SLO
    # evaluator's label_in restriction for gauges)
    family: str = ""
    threshold: float = 0.0
    label_in: tuple = ()
    window_n: int = 6  # counter_rate: snapshots in the rate window
    # event_seen: the journal type watched
    event_type: str = ""


def default_rules() -> list[AlertRule]:
    """The stock rule set, thresholds from env at call time (CFS_ALERT_*).
    Families absent on a role evaluate quiet and never fire — one rule set
    serves every daemon, the default_slos() contract."""
    return [
        AlertRule("slo_failing", "slo_failing",
                  consecutive=max(1, int(_env_f("CFS_ALERT_SLO_N", 3))),
                  description="an SLO held FAILING across N consecutive "
                              "evaluations"),
        AlertRule("lease_expiry_rate", "counter_rate",
                  family="cfs_scheduler_lease_expired",
                  threshold=_env_f("CFS_ALERT_LEASE_RATE", 1.0),
                  severity=events.SEV_WARNING,
                  description="repair lease expiries/s (workers dying or "
                              "wedged)"),
        AlertRule("broken_disks", "gauge_sum",
                  family="cfs_clustermgr_disks",
                  label_in=("status", ("broken",)),
                  threshold=_env_f("CFS_ALERT_BROKEN_DISKS", 0.0),
                  description="disks marked BROKEN awaiting repair"),
        AlertRule("repair_backlog", "gauge_sum",
                  family="cfs_scheduler_tasks",
                  label_in=("state", ("prepared", "working")),
                  threshold=_env_f("CFS_ALERT_REPAIR_BACKLOG", 256.0),
                  severity=events.SEV_WARNING,
                  description="repair tasks outstanding"),
        AlertRule("lock_inversion", "event_seen",
                  event_type="lock_inversion",
                  description="lock-order inversion observed (latent "
                              "deadlock)"),
    ]


def fingerprint(rule_name: str, labels: dict | None) -> str:
    return rule_name + "".join(
        f"|{k}={v}" for k, v in sorted((labels or {}).items()))


# -- lifecycle hooks (the alert-lifecycle subscription, ROADMAP item 4) --------
#
# Callbacks run AFTER the transition's event+counter, outside the manager
# lock, on the evaluating thread — cb(fingerprint, instance_report).
# Private managers (soak probes) never invoke them, same as they never
# publish the cfs_alerts_firing gauge: a probe's synthetic windows must not
# trigger the serving process's incident machinery. A raising hook is
# swallowed — subscribers must not kill the evaluator. on_resolved mirrors
# on_firing for the RESOLVED edge: the autopilot's strict-improvement gate
# confirms a nudge helped by watching the triggering alert clear.

_firing_hooks: list = []
_resolved_hooks: list = []


def on_firing(cb) -> None:
    if cb not in _firing_hooks:
        _firing_hooks.append(cb)


def remove_firing_hook(cb) -> None:
    try:
        _firing_hooks.remove(cb)
    except ValueError:
        pass


def on_resolved(cb) -> None:
    if cb not in _resolved_hooks:
        _resolved_hooks.append(cb)


def remove_resolved_hook(cb) -> None:
    try:
        _resolved_hooks.remove(cb)
    except ValueError:
        pass


@dataclass
class _Instance:
    rule: AlertRule
    labels: dict = field(default_factory=dict)
    state: str = STATE_FIRING
    value: float | None = None
    since_ts: float = 0.0
    since_mono: float = 0.0
    resolved_ts: float | None = None
    silenced: bool = False

    def report(self) -> dict:
        return {"name": self.rule.name, "labels": dict(self.labels),
                "state": self.state, "severity": self.rule.severity,
                "value": self.value, "since": self.since_ts,
                "resolved": self.resolved_ts, "silenced": self.silenced,
                "description": self.rule.description}


class AlertManager:
    """Evaluates a rule set and owns the firing/resolved instance table."""

    RESOLVED_KEEP = 128  # bounded resolved history for /alerts

    def __init__(self, rules: list[AlertRule] | None = None, journal=None,
                 private: bool = False):
        self.rules = list(rules if rules is not None else default_rules())
        self.journal = journal  # None = the process default, bound lazily
        # a PRIVATE manager (a soak probe, an A/B harness) must not clobber
        # the cfs_alerts_firing gauge cfs-top scrapes — that series belongs
        # to the process's serving manager (last-writer-wins would let a
        # probe's table overwrite the real one). Transition events/counters
        # still record: they are additive evidence, not a shared cell.
        self.private = private
        self._lock = SanitizedLock(name="alerts.manager")
        self._instances: dict[str, _Instance] = {}
        self._slo_streak: dict[str, int] = {}
        # event_seen cursors start at the journal HEAD: this manager judges
        # events from its own birth onward — a stale inversion emitted by
        # some earlier phase of the process must not fire a fresh manager
        try:
            base = self._journal().last_seq()
        except Exception:
            base = 0
        self._event_cursor: dict[str, int] = {
            r.name: base for r in self.rules if r.kind == "event_seen"}
        self._event_quiet: dict[str, int] = {}
        self._silences: list[dict] = []  # {pattern, until_mono}
        self._fired_names: set[str] = set()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _journal(self):
        return self.journal if self.journal is not None \
            else events.default_journal()

    # -- silences --------------------------------------------------------------

    def silence(self, pattern: str, duration_s: float = 3600.0) -> None:
        """Suppress firing notifications for instances whose fingerprint
        contains `pattern`, for duration_s from now."""
        with self._lock:
            self._silences.append({"pattern": pattern,
                                   "until_mono": time.monotonic() + duration_s})

    def _silenced_locked(self, fp: str) -> bool:
        now = time.monotonic()
        self._silences = [s for s in self._silences if s["until_mono"] > now]
        return any(s["pattern"] in fp for s in self._silences)

    # -- evaluation ------------------------------------------------------------

    def _eval_rule(self, rule: AlertRule,
                   snaps: list[dict]) -> list[tuple[dict, float | None]]:
        """Instances of one rule currently in breach: [(labels, value)]."""
        if rule.kind == "slo_failing":
            from chubaofs_tpu.utils import slo as slomod

            # track_flips=False: slo_flip events belong to the /health
            # judgment stream; a second evaluator over its own windows must
            # not ping-pong the shared flip state. A PRIVATE manager (soak
            # probe) also skips publishing, or its windows would clobber
            # the serving daemon's cfs_slo_status gauges
            rep = slomod.evaluate(slomod.default_slos(), snaps,
                                  track_flips=False,
                                  publish=not self.private)
            out = []
            for name, s in rep["slos"].items():
                streak = self._slo_streak.get(name, 0)
                streak = streak + 1 if s["status"] == FAILING else 0
                self._slo_streak[name] = streak
                if streak >= rule.consecutive:
                    out.append(({"slo": name}, float(streak)))
            return out
        if rule.kind == "counter_rate":
            spec = SLO(rule.name, "counter_rate", rule.family, rule.threshold)
            v = _eval_window(spec, snaps[-rule.window_n:])
            return [({}, v)] if v is not None and v > rule.threshold else []
        if rule.kind == "gauge_sum":
            spec = SLO(rule.name, "gauge_sum", rule.family, rule.threshold,
                       label_in=rule.label_in)
            v = _eval_window(spec, snaps[-1:])
            return [({}, v)] if v is not None and v > rule.threshold else []
        if rule.kind == "event_seen":
            j = self._journal()
            since = self._event_cursor.get(rule.name, 0)
            evs, cursor = j.query(since=since, n=10 ** 6,
                                  types=(rule.event_type,))
            self._event_cursor[rule.name] = cursor
            if evs:
                self._event_quiet[rule.name] = 0
                return [({}, float(len(evs)))]
            quiet = self._event_quiet.get(rule.name, rule.consecutive) + 1
            self._event_quiet[rule.name] = quiet
            fp = fingerprint(rule.name, {})
            inst = self._instances.get(fp)
            if inst is not None and inst.state == STATE_FIRING \
                    and quiet < rule.consecutive:
                return [({}, inst.value)]  # hold until N quiet evaluations
            return []
        raise ValueError(f"unknown alert rule kind {rule.kind!r}")

    def evaluate(self, snaps: list[dict] | None = None) -> dict:
        """One evaluation pass over every rule; returns report(). With no
        `snaps`, reads (and, when the periodic recorder isn't armed, feeds)
        the process metric history — the /alerts-poll-driven cadence."""
        from chubaofs_tpu.utils.exporter import registry
        from chubaofs_tpu.utils.metrichist import default_history

        if snaps is None:
            hist = default_history()
            if not hist.armed:
                hist.record()
            snaps = hist.snapshots()
        transitions: list[tuple[str, _Instance]] = []
        with self._lock:
            now_firing: dict[str, tuple[AlertRule, dict, float | None]] = {}
            for rule in self.rules:
                try:
                    breaches = self._eval_rule(rule, snaps)
                except Exception:
                    continue  # one rule's bad family must not kill the pass
                for labels, value in breaches:
                    now_firing[fingerprint(rule.name, labels)] = \
                        (rule, labels, value)
            for fp, (rule, labels, value) in now_firing.items():
                inst = self._instances.get(fp)
                if inst is None or inst.state != STATE_FIRING:
                    inst = _Instance(rule=rule, labels=labels,
                                     since_ts=time.time(),
                                     since_mono=time.monotonic(),
                                     silenced=self._silenced_locked(fp))
                    self._instances[fp] = inst
                    if not inst.silenced:
                        self._fired_names.add(rule.name)
                        transitions.append((STATE_FIRING, inst))
                inst.value = value
            for fp, inst in self._instances.items():
                if inst.state == STATE_FIRING and fp not in now_firing:
                    inst.state = STATE_RESOLVED
                    inst.resolved_ts = time.time()
                    if not inst.silenced:
                        transitions.append((STATE_RESOLVED, inst))
            self._prune_resolved_locked()
            firing = sum(1 for i in self._instances.values()
                         if i.state == STATE_FIRING)
        reg = registry("alerts")
        if not self.private:
            reg.gauge("firing").set(firing)
        reg.counter("evaluations").add()
        for state, inst in transitions:
            etype = "alert_firing" if state == STATE_FIRING \
                else "alert_resolved"
            sev = inst.rule.severity if state == STATE_FIRING \
                else events.SEV_INFO
            events.emit(etype, sev, entity=inst.rule.name,
                        detail={"labels": dict(inst.labels),
                                "value": inst.value,
                                "description": inst.rule.description})
            reg.counter("transitions",
                        {"rule": inst.rule.name, "state": state}).add()
        if not self.private:
            for state, inst in transitions:
                hooks = _firing_hooks if state == STATE_FIRING \
                    else _resolved_hooks
                fp = fingerprint(inst.rule.name, inst.labels)
                for cb in list(hooks):
                    try:
                        cb(fp, inst.report())
                    except Exception:
                        pass  # a subscriber must not kill the evaluator
        return self.report()

    def _prune_resolved_locked(self) -> None:
        resolved = [(fp, i) for fp, i in self._instances.items()
                    if i.state == STATE_RESOLVED]
        if len(resolved) <= self.RESOLVED_KEEP:
            return
        resolved.sort(key=lambda kv: kv[1].resolved_ts or 0.0)
        for fp, _ in resolved[: len(resolved) - self.RESOLVED_KEEP]:
            del self._instances[fp]

    # -- report surface --------------------------------------------------------

    def report(self) -> dict:
        """The /alerts payload: firing first (newest first within a state),
        then recent resolved."""
        with self._lock:
            insts = sorted(
                self._instances.values(),
                key=lambda i: (i.state != STATE_FIRING, -i.since_mono))
            return {"alerts": [i.report() for i in insts],
                    "firing": sum(1 for i in insts
                                  if i.state == STATE_FIRING),
                    "silences": [dict(s) for s in self._silences]}

    def firing(self) -> list[dict]:
        with self._lock:
            return [i.report() for i in self._instances.values()
                    if i.state == STATE_FIRING]

    def fired_names(self) -> list[str]:
        """Every rule name that transitioned to firing (non-silenced) over
        this manager's lifetime — the soak/capacity gate's evidence."""
        with self._lock:
            return sorted(self._fired_names)

    # -- periodic evaluation (the metrichist arming discipline) ----------------

    @property
    def armed(self) -> bool:
        return self._thread is not None

    def start(self, period_s: float) -> "AlertManager":
        if self._thread is not None:
            return self
        self._stop.clear()

        def _run():
            while not self._stop.wait(period_s):
                try:
                    self.evaluate()
                except Exception:
                    pass  # one bad pass must not kill the evaluator

        self._thread = threading.Thread(target=_run, daemon=True,
                                        name="cfs-alerts")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


# -- process-wide default ------------------------------------------------------

_default: AlertManager | None = None
_dlock = threading.Lock()


def env_period() -> float:
    try:
        p = float(os.environ.get(_ENV_PERIOD, "") or 0.0)
    except ValueError:
        return 0.0
    return p if p > 0.0 else 0.0


def default_manager() -> AlertManager:
    global _default
    with _dlock:
        if _default is None:
            _default = AlertManager()
        return _default


def activate_from_env() -> AlertManager | None:
    """Arm the periodic evaluator iff CFS_ALERT_EVAL_S asks for it — the
    daemon-boot hook. Unset env = nothing started (zero overhead)."""
    if not env_period():
        return _default
    return default_manager().start(env_period())


def deactivate() -> None:
    """Stop + forget the process manager (test isolation)."""
    global _default
    with _dlock:
        m, _default = _default, None
    if m is not None:
        m.stop()


def alerts_report(evaluate_if_cold: bool = True) -> dict:
    """The /alerts payload for THIS process. When the periodic evaluator
    isn't armed, each call evaluates first — polling /alerts then IS the
    evaluation cadence (the health_report() contract)."""
    m = default_manager()
    if evaluate_if_cold and not m.armed:
        return m.evaluate()
    return m.report()
