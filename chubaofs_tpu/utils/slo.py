"""SLO health plane — declarative objectives evaluated over metric history.

"Is the cluster healthy?" finally gets a machine answer: a small set of
declarative SLO specs (PUT/GET p99 latency, error rate, repair backlog,
evloop backpressure) evaluated over utils/metrichist.py's snapshot ring
with the multi-window burn-rate discipline of SRE alerting — a FAST window
(is it burning right now?) and a SLOW window (has it been burning long
enough to matter?):

    breach in both windows  -> failing   (sustained: page-worthy)
    breach in one window    -> degraded  (spiking or recovering)
    breach in neither       -> ok

Surfaced three ways, all from the same evaluation:

  * `/health` on every daemon (rpc/server.py mounts it next to /metrics):
    `{status, reasons, slos}` — always HTTP 200; machine clients read the
    status field, and the console's `/api/health` rollup treats a target
    that can't answer at all as FAILING rather than omitting it;
  * `cfs_slo_status{slo=...}` gauges (0 ok / 1 degraded / 2 failing) and a
    `cfs_slo_evaluations` counter, so SLO state is itself scrapeable and
    history'd;
  * `cfs-top` (tools/cfstop.py) renders the rollup live.

Thresholds are env knobs (CFS_SLO_*) read at evaluation time, so a test or
an operator can retune without a restart. An SLO with no data in the window
(no traffic, series absent on this role) evaluates to None and does NOT
breach — a quiet metanode is healthy, not unknown-unhealthy; reachability
is the console rollup's job.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from chubaofs_tpu.utils.metrichist import (
    default_history, family_sum, hist_delta, hist_quantile, parse_key)

OK, DEGRADED, FAILING = "ok", "degraded", "failing"
RANK = {OK: 0, DEGRADED: 1, FAILING: 2}


@dataclass(frozen=True)
class SLO:
    """One objective: `kind` picks the evaluator, `family` the metric
    family, `threshold` the breach bound (value > threshold = breach)."""

    name: str
    # "hist_p99_ms" | "error_ratio" | "counter_rate" | "counter_ratio"
    # | "gauge_sum"
    kind: str
    family: str
    threshold: float
    # error_ratio denominator (a histogram family); counter_ratio
    # denominator (a plain counter family)
    ops_family: str = ""
    # label restriction: (label_key, (allowed values...)) — gauge_sum uses
    # it to keep live task states only, counter_ratio to slice BOTH
    # families down to one tenant's series (the per-tenant QoS SLOs)
    label_in: tuple = ()
    description: str = ""


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_n(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, "") or default))
    except ValueError:
        return default


# dynamic objective providers (name -> zero-arg callable returning [SLO]):
# subsystems whose objectives exist only when configured — the QoS plane's
# per-tenant throttle ratios — register here at arm time and unregister at
# teardown, and every /health evaluation picks them up live
_slo_providers: dict = {}


def register_slo_provider(name: str, fn) -> None:
    _slo_providers[name] = fn


def unregister_slo_provider(name: str) -> None:
    _slo_providers.pop(name, None)


def default_slos() -> list[SLO]:
    """The stock objectives, thresholds from env at call time. Families
    missing on a role (no access layer on a metanode) evaluate to None and
    never breach — one spec set serves every daemon."""
    err = _env_f("CFS_SLO_ERR_RATIO", 0.01)
    out = _base_slos(err)
    for fn in list(_slo_providers.values()):
        out.extend(fn())
    return out


def _base_slos(err: float) -> list[SLO]:
    return [
        SLO("put_p99", "hist_p99_ms", "cfs_access_put",
            _env_f("CFS_SLO_PUT_P99_MS", 2000.0),
            description="access PUT p99 latency (ms)"),
        SLO("get_p99", "hist_p99_ms", "cfs_access_get",
            _env_f("CFS_SLO_GET_P99_MS", 2000.0),
            description="access GET p99 latency (ms)"),
        SLO("put_errors", "error_ratio", "cfs_access_put_errors", err,
            ops_family="cfs_access_put", description="PUT error ratio"),
        SLO("get_errors", "error_ratio", "cfs_access_get_errors", err,
            ops_family="cfs_access_get", description="GET error ratio"),
        SLO("repair_backlog", "gauge_sum", "cfs_scheduler_tasks",
            _env_f("CFS_SLO_REPAIR_BACKLOG", 256.0),
            label_in=("state", ("prepared", "working")),
            description="repair tasks outstanding (prepared+working)"),
        SLO("evloop_backpressure", "counter_rate", "cfs_evloop_backpressure",
            _env_f("CFS_SLO_BP_RATE", 16.0),
            description="evloop read-pause events/s"),
        # cache plane (ISSUE 12): sustained miss ratio above threshold means
        # the zipfian hot head is NOT being absorbed — admission broken,
        # budget too small, or an invalidation storm. Absent families (no
        # cache configured on this role) evaluate to None and never breach.
        SLO("cache_miss_ratio", "counter_ratio", "cfs_cache_misses",
            _env_f("CFS_SLO_CACHE_MISS", 0.95),
            ops_family="cfs_cache_lookups",
            description="block-cache miss ratio (misses/lookups)"),
    ]


# -- per-window evaluators -----------------------------------------------------


def _restart_delta(first: dict, last: dict, family: str,
                   label_in: tuple = ()) -> float:
    """Counter-family window delta under the restart contract shared with
    metrichist.rates() / hist_delta / cfs-stat: a total that went DOWN
    means the daemon restarted, and the post-restart total IS the delta —
    clamping to zero would read a restarting-and-erroring daemon as clean
    exactly when it most needs watching."""
    end = family_sum(last, family, label_in)
    d = end - family_sum(first, family, label_in)
    return end if d < 0 else d


def _eval_window(slo: SLO, window: list[dict],
                 worst: bool = False) -> float | None:
    """The SLO's value over one snapshot window; None = no data (series
    absent, zero traffic, no window yet).

    Flow kinds (latency, error ratio, rate) need a DELTA, so they need at
    least two snapshots: a single snapshot only offers process-lifetime
    totals, and lifetime is not a burn window — one error-burst an hour
    after boot would read as "failing NOW" forever, and a just-booted
    daemon would inherit a verdict from traffic that predates the poller.
    Until the second snapshot lands, flow SLOs report None (no data).
    Gauge kinds carry state, not flow, and evaluate from one snapshot."""
    if not window:
        return None
    last = window[-1]["metrics"]
    first = window[0]["metrics"]
    if slo.kind == "hist_p99_ms":
        if len(window) < 2:
            return None
        buckets, count = hist_delta(first, last, slo.family)
        q = hist_quantile(buckets, count, 0.99)
        return None if q is None else q * 1e3  # exporter buckets are seconds
    if slo.kind == "error_ratio":
        if len(window) < 2:
            return None
        errs = _restart_delta(first, last, slo.family)
        _, ops = hist_delta(first, last, slo.ops_family)
        if ops <= 0:
            return None if errs <= 0 else 1.0  # errors with zero completions
        return errs / ops
    if slo.kind == "counter_ratio":
        # two plain counter families, numerator over denominator (the cache
        # miss-ratio shape); same restart contract as error_ratio. label_in
        # slices BOTH families (per-tenant QoS throttle ratios)
        if len(window) < 2:
            return None
        num = _restart_delta(first, last, slo.family, slo.label_in)
        den = _restart_delta(first, last, slo.ops_family, slo.label_in)
        if den <= 0:
            return None  # no lookups in the window: a quiet cache is healthy
        return num / den
    if slo.kind == "counter_rate":
        if len(window) < 2:
            return None
        dt = window[-1]["mono"] - window[0]["mono"]
        if dt <= 0:
            return None
        return _restart_delta(first, last, slo.family) / dt
    if slo.kind == "gauge_sum":
        def keep(key: str) -> bool:
            name, labels = parse_key(key)
            if name != slo.family:
                return False
            if slo.label_in:
                lk, allowed = slo.label_in
                return labels.get(lk) in allowed
            return True

        if not any(keep(k) for k in last):
            return None
        # gauges carry state, not flow: the FAST window is the backlog NOW
        # (latest snapshot — a drained spike is over); only the SLOW window
        # (worst=True) takes the worst snapshot, so a sustained-high backlog
        # that dips at poll time still registers as burning
        per_snap = [sum(v for k, v in s["metrics"].items() if keep(k))
                    for s in window]
        return max(per_snap) if worst else per_snap[-1]
    raise ValueError(f"unknown SLO kind {slo.kind!r}")


# last published status per SLO name, for flip detection. Module state on
# purpose: ONE judgment stream per process — the /health evaluation path
# (health_report) owns it. Secondary evaluators over their own snapshot
# windows (the alert plane's slo_failing rule, soak probes) pass
# track_flips=False, or each pass would flip the shared stream back and
# forth and spray spurious slo_flip pairs onto the timeline.
_last_status: dict[str, str] = {}


def evaluate(slos: list[SLO], snaps: list[dict],
             fast_n: int | None = None, slow_n: int | None = None,
             track_flips: bool = True, publish: bool = True) -> dict:
    """Evaluate every SLO over the fast (last CFS_SLO_FAST_N snapshots) and
    slow (last CFS_SLO_SLOW_N) windows; returns the /health payload and
    (with publish, the serving-path default) the cfs_slo_* metrics. With
    track_flips (the /health stream), status CHANGES (ok<->degraded<->
    failing) land on the event timeline as `slo_flip`, emitted once per
    transition. A PRIVATE evaluator over its own snapshot windows (a soak
    probe's slo_failing rule) passes publish=False + track_flips=False so
    it neither clobbers the shared cfs_slo_status gauges nor ping-pongs the
    flip stream."""
    from chubaofs_tpu.utils.exporter import registry

    fast_n = fast_n or _env_n("CFS_SLO_FAST_N", 3)
    slow_n = slow_n or _env_n("CFS_SLO_SLOW_N", 12)
    reg = registry("slo")
    out: dict[str, dict] = {}
    reasons: list[str] = []
    worst = OK
    # "breach in both windows" only means SUSTAINED when the slow window
    # actually extends beyond the fast one; on a young ring (or fast_n >=
    # slow_n) the two windows are the same snapshots and a single spike
    # would trivially "breach both" — cap that at degraded until the slow
    # window has independent evidence
    fast_win = snaps[-fast_n:]
    slow_win = snaps[-slow_n:]
    sustained_provable = len(slow_win) > len(fast_win)
    flips: list[tuple[SLO, str, str, float | None, float | None]] = []
    for slo in slos:
        v_fast = _eval_window(slo, fast_win)
        v_slow = _eval_window(slo, slow_win, worst=True)
        b_fast = v_fast is not None and v_fast > slo.threshold
        b_slow = v_slow is not None and v_slow > slo.threshold
        status = FAILING if (b_fast and b_slow and sustained_provable) else (
            DEGRADED if (b_fast or b_slow) else OK)
        out[slo.name] = {
            "status": status, "threshold": slo.threshold,
            "fast": None if v_fast is None else round(v_fast, 6),
            "slow": None if v_slow is None else round(v_slow, 6),
            "description": slo.description,
        }
        if status != OK:
            reasons.append(
                f"{slo.name}: fast={v_fast if v_fast is None else round(v_fast, 3)}"
                f" slow={v_slow if v_slow is None else round(v_slow, 3)}"
                f" > {slo.threshold} ({status})")
        if RANK[status] > RANK[worst]:
            worst = status
        if publish:
            reg.gauge("status", {"slo": slo.name}).set(RANK[status])
        if track_flips:
            prev = _last_status.get(slo.name)
            if prev is not None and prev != status:
                flips.append((slo, prev, status, v_fast, v_slow))
            _last_status[slo.name] = status
    if publish:
        reg.counter("evaluations").add()
    for slo, prev, status, v_fast, v_slow in flips:
        from chubaofs_tpu.utils import events

        sev = (events.SEV_CRITICAL if status == FAILING else
               events.SEV_WARNING if status == DEGRADED else events.SEV_INFO)
        events.emit("slo_flip", sev, entity=slo.name,
                    detail={"from": prev, "to": status,
                            "fast": v_fast, "slow": v_slow,
                            "threshold": slo.threshold})
    return {"status": worst, "reasons": reasons, "slos": out}


def health_report(fast_n: int | None = None,
                  slow_n: int | None = None) -> dict:
    """The /health payload for THIS process. When the periodic recorder
    isn't armed, each call records a snapshot first — polling /health then
    IS the history feed (bounded by the ring), so the burn windows fill at
    the poller's cadence instead of needing a second config knob."""
    hist = default_history()
    if not hist.armed:
        hist.record()
    snaps = hist.snapshots()
    rep = evaluate(default_slos(), snaps, fast_n=fast_n, slow_n=slow_n)
    rep["snapshots"] = len(snaps)
    return rep
