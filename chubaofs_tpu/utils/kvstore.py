"""KVStore — Python face of the native libcfskv engine (RocksDB stand-in).

Reference counterpart: blobstore/common/kvstore/db.go:28,115-181 (gorocksdb
wrapper: Get/Put/Delete/WriteBatch/NewIterator-with-prefix) and
raftstore/raftstore_db. Kept: the same surface the reference code leans on —
point ops, crash-atomic write batches, ordered prefix scans, checkpoints for
raft snapshot streams — and the reference's native-engine split: the store
IS C++ (native/kvstore/kvstore.cc), loaded via ctypes the way the reference
loads RocksDB via cgo.

`PyKV` is a byte-compatible pure-Python engine: it reads and writes the
exact log format (same CRC framing), so a directory written by one engine
opens under the other. It serves two jobs: a fallback where no C++ toolchain
exists, and a cross-implementation correctness check (tests open each
engine's files with the other).
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
import zlib

from chubaofs_tpu.utils.locks import SanitizedLock

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native", "kvstore")
_SO_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "build", "libcfskv.so"))

_PUT, _DEL, _BATCH = 1, 2, 3
_U32 = struct.Struct("<I")
_SUB = struct.Struct("<BII")


class KVError(Exception):
    pass


# -- native engine loading -----------------------------------------------------

_lib = None
_lib_failed = False  # a failed build is cached: pay the make attempt once
_lib_lock = threading.Lock()


def _build_native() -> bool:
    try:
        subprocess.run(["make", "-C", os.path.abspath(_NATIVE_DIR)],
                       check=True, capture_output=True, timeout=120)
        return os.path.exists(_SO_PATH)
    except (OSError, subprocess.SubprocessError):
        return False


def _load_native():
    global _lib, _lib_failed
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _lib_failed:
            return None
        if not os.path.exists(_SO_PATH) and not _build_native():
            _lib_failed = True
            return None
        lib = ctypes.CDLL(_SO_PATH)
        lib.cfskv_open.restype = ctypes.c_void_p
        lib.cfskv_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        lib.cfskv_close.argtypes = [ctypes.c_void_p]
        lib.cfskv_errmsg.restype = ctypes.c_char_p
        lib.cfskv_errmsg.argtypes = [ctypes.c_void_p]
        lib.cfskv_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                                  ctypes.c_char_p, ctypes.c_int]
        lib.cfskv_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        lib.cfskv_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                                  ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
                                  ctypes.POINTER(ctypes.c_int)]
        lib.cfskv_free.argtypes = [ctypes.POINTER(ctypes.c_char)]
        lib.cfskv_batch.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int, ctypes.c_int]
        lib.cfskv_scan.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                                   ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                                   ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
                                   ctypes.POINTER(ctypes.c_int)]
        lib.cfskv_count.restype = ctypes.c_long
        lib.cfskv_count.argtypes = [ctypes.c_void_p]
        lib.cfskv_compact.argtypes = [ctypes.c_void_p]
        lib.cfskv_checkpoint.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        _lib = lib
        return lib


class NativeKV:
    """ctypes binding over libcfskv (the cgo-RocksDB analog)."""

    def __init__(self, path: str):
        lib = _load_native()
        if lib is None:
            raise KVError("libcfskv.so unavailable (no toolchain?)")
        self._lib = lib
        err = ctypes.create_string_buffer(512)
        self._h = lib.cfskv_open(path.encode(), err, len(err))
        if not self._h:
            raise KVError(f"open {path}: {err.value.decode()}")
        self._lock = SanitizedLock(name="kvstore.native")

    def _check(self, rc: int):
        if rc < 0:
            raise KVError(self._lib.cfskv_errmsg(self._h).decode())

    def put(self, key: bytes, value: bytes) -> None:
        self._check(self._lib.cfskv_put(self._h, key, len(key), value, len(value)))

    def get(self, key: bytes) -> bytes | None:
        out = ctypes.POINTER(ctypes.c_char)()
        n = ctypes.c_int()
        rc = self._lib.cfskv_get(self._h, key, len(key),
                                 ctypes.byref(out), ctypes.byref(n))
        if rc == 1:
            return None
        self._check(rc)
        try:
            return ctypes.string_at(out, n.value)
        finally:
            self._lib.cfskv_free(out)

    def delete(self, key: bytes) -> None:
        self._check(self._lib.cfskv_del(self._h, key, len(key)))

    def write_batch(self, puts=(), deletes=()) -> None:
        """Crash-atomic batch (gorocksdb WriteBatch analog)."""
        buf = bytearray()
        count = 0
        for k, v in puts:
            buf += _SUB.pack(_PUT, len(k), len(v)) + k + v
            count += 1
        for k in deletes:
            buf += _SUB.pack(_DEL, len(k), 0) + k
            count += 1
        if not count:
            return
        self._check(self._lib.cfskv_batch(self._h, bytes(buf), len(buf), count))

    def scan(self, prefix: bytes = b"", start: bytes = b"",
             limit: int = 1 << 30) -> list[tuple[bytes, bytes]]:
        out = ctypes.POINTER(ctypes.c_char)()
        n = ctypes.c_int()
        rc = self._lib.cfskv_scan(self._h, prefix, len(prefix), start,
                                  len(start), limit,
                                  ctypes.byref(out), ctypes.byref(n))
        self._check(rc)
        try:
            blob = ctypes.string_at(out, n.value)
        finally:
            self._lib.cfskv_free(out)
        pairs, off = [], 0
        while off < len(blob):
            klen, vlen = _U32.unpack_from(blob, off)[0], _U32.unpack_from(blob, off + 4)[0]
            off += 8
            pairs.append((blob[off:off + klen], blob[off + klen:off + klen + vlen]))
            off += klen + vlen
        return pairs

    def count(self) -> int:
        return self._lib.cfskv_count(self._h)

    def compact(self) -> None:
        self._check(self._lib.cfskv_compact(self._h))

    def checkpoint(self, out_dir: str) -> None:
        self._check(self._lib.cfskv_checkpoint(self._h, out_dir.encode()))

    def close(self) -> None:
        with self._lock:
            if self._h:
                self._lib.cfskv_close(self._h)
                self._h = None


class PyKV:
    """Pure-Python engine writing the identical on-disk format."""

    COMPACT_MIN_DEAD = 4 << 20

    def __init__(self, path: str):
        self.dir = path
        os.makedirs(path, exist_ok=True)
        # same single-handle discipline as the native engine: a second live
        # handle would keep appending to a log that compaction unlinks
        import fcntl

        self._lockf = open(os.path.join(path, "LOCK"), "a+")
        try:
            fcntl.flock(self._lockf, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            self._lockf.close()
            raise KVError(f"store {path} already open (LOCK held)") from None
        self.index: dict[bytes, bytes] = {}
        self._live = 0
        self._total = 0
        self._lock = SanitizedLock(name="kvstore.pykv")
        ids = sorted(int(f[:8]) for f in os.listdir(path)
                     if len(f) == 12 and f.endswith(".log"))
        for i, fid in enumerate(ids):
            self._replay(self._log_path(fid), last=(i + 1 == len(ids)))
        self.active_id = ids[-1] if ids else 1
        self._f = open(self._log_path(self.active_id), "ab")

    def _log_path(self, fid: int) -> str:
        return os.path.join(self.dir, f"{fid:08d}.log")

    def _replay(self, path: str, last: bool):
        with open(path, "rb") as f:
            data = f.read()
        off = 0
        while off + 13 <= len(data):
            (crc,) = _U32.unpack_from(data, off)
            typ, a, b = _SUB.unpack_from(data, off + 4)
            body_len = 9 + b if typ == _BATCH else 9 + a + b
            if off + 4 + body_len > len(data):
                break
            body = data[off + 4:off + 4 + body_len]
            if zlib.crc32(body) != crc or not self._apply_body(body):
                break
            off += 4 + body_len
        self._total += off
        if off != len(data):
            if not last:
                raise KVError(f"corrupt log {path}")
            with open(path, "r+b") as f:
                f.truncate(off)

    def _apply(self, typ: int, k: bytes, v: bytes):
        if typ == _PUT:
            old = self.index.get(k)
            if old is not None:
                self._live -= len(k) + len(old)
            self.index[k] = v
            self._live += len(k) + len(v)
        elif typ == _DEL:
            old = self.index.pop(k, None)
            if old is not None:
                self._live -= len(k) + len(old)

    def _apply_body(self, body: bytes) -> bool:
        typ, a, b = _SUB.unpack_from(body, 0)
        if typ == _BATCH:
            q, rem, n = 9, len(body) - 9, 0
            while rem >= 9 and n < a:
                t, kl, vl = _SUB.unpack_from(body, q)
                if rem < 9 + kl + vl:
                    return False
                self._apply(t, body[q + 9:q + 9 + kl],
                            body[q + 9 + kl:q + 9 + kl + vl])
                q += 9 + kl + vl
                rem -= 9 + kl + vl
                n += 1
            return rem == 0 and n == a
        if 9 + a + b != len(body):
            return False
        self._apply(typ, body[9:9 + a], body[9 + a:9 + a + b])
        return True

    @staticmethod
    def _frame(body: bytes) -> bytes:
        return _U32.pack(zlib.crc32(body)) + body

    def _append_locked(self, body: bytes):
        framed = self._frame(body)
        self._f.write(framed)
        self._f.flush()
        self._total += len(framed)

    def put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._append_locked(_SUB.pack(_PUT, len(key), len(value)) + key + value)
            self._apply(_PUT, key, value)
            self._maybe_compact()

    def get(self, key: bytes) -> bytes | None:
        with self._lock:
            return self.index.get(key)

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._append_locked(_SUB.pack(_DEL, len(key), 0) + key)
            self._apply(_DEL, key, b"")
            self._maybe_compact()

    def write_batch(self, puts=(), deletes=()) -> None:
        payload = bytearray()
        count = 0
        for k, v in puts:
            payload += _SUB.pack(_PUT, len(k), len(v)) + k + v
            count += 1
        for k in deletes:
            payload += _SUB.pack(_DEL, len(k), 0) + k
            count += 1
        if not count:
            return
        with self._lock:
            body = _SUB.pack(_BATCH, count, len(payload)) + bytes(payload)
            self._append_locked(body)
            self._apply_body(body)
            self._maybe_compact()

    def scan(self, prefix: bytes = b"", start: bytes = b"",
             limit: int = 1 << 30) -> list[tuple[bytes, bytes]]:
        with self._lock:
            lo = max(prefix, start)
            keys = sorted(k for k in self.index
                          if k >= lo and k.startswith(prefix))
            return [(k, self.index[k]) for k in keys[:limit]]

    def count(self) -> int:
        with self._lock:
            return len(self.index)

    def _write_full(self, path: str):
        tmp = path + ".tmp"
        with open(tmp, "wb") as out:
            for k in sorted(self.index):
                v = self.index[k]
                out.write(self._frame(_SUB.pack(_PUT, len(k), len(v)) + k + v))
            out.flush()
            os.fsync(out.fileno())
        os.replace(tmp, path)

    def _maybe_compact(self):
        if self._total > self._live + len(self.index) * 13 + self.COMPACT_MIN_DEAD:
            self._compact_locked()

    def _compact_locked(self):
        nxt = self.active_id + 1
        self._write_full(self._log_path(nxt))
        self._f.close()
        for fid in range(1, self.active_id + 1):
            try:
                os.remove(self._log_path(fid))
            except FileNotFoundError:
                pass
        self.active_id = nxt
        self._f = open(self._log_path(nxt), "ab")
        self._total = sum(len(k) + len(v) + 13 for k, v in self.index.items())

    def compact(self) -> None:
        with self._lock:
            self._compact_locked()

    def checkpoint(self, out_dir: str) -> None:
        with self._lock:
            os.makedirs(out_dir, exist_ok=True)
            self._write_full(os.path.join(out_dir, f"{1:08d}.log"))

    def close(self) -> None:
        with self._lock:
            if self._f:
                self._f.close()
                self._f = None
            if self._lockf:
                self._lockf.close()  # releases the flock
                self._lockf = None


def open_kv(path: str, engine: str = "auto"):
    """Open a KV store. engine: 'native' | 'python' | 'auto' (native when the
    shared library loads, else python — same files either way)."""
    if engine == "python":
        return PyKV(path)
    if engine == "native":
        return NativeKV(path)
    try:
        return NativeKV(path)
    except KVError:
        return PyKV(path)
