"""Pooled TCP connections keyed by address (util/conn_pool.go analog).

The reference pools idle conns per target with an idle timeout and closes on
error (util/conn_pool.go); same policy here. A checked-out socket is returned
via put(ok=...) — broken sockets are dropped, healthy ones reused."""

from __future__ import annotations

import socket
import time

from chubaofs_tpu.utils.locks import SanitizedLock


class ConnPool:
    def __init__(self, idle_timeout: float = 30.0, connect_timeout: float = 5.0,
                 io_timeout: float = 30.0):
        self.idle_timeout = idle_timeout
        self.connect_timeout = connect_timeout
        self.io_timeout = io_timeout
        self._idle: dict[str, list[tuple[socket.socket, float]]] = {}
        self._lock = SanitizedLock(name="conn_pool.idle")

    @staticmethod
    def _split(addr: str) -> tuple[str, int]:
        host, port = addr.rsplit(":", 1)
        return host, int(port)

    def get(self, addr: str) -> socket.socket:
        with self._lock:
            bucket = self._idle.get(addr, [])
            while bucket:
                sock, ts = bucket.pop()
                if time.monotonic() - ts <= self.idle_timeout:
                    return sock
                sock.close()
        host, port = self._split(addr)
        sock = socket.create_connection((host, port), timeout=self.connect_timeout)
        sock.settimeout(self.io_timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def put(self, addr: str, sock: socket.socket, ok: bool = True) -> None:
        if not ok:
            sock.close()
            return
        with self._lock:
            self._idle.setdefault(addr, []).append((sock, time.monotonic()))

    def close(self) -> None:
        with self._lock:
            for bucket in self._idle.values():
                for sock, _ in bucket:
                    sock.close()
            self._idle.clear()
