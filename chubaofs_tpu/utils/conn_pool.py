"""Pooled TCP connections keyed by address (util/conn_pool.go analog).

The reference pools idle conns per target with an idle timeout and closes on
error (util/conn_pool.go); same policy here. A checked-out socket is returned
via put(ok=...) — broken sockets are dropped, healthy ones reused.

Observability parity with rpc/pool.py (ISSUE 8 satellite): every checkout is
a `cfs_connpool_reuse` (warm socket handed back out) or `cfs_connpool_miss`
(fresh connect), and every idle-timeout drop is a `cfs_connpool_evict` — the
same reuse/miss/evict truth the HTTP pool reports, so a packet-TCP client's
churn is visible on /metrics. Socket close() never happens under the pool
lock (close can block in the kernel flushing a dead peer's send buffer — the
exact 181 ms hold-time class the lock sanitizer caught in rpc/pool)."""

from __future__ import annotations

import socket
import time

from chubaofs_tpu.utils.exporter import registry
from chubaofs_tpu.utils.locks import SanitizedLock


class ConnPool:
    def __init__(self, idle_timeout: float = 30.0, connect_timeout: float = 5.0,
                 io_timeout: float = 30.0):
        self.idle_timeout = idle_timeout
        self.connect_timeout = connect_timeout
        self.io_timeout = io_timeout
        self._idle: dict[str, list[tuple[socket.socket, float]]] = {}
        self._lock = SanitizedLock(name="conn_pool.idle")
        reg = registry("connpool")
        self._reuse = reg.counter("reuse")
        self._miss = reg.counter("miss")
        self._evict = reg.counter("evict")

    @staticmethod
    def _split(addr: str) -> tuple[str, int]:
        host, port = addr.rsplit(":", 1)
        return host, int(port)

    def get(self, addr: str) -> socket.socket:
        stale: list[socket.socket] = []
        found: socket.socket | None = None
        with self._lock:
            bucket = self._idle.get(addr, [])
            while bucket:
                sock, ts = bucket.pop()
                if time.monotonic() - ts <= self.idle_timeout:
                    found = sock
                    break
                stale.append(sock)
        # closes happen OUTSIDE the lock: a dead peer's close can block in
        # the kernel, and holding the pool lock through it starves every
        # other checkout (the rpc/pool 181 ms hold-time bug class)
        for sock in stale:
            self._evict.add()
            sock.close()
        if found is not None:
            self._reuse.add()
            return found
        self._miss.add()
        host, port = self._split(addr)
        sock = socket.create_connection((host, port), timeout=self.connect_timeout)
        sock.settimeout(self.io_timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def put(self, addr: str, sock: socket.socket, ok: bool = True) -> None:
        if not ok:
            sock.close()
            return
        with self._lock:
            self._idle.setdefault(addr, []).append((sock, time.monotonic()))

    def close(self) -> None:
        with self._lock:
            buckets = list(self._idle.values())
            self._idle.clear()
        for bucket in buckets:
            for sock, _ in bucket:
                sock.close()
