"""Event journal — the cluster's structured state-transition timeline.

The observability plane's third leg (spans tell WHERE time went, metrics
tell HOW MUCH, events tell WHAT CHANGED): every significant transition —
a disk leaving NORMAL, a repair lease granted or expired, a tier promote
committed, a raft leadership change, an evloop backpressure flip, an SLO
status flip, a chaos injection — lands as ONE typed record in a per-daemon
`EventJournal`:

    bounded in-memory ring (CFS_EVENTS_LEN) for the /events HTTP side-door
        +
    rotating JSONL trail (CFS_EVENT_BYTES / CFS_EVENT_FILES) through the
    same utils/auditlog.RotatingFile rotor as the slow-op audit

so "why did the SLO flip at 14:02" stops being a nine-daemon grep and
becomes `cfs-events --since 300` (tools/cfsevents.py merges the cluster's
journals via the console `/api/events` rollup, cursor-paged).

Records carry a wall stamp (display / cross-daemon merge), a monotonic
stamp (same-process ordering that survives NTP steps), a monotonically
increasing `seq` (the pagination cursor), role/addr (stamped by the daemon
at RPCServer boot), severity, a type from the closed EVENT_TYPES set, an
entity string, a small detail dict, and an optional trace id — auto-filled
from the current span when one is live, so a repair task's terminal event
joins the repair trace without the emitter knowing about tracing
(`cfs-events --correlate <trace-id>` is that join).

Discipline:

  * `emit()` NEVER raises — it runs inside serve loops, lock-sanitizer
    callbacks, and scheduler threads where a full disk must degrade to a
    lost timeline line, not a dead daemon.
  * The plane records TRANSITIONS, never per-op traffic: no PUT/GET/packet
    path calls emit(). perfbench's events-overhead smoke pins that down
    (a MiniCluster PUT/GET burst must emit zero events).
  * `cfs_events_total{type,severity}` counters ride the PR-11 bounded-label
    guard: both label keys are declared closed sets, so a typo'd event type
    fails loudly at the metric layer instead of minting unbounded series.
"""

from __future__ import annotations

import json
import os
import tempfile
import time

from chubaofs_tpu.utils.auditlog import RotatingFile
from chubaofs_tpu.utils.locks import SanitizedLock

SEV_INFO, SEV_WARNING, SEV_CRITICAL = "info", "warning", "critical"
SEVERITIES = (SEV_INFO, SEV_WARNING, SEV_CRITICAL)

# the closed event-type set (obslint rule 1 spirit, enforced at runtime by
# exporter.declare_label_values): a new transition class is added HERE, not
# minted ad hoc at a call site
EVENT_TYPES = (
    "daemon_boot",          # RPCServer came up (role/addr stamp)
    "disk_status",          # clustermgr disk NORMAL->BROKEN->DROPPED
    "lease_acquired",       # scheduler handed a task to a worker
    "lease_expired",        # reaper requeued a silent worker's task
    "task_finished",        # repair/migrate/tier task went terminal-OK
    "task_failed",          # task went terminal FAILED
    "tier_promote",         # hot-tier redirect committed
    "tier_demote",          # hot-tier redirect dropped
    "partition_moved",      # master re-homed a partition replica
    "meta_split",           # mid-range meta split: freeze/commit/complete
    "meta_migrate",         # meta partition replica add-peer/remove-peer
    "node_decommissioned",  # master drained a node
    "scrub_finding",        # blobnode CRC scrub found bad shards
    "raft_leader",          # a raft group elected this node leader
    "backpressure_on",      # evloop paused reads on a connection
    "backpressure_off",     # evloop resumed reads
    "slo_flip",             # an SLO changed status (ok<->degraded<->failing)
    "lock_inversion",       # lock-order sanitizer saw a cycle
    "chaos_inject",         # chaos scheduler injected a fault plan step
    "chaos_lift",           # chaos scheduler lifted a fault
    "failpoint_armed",      # a failpoint was armed
    "failpoint_disarmed",   # a failpoint was disarmed
    "alert_firing",         # an alert rule started firing
    "alert_resolved",       # a firing alert cleared
    "qos_throttle",         # gateway QoS throttled a tenant (episode, 1/s)
    "bench_tick",           # perfbench events-overhead smoke traffic
    "incident_capture",     # flight recorder froze a capture bundle
    "autopilot_considered",  # a firing alert matched an armed binding
    "autopilot_damped",      # flap damper / cooldown held an action back
    "autopilot_refused",     # hourly action budget exhausted
    "autopilot_executed",    # an actuator ran (or dry-run logged intent)
    "autopilot_rolled_back",  # strict-improvement gate undid a nudge
)

_SEV_RANK = {SEV_INFO: 0, SEV_WARNING: 1, SEV_CRITICAL: 2}

_ENV_LEN = "CFS_EVENTS_LEN"
_ENV_BYTES = "CFS_EVENT_BYTES"
_ENV_FILES = "CFS_EVENT_FILES"
DEFAULT_LEN = 2048

# process boot stamp (wall): the cfs_boot_time_seconds gauge every daemon
# exports, and the UP / (restart) cross-check cfs-top renders. Wall on
# purpose — it is cross-process protocol (scrapers subtract it from their
# own wall clock), exactly like heartbeat stamps.
BOOT_TS = time.time()


class EventJournal:
    """Bounded ring + rotating JSONL of typed transition records."""

    def __init__(self, logdir: str, role: str = "", addr: str = "",
                 ring_len: int | None = None, max_bytes: int | None = None,
                 max_files: int | None = None):
        from chubaofs_tpu.utils.config import env_int

        self.dir = logdir
        self.role = role
        self.addr = addr
        self._ring_len = ring_len or env_int(_ENV_LEN, DEFAULT_LEN)
        self._rotor = RotatingFile(
            logdir, "events",
            max_bytes if max_bytes is not None else env_int(_ENV_BYTES,
                                                            4 << 20),
            max_files if max_files is not None else env_int(_ENV_FILES, 4))
        self._ring: list[dict] = []
        self._seq = 0
        self._lock = SanitizedLock(name="events.journal")
        self._declare_labels()

    @staticmethod
    def _declare_labels() -> None:
        """The runtime half of the closed-set contract: cfs_events_total's
        label values are bounded BY DECLARATION, so an undeclared type
        string fails at the metric call instead of growing /metrics.

        This RESERVES the bare label keys `type`/`severity` process-wide
        (declare_label_values is keyed by label name): no metric family
        uses either key today, and any future one must either carry a
        declared event type/severity or pick a scoped key — a loud
        ValueError at the call site, which is the guard working, not a
        collision to paper over."""
        from chubaofs_tpu.utils.exporter import declare_label_values

        declare_label_values("type", EVENT_TYPES)
        declare_label_values("severity", SEVERITIES)

    # -- ingest ----------------------------------------------------------------

    def emit(self, etype: str, severity: str = SEV_INFO, *, entity: str = "",
             detail: dict | None = None, trace_id: str | None = None) -> dict:
        """Append one event; returns the record. Raises on an unknown type
        or severity — emitters are code, and a typo'd type is a bug the
        module-level emit() wrapper reports rather than records."""
        if etype not in EVENT_TYPES:
            raise ValueError(f"unknown event type {etype!r}; add it to "
                             "events.EVENT_TYPES")
        if severity not in _SEV_RANK:
            raise ValueError(f"unknown severity {severity!r}")
        if trace_id is None:
            # join the live span's trace when one exists: the emitter gets
            # trace correlation (cfs-events --correlate) for free
            try:
                from chubaofs_tpu.blobstore import trace

                span = trace.current_span()
                if span is not None:
                    trace_id = span.trace_id
            except Exception:
                trace_id = None
        rec = {"ts": time.time(), "mono": time.monotonic(),
               "role": self.role, "addr": self.addr,
               "severity": severity, "type": etype, "entity": entity,
               "detail": dict(detail or {})}
        if trace_id:
            rec["trace_id"] = trace_id
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
            if len(self._ring) > self._ring_len:
                del self._ring[: len(self._ring) - self._ring_len]
            # the JSONL line lands INSIDE the seq critical section: the
            # on-disk trail (which outlives the ring) must stay seq-ordered,
            # and a preempted emitter writing after a later seq would break
            # every oldest-first read_lines() consumer
            self._rotor.write_line(json.dumps(rec, default=str))
        from chubaofs_tpu.utils.exporter import registry

        registry("events").counter(
            "total", {"type": etype, "severity": severity}).add()
        return rec

    # -- queries ---------------------------------------------------------------

    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def query(self, since: int = 0, n: int = 200,
              types: tuple | list | None = None,
              severity: tuple | list | None = None,
              min_ts: float = 0.0) -> tuple[list[dict], int]:
        """Events with seq > since (oldest first, at most n), plus the
        cursor to pass as the NEXT since. The cursor advances past filtered-
        out events too, so a poller never re-fetches what it chose to skip;
        it only stops short when `n` truncated the page (resume there)."""
        with self._lock:
            ring = list(self._ring)
            last = self._seq
        if since > last:
            # the poller's cursor outruns this journal's head: seq is
            # process-local, so the daemon RESTARTED and the cursor belongs
            # to its previous life. Reset to the start — the restart-era
            # events are exactly the forensics a cursor must not skip —
            # rather than blinding the poller forever behind a stale seq.
            since = 0
        out = []
        cursor = since
        for rec in ring:
            if rec["seq"] <= since:
                continue
            if len(out) >= max(0, n):
                return out, cursor  # page full: resume from the last taken
            cursor = rec["seq"]
            if types and rec["type"] not in types:
                continue
            if severity and rec["severity"] not in severity:
                continue
            if min_ts and rec["ts"] < min_ts:
                continue
            out.append(rec)
        # the whole ring was examined (truncated pages returned above):
        # the cursor is the journal head, even when old events already
        # fell out of the ring
        return out, max(cursor, last)

    def close(self):
        self._rotor.close()


# -- process-wide default ------------------------------------------------------

_default: EventJournal | None = None
_lock = SanitizedLock(name="events.default")


def default_journal() -> EventJournal:
    """The process journal, created on first use: directory from
    CFS_EVENTS_DIR (default a per-process tmpdir), budgets from env."""
    global _default
    with _lock:
        if _default is None:
            logdir = os.environ.get("CFS_EVENTS_DIR") or os.path.join(
                tempfile.gettempdir(), f"cfs-events-{os.getpid()}")
            _default = EventJournal(logdir)
        return _default


def configure(logdir: str | None = None, role: str | None = None,
              addr: str | None = None) -> EventJournal:
    """(Re)bind the process journal — daemons stamp their role/addr at
    RPCServer boot, tests point it at a tmpdir. Passing only role/addr
    retags in place (the ring and rotor carry forward); a logdir change
    rebuilds the journal."""
    global _default
    with _lock:
        if _default is not None and logdir is not None \
                and logdir != _default.dir:
            _default.close()
            _default = None
        if _default is None:
            _default = EventJournal(
                logdir or os.environ.get("CFS_EVENTS_DIR") or os.path.join(
                    tempfile.gettempdir(), f"cfs-events-{os.getpid()}"),
                role=role or "", addr=addr or "")
        else:
            if role is not None:
                _default.role = role
            if addr is not None:
                _default.addr = addr
        return _default


def reset() -> None:
    """Close + forget the process journal (test isolation)."""
    global _default
    with _lock:
        j, _default = _default, None
    if j is not None:
        j.close()


def emit(etype: str, severity: str = SEV_INFO, *, entity: str = "",
         detail: dict | None = None, trace_id: str | None = None) -> bool:
    """The one emitter every subsystem calls. NEVER raises — it runs in
    serve loops, reaper threads, and sanitizer callbacks, where a full disk
    or a mis-typed detail value must degrade to a lost timeline line, not a
    dead daemon. Returns True when the event was recorded."""
    try:
        default_journal().emit(etype, severity, entity=entity, detail=detail,
                               trace_id=trace_id)
        return True
    except Exception:
        return False


def recent_page(n: int = 200, types: tuple | list | None = None,
                severity: tuple | list | None = None
                ) -> tuple[list[dict], int]:
    """The newest n matching events (oldest first) plus the journal-head
    cursor FROM THE SAME QUERY — the /events one-shot response (a separate
    last_seq() read could race a fresh emit and hand a cursor that skips
    it). n<=0 is an empty window, never the whole-ring [-0:] slice."""
    evs, cursor = default_journal().query(since=0, n=10 ** 9, types=types,
                                          severity=severity)
    return (evs[-n:] if n > 0 else []), cursor


def recent(n: int = 200, types: tuple | list | None = None,
           severity: tuple | list | None = None) -> list[dict]:
    """The newest n matching events, oldest first."""
    return recent_page(n, types, severity)[0]
