"""Per-tenant QoS/admission plane for the S3 gateway (ISSUE 14).

Reference counterparts: blobstore/access/limiter.go (read/write bandwidth +
concurrency gates on the gateway) and the reference object gateway's
per-user traffic shaping — one abusive tenant must not flip every tenant's
SLO burn windows (the mixed-tenant regimes of arxiv 1709.05365 are the
workload model `cfs-capacity` drives).

Shape:

  * Tenant identity is the sigv4 ACCESS KEY the request claims (parsed
    pre-auth by `objectnode.auth.access_key_of`) — shaping runs BEFORE the
    signature check on purpose: throttling must cost less than the HMAC
    chain it protects. A spoofed key burns the spoofed tenant's budget; the
    signature check still rejects the request afterward, exactly like the
    reference gateways that shape on the parsed credential.
  * Two resources, each a `FairLimiter`: request RATE (cost 1/request) and
    BANDWIDTH (cost = body bytes in; response bytes are debited post-hoc,
    driving the bucket negative until the debt refills). Each limiter is a
    shared PARENT token bucket (the total cap) plus optional per-tenant
    child buckets (hard caps). Idle capacity is work-conserving: a lone
    tenant can use the whole parent; under saturation a deficit-style
    round-robin queue grants parent tokens fairly across the tenants
    waiting, so the noisy tenant queues behind its own backlog while the
    victim's occasional request is granted almost immediately.
  * Hard denials answer 429 (caps, queue timeout) or 503 (queue overflow)
    with a `Retry-After` estimate from the bucket's refill rate.
  * Observability: `cfs_objectnode_requests{tenant}`,
    `cfs_objectnode_throttled{tenant,bucket,reason}`,
    `cfs_objectnode_bytes{tenant,dir}` — tenant label values BOUNDED via
    `exporter.declare_label_values` (declared tenants + "other" +
    "anonymous"; undeclared keys fold into "other", so an attacker minting
    random access keys cannot mint metric series). A `qos_throttle` event
    (rate-limited to one per tenant+bucket per second) lands on the
    timeline with the deficit in the detail dict, and per-tenant
    throttle-ratio SLOs ride utils/slo.py's provider hook so ONLY the
    abusive tenant's objective flips.

Knobs (all unset = plane dormant, zero per-request overhead — the
middleware is simply never installed):

    CFS_QOS_RPS             total request-rate cap, requests/s (parent)
    CFS_QOS_BW_MB           total bandwidth cap, MiB/s (parent)
    CFS_QOS_TENANT_RPS      per-tenant hard request-rate cap (child)
    CFS_QOS_TENANT_BW_MB    per-tenant hard bandwidth cap (child)
    CFS_QOS_TENANT_MIN_RPS  per-tenant GUARANTEED request rate (reserve
                            child bucket — admitted without queueing; size
                            sum(guarantees) <= the parent cap)
    CFS_QOS_TENANT_MIN_BW_MB  per-tenant guaranteed bandwidth
    CFS_QOS_TENANTS       comma-separated declared tenant access keys
    CFS_QOS_QUEUE_MS      max fair-queue wait when saturated (default 200)
    CFS_QOS_QUEUE         max queued requests per tenant (default 64)
    CFS_SLO_QOS_THROTTLE  per-tenant SLO threshold on throttled/requests
                          (default 0.5), read at evaluation time
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from chubaofs_tpu.utils.locks import SanitizedLock
from chubaofs_tpu.utils.ratelimit import TokenBucket

ANON = "anonymous"
OTHER = "other"

# bandwidth DRR quantum: enough for a small op per turn, so mixed small/large
# tenants still alternate instead of a large op starving the wheel
_BW_QUANTUM = 64 << 10


def _env_f(name: str, default: float = 0.0) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_i(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, "") or default))
    except ValueError:
        return default


class Decision:
    """One admission verdict. `ok` admits; otherwise `status`/`reason`/
    `retry_after`/`deficit` describe the throttle for the reply, the
    metrics, and the timeline event."""

    __slots__ = ("ok", "status", "bucket", "reason", "retry_after", "deficit",
                 "queued_ms")

    def __init__(self, ok: bool, status: int = 0, bucket: str = "",
                 reason: str = "", retry_after: float = 0.0,
                 deficit: float = 0.0, queued_ms: float = 0.0):
        self.ok = ok
        self.status = status
        self.bucket = bucket
        self.reason = reason
        self.retry_after = retry_after
        self.deficit = deficit
        self.queued_ms = queued_ms


_OK = Decision(True)

# every live plane in this process: the bounded 'tenant' label declaration
# is the union of their label sets (see QosPlane.__init__/close)
_active_planes: list = []
_planes_lock = threading.Lock()


def _redeclare_tenants_locked() -> None:
    from chubaofs_tpu.utils.exporter import declare_label_values

    if not _active_planes:
        declare_label_values("tenant", None)
        return
    union: set = set()
    for p in _active_planes:
        union |= p._labels
    declare_label_values("tenant", sorted(union))


class FairLimiter:
    """One resource's shared-parent + per-tenant-child shaping with a
    deficit-round-robin wait queue.

    Admission: the per-tenant HARD cap (child bucket) is checked first —
    a capped tenant is denied outright, no queueing (it asked for more
    than it bought). Then the shared parent: free tokens admit
    immediately WHEN NOBODY IS QUEUED (no line-jumping); a saturated
    parent parks the request in its tenant's FIFO and a deficit-style
    round-robin pump grants refilling parent tokens one tenant at a time,
    so capacity under contention splits fairly regardless of offered
    load. Bounded wait (`queue_ms`) then 429; bounded queue depth then
    503."""

    def __init__(self, name: str, parent_rate: float, tenant_rate: float,
                 reserve_rate: float = 0.0, quantum: float = 1.0,
                 queue_ms: float = 200.0, queue_len: int = 64):
        self.name = name  # "rate" | "bandwidth" (the metric/event label)
        self.parent = TokenBucket(parent_rate) if parent_rate > 0 else None
        self.tenant_rate = tenant_rate      # per-tenant HARD cap
        self.reserve_rate = reserve_rate    # per-tenant GUARANTEED share
        self.quantum = quantum
        self.queue_ms = queue_ms
        self.queue_len = queue_len
        self._children: dict[str, TokenBucket] = {}
        self._reserves: dict[str, TokenBucket] = {}
        self._queues: dict[str, deque] = {}
        self._rr: deque = deque()          # tenants with queued waiters
        self._deficit: dict[str, float] = {}
        self._waiting = 0                  # waiters currently parked
        # each parked waiter occupies an evloop dispatch worker for up to
        # queue_ms: bound the herd to HALF the worker pool so a shaped
        # flood's queue can never starve the workers that serve admitted
        # (reserve-bucket) requests
        self.max_waiting = max(4, _env_i("CFS_EVLOOP_WORKERS", 16) // 2)
        self._lock = SanitizedLock(name=f"qos.{name}")

    def _bucket(self, table: dict, tenant: str, rate: float) \
            -> TokenBucket | None:
        if rate <= 0:
            return None
        with self._lock:
            b = table.get(tenant)
            if b is None:
                b = table[tenant] = TokenBucket(rate)
            return b

    @staticmethod
    def _take(bucket: TokenBucket, cost: float) -> bool:
        """Acquire `cost` from a bucket whose burst may be SMALLER than the
        cost (a 20 MiB PUT under a 10 MiB/s cap): acquire the burst's
        worth and debit the remainder, so the big op is admitted once and
        PACED by the debt it leaves — never permanently unadmittable
        (try_acquire(cost>burst) would be False forever, the trap
        TokenBucket.acquire's own `n > burst` guard documents)."""
        take = min(cost, bucket.burst)
        if not bucket.try_acquire(take):
            return False
        if cost > take:
            bucket.debit(cost - take)
        return True

    def debit(self, tenant: str, cost: float) -> None:
        """Post-hoc charge (response bytes): every configured bucket the
        tenant draws from goes negative and pays the debt down at its
        refill rate."""
        for b in (self._bucket(self._reserves, tenant, self.reserve_rate),
                  self._bucket(self._children, tenant, self.tenant_rate),
                  self.parent):
            if b is not None:
                b.debit(cost)

    def admit(self, tenant: str, cost: float) -> Decision:
        child = self._bucket(self._children, tenant, self.tenant_rate)
        if child is not None and not self._take(child, cost):
            wait = child.wait_time(min(cost, child.burst))
            return Decision(False, 429, self.name, "tenant_cap",
                            retry_after=wait,
                            deficit=wait * max(self.tenant_rate, 1.0))
        # the tenant's GUARANTEED share (child reserve bucket): admitted
        # without touching the parent or the queue, so a within-guarantee
        # tenant never waits behind a noisy neighbor's backlog — the victim
        # p99 protection. Sizing sum(reserves) <= parent is the operator's
        # contract (the borrow pool is what's left)
        reserve = self._bucket(self._reserves, tenant, self.reserve_rate)
        if reserve is not None and self._take(reserve, cost):
            return _OK
        if self.parent is None:
            return _OK
        # the queued cost is clamped to the parent's burst (the pump grants
        # it and the remainder is debited at grant time) — a cost the
        # parent could never accrue would otherwise wait out queue_ms for
        # a grant that cannot happen
        pcost = min(cost, self.parent.burst)
        with self._lock:
            if not self._rr and self._take(self.parent, cost):
                return _OK  # free capacity, nobody queued: no line-jump risk
            q = self._queues.setdefault(tenant, deque())
            if len(q) >= self.queue_len:
                return Decision(False, 503, self.name, "queue_full",
                                retry_after=self.parent.wait_time(pcost),
                                deficit=float(len(q)))
            # every queued waiter PARKS a dispatch worker for up to
            # queue_ms; bound the herd below the evloop pool or a shaped
            # flood starves the very tenants admission just protected
            if self._waiting >= self.max_waiting:
                return Decision(False, 429, self.name, "saturated",
                                retry_after=max(0.05,
                                                self.parent.wait_time(pcost)),
                                deficit=float(self._waiting))
            self._waiting += 1
            ev = threading.Event()
            # [event, parent-clamped cost, granted, debit-remainder]
            waiter = [ev, pcost, False, cost - pcost]
            q.append(waiter)
            if tenant not in self._rr:
                self._rr.append(tenant)
        t0 = time.monotonic()
        deadline = t0 + self.queue_ms / 1e3
        while True:
            self._pump()
            if waiter[2]:
                return Decision(True, queued_ms=(time.monotonic() - t0) * 1e3)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            # grants arrive via ev.set() from whichever waiter's pump runs;
            # the tick only exists so SOMEONE pumps as tokens refill. 20ms
            # keeps a saturated tenant's waiter herd from becoming a GIL
            # wakeup storm that the admitted tenants' tail latency pays for
            ev.wait(min(remaining, 0.02))
        with self._lock:
            granted = waiter[2]
            if not granted:
                try:
                    self._queues.get(tenant, deque()).remove(waiter)
                    self._waiting -= 1
                except ValueError:
                    granted = waiter[2]  # pump won the race after all
        if granted:
            return Decision(True, queued_ms=(time.monotonic() - t0) * 1e3)
        return Decision(False, 429, self.name, "saturated",
                        retry_after=max(0.05, self.parent.wait_time(pcost)),
                        deficit=self._deficit.get(tenant, 0.0))

    def _pump(self) -> None:
        """Grant refilled parent tokens to queued waiters, deficit-RR order:
        each pass around the wheel tops every waiting tenant's deficit up by
        one quantum and grants its head-of-line while the deficit and the
        parent both cover the cost — cost-fair across tenants whatever their
        op-size mix. Runs under the limiter lock; every waiter tick calls
        it, so refill progress needs no dedicated thread."""
        with self._lock:
            misses = 0
            while self._rr and misses < len(self._rr):
                t = self._rr[0]
                q = self._queues.get(t)
                if not q:
                    self._rr.popleft()
                    self._queues.pop(t, None)
                    self._deficit.pop(t, None)
                    continue
                self._deficit[t] = min(
                    self._deficit.get(t, 0.0) + self.quantum,
                    max(self.quantum, q[0][1]))
                if q[0][1] <= self._deficit[t] \
                        and self.parent.try_acquire(q[0][1]):
                    waiter = q.popleft()
                    self._deficit[t] -= waiter[1]
                    waiter[2] = True
                    if waiter[3]:  # burst-clamped cost: debit the rest so
                        self.parent.debit(waiter[3])  # the big op is paced
                    waiter[0].set()
                    self._waiting -= 1
                    misses = 0
                    # the serviced tenant goes to the BACK and the wheel
                    # position PERSISTS across pump calls — tokens that
                    # trickle in one at a time then alternate across
                    # waiting tenants instead of feeding whoever sits at
                    # the wheel's head (the whole fairness property)
                    if q:
                        self._rr.rotate(-1)
                    else:
                        self._rr.popleft()
                        self._queues.pop(t, None)
                        self._deficit.pop(t, None)
                else:
                    # can't serve this tenant NOW (deficit short or parent
                    # dry): let the others try this pass; capped deficits
                    # keep the repeated top-ups from accruing unfairly
                    self._rr.rotate(-1)
                    misses += 1


class QosPlane:
    """The gateway-side plane: admit/debit around every S3 request, wired
    as router middleware by objectnode when armed. Construction declares
    the bounded tenant label set, registers the per-tenant SLO provider,
    and mints the cfs_objectnode_* families; `close()` unwinds all of it
    (test hygiene)."""

    def __init__(self, tenants: tuple = (), rps: float = 0.0,
                 bw_mbs: float = 0.0, tenant_rps: float = 0.0,
                 tenant_bw_mbs: float = 0.0, tenant_min_rps: float = 0.0,
                 tenant_min_bw_mbs: float = 0.0, queue_ms: float = 200.0,
                 queue_len: int = 64):
        from chubaofs_tpu.utils import slo
        from chubaofs_tpu.utils.exporter import declare_label_values, registry

        self.tenants = tuple(tenants)
        self._labels = frozenset(self.tenants) | {ANON, OTHER}
        self.rate = FairLimiter("rate", rps, tenant_rps,
                                reserve_rate=tenant_min_rps, quantum=1.0,
                                queue_ms=queue_ms, queue_len=queue_len) \
            if (rps > 0 or tenant_rps > 0) else None
        self.bw = FairLimiter("bandwidth", bw_mbs * (1 << 20),
                              tenant_bw_mbs * (1 << 20),
                              reserve_rate=tenant_min_bw_mbs * (1 << 20),
                              quantum=_BW_QUANTUM,
                              queue_ms=queue_ms, queue_len=queue_len) \
            if (bw_mbs > 0 or tenant_bw_mbs > 0) else None
        self._reg = registry("objectnode")
        self._last_event: dict[tuple, float] = {}
        self._ev_lock = SanitizedLock(name="qos.events")
        # global surfaces (the bounded tenant label set, the SLO provider
        # table) are shared by every plane in the process — tests and
        # multi-gateway processes run several. Each plane registers under
        # its own key and the label declaration is the UNION of the active
        # planes', so constructing/closing one can neither 500 another's
        # admit() (undeclared-label ValueError) nor unregister its SLOs.
        with _planes_lock:
            _active_planes.append(self)
            _redeclare_tenants_locked()
        slo.register_slo_provider(f"qos:{id(self)}", self._slos)

    @classmethod
    def from_env(cls) -> "QosPlane | None":
        """CFS_QOS_*-armed plane, or None (the default: not installed, zero
        per-request overhead)."""
        rps = _env_f("CFS_QOS_RPS")
        bw = _env_f("CFS_QOS_BW_MB")
        t_rps = _env_f("CFS_QOS_TENANT_RPS")
        t_bw = _env_f("CFS_QOS_TENANT_BW_MB")
        if rps <= 0 and bw <= 0 and t_rps <= 0 and t_bw <= 0:
            return None
        tenants = tuple(t for t in
                        os.environ.get("CFS_QOS_TENANTS", "").split(",") if t)
        return cls(tenants, rps=rps, bw_mbs=bw, tenant_rps=t_rps,
                   tenant_bw_mbs=t_bw,
                   tenant_min_rps=_env_f("CFS_QOS_TENANT_MIN_RPS"),
                   tenant_min_bw_mbs=_env_f("CFS_QOS_TENANT_MIN_BW_MB"),
                   queue_ms=_env_f("CFS_QOS_QUEUE_MS", 200.0),
                   queue_len=int(_env_f("CFS_QOS_QUEUE", 64.0)))

    def close(self) -> None:
        from chubaofs_tpu.utils import slo

        slo.unregister_slo_provider(f"qos:{id(self)}")
        with _planes_lock:
            if self in _active_planes:
                _active_planes.remove(self)
            _redeclare_tenants_locked()

    # -- admission -------------------------------------------------------------

    def label(self, tenant: str | None) -> str:
        """Bounded metric/SLO label for a claimed access key: declared keys
        keep their identity, everything else folds into OTHER (an attacker
        minting random keys cannot mint series), no key at all is ANON."""
        if tenant is None:
            return ANON
        return tenant if tenant in self._labels else OTHER

    def admit(self, tenant: str | None, nbytes: int = 0):
        """Admit or throttle one request: returns None to proceed, or an
        rpc Response (429/503 + Retry-After) to answer instead. `tenant`
        is the claimed access key (None = anonymous); `nbytes` the request
        body size (the PUT-side bandwidth cost — response bytes are
        debited via debit_out)."""
        label = self.label(tenant)
        self._reg.counter("requests", {"tenant": label}).add()
        decision = _OK
        if self.rate is not None:
            decision = self.rate.admit(label, 1.0)
        if decision.ok and self.bw is not None and nbytes > 0:
            decision = self.bw.admit(label, float(nbytes))
        if decision.ok:
            if nbytes:
                self._reg.counter("bytes",
                                  {"tenant": label, "dir": "in"}).add(nbytes)
            if decision.queued_ms:
                self._reg.summary("queue_wait_ms").observe(decision.queued_ms)
            return None
        self._reg.counter("throttled",
                          {"tenant": label, "bucket": decision.bucket,
                           "reason": decision.reason}).add()
        self._emit_throttle(label, decision)
        retry = max(1, int(decision.retry_after + 0.999))
        from chubaofs_tpu.rpc.router import Response

        return Response(
            decision.status,
            {"Content-Type": "application/xml", "Retry-After": str(retry)},
            (f"<?xml version=\"1.0\"?><Error><Code>SlowDown</Code>"
             f"<Message>tenant {label} throttled: {decision.reason} "
             f"({decision.bucket})</Message></Error>").encode())

    def debit_out(self, tenant: str | None, nbytes: int) -> None:
        """Charge response bytes (GET bodies) against the bandwidth plane
        after the fact — the bucket goes negative and future admits wait."""
        if nbytes <= 0:
            return
        label = self.label(tenant)
        self._reg.counter("bytes", {"tenant": label, "dir": "out"}).add(nbytes)
        if self.bw is not None:
            self.bw.debit(label, float(nbytes))

    def _emit_throttle(self, label: str, decision: Decision) -> None:
        """qos_throttle -> timeline, rate-limited to one per tenant+bucket
        per second: the journal records the EPISODE, the counter the
        per-op volume."""
        now = time.monotonic()
        key = (label, decision.bucket)
        with self._ev_lock:
            if now - self._last_event.get(key, -9e9) < 1.0:
                return
            self._last_event[key] = now
        from chubaofs_tpu.utils import events

        events.emit("qos_throttle", events.SEV_WARNING, entity=label,
                    detail={"tenant": label, "bucket": decision.bucket,
                            "reason": decision.reason,
                            "deficit": round(decision.deficit, 3),
                            "retry_after": round(decision.retry_after, 3)})

    # -- per-tenant SLOs --------------------------------------------------------

    def _slos(self) -> list:
        """One throttle-ratio objective per declared tenant (+ OTHER/ANON):
        throttled/requests over the burn windows, so a capped noisy tenant
        flips ITS objective while the victim's stays green — the fairness
        verdict cfs-capacity's gate reads."""
        from chubaofs_tpu.utils.slo import SLO

        thr = _env_f("CFS_SLO_QOS_THROTTLE", 0.5)
        return [
            SLO(f"qos_throttle:{t}", "counter_ratio",
                "cfs_objectnode_throttled", thr,
                ops_family="cfs_objectnode_requests",
                label_in=("tenant", (t,)),
                description=f"tenant {t} throttled-request ratio")
            for t in sorted(self._labels)
        ]
