"""Circuit breaker — the access client's hystrix analog.

Reference counterpart: blobstore/access wraps allocator/proxy calls in
hystrix commands (stream_put.go:68 allocFromAllocatorWithHystrix), so a dead
or drowning control-plane dependency fails PUTs FAST instead of stacking
every request behind timeouts. Same contract here: count failures in a
sliding window; past the threshold the circuit OPENS and calls raise
CircuitOpen immediately for a cooldown; after the cooldown ONE probe call is
admitted (half-open) — success closes the circuit, failure re-opens it.
"""

from __future__ import annotations

import threading
import time


class CircuitOpen(Exception):
    """Fail-fast: the wrapped dependency is considered down."""


class CircuitBreaker:
    def __init__(self, name: str = "", failures: int = 5,
                 window: float = 10.0, cooldown: float = 15.0):
        self.name = name
        self.failures = failures
        self.window = window
        self.cooldown = cooldown
        self._lock = threading.Lock()
        self._fail_times: list[float] = []
        self._open_until = 0.0
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            if time.monotonic() < self._open_until:
                return "open"
            return "half-open" if self._open_until else "closed"

    def call(self, fn, *args, **kwargs):
        with self._lock:
            now = time.monotonic()
            if now < self._open_until:
                raise CircuitOpen(
                    f"{self.name or fn.__name__}: circuit open "
                    f"({self._open_until - now:.1f}s left)")
            if self._open_until:  # cooldown elapsed: admit ONE probe
                if self._probing:
                    raise CircuitOpen(f"{self.name}: probe in flight")
                self._probing = True
        done = False
        try:
            result = fn(*args, **kwargs)
            done = True
        except Exception:
            self._record_failure()
            done = True
            raise
        finally:
            if not done:  # BaseException (KeyboardInterrupt, ...) escaped:
                with self._lock:  # the probe slot must not wedge shut
                    self._probing = False
        with self._lock:
            self._fail_times.clear()
            self._open_until = 0.0
            self._probing = False
        return result

    def _record_failure(self) -> None:
        with self._lock:
            now = time.monotonic()
            self._probing = False
            if self._open_until:  # failed probe: straight back to open
                self._open_until = now + self.cooldown
                return
            self._fail_times = [t for t in self._fail_times
                                if now - t < self.window]
            self._fail_times.append(now)
            if len(self._fail_times) >= self.failures:
                self._open_until = now + self.cooldown
