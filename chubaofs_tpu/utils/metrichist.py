"""Metric history — a bounded in-process ring of registry snapshots.

`cfs-stat` can diff two hand-timed scrapes, but nothing in a daemon
remembers what its counters looked like a minute ago — so every dashboard
re-derives deltas client-side and a p99 regression that happened before the
operator attached is simply gone. This module keeps the short-term memory:
a deque of periodic `exporter.render_all()` snapshots (parsed back into the
exact `name{labels} -> value` keys a scraper sees, so history keys and
scrape keys can never drift), plus server-side `rate()` over adjacent
snapshots — monotonic families only, with counter-reset clamping — served
by the `/metrics/history` side-door rpc/server.py mounts next to /metrics.

Discipline (mirrors utils/profiler.py and the lock sanitizer):

  * **Disarmed (CFS_METRIC_HIST_S unset): zero overhead.** No recorder
    thread, nothing snapshotted, `activate_from_env()` touches nothing.
  * **Armed:** one `cfs-methist` thread records every CFS_METRIC_HIST_S
    seconds into a CFS_METRIC_HIST_LEN-bounded ring (default 240 — an hour
    at 15 s).
  * Either way `record()` works on demand: the SLO evaluator (utils/slo.py)
    snapshots per /health poll when the recorder isn't armed, so health is
    poll-driven history rather than a second bespoke pipeline.

The exposition-key helpers at the bottom (parse_key / histogram deltas /
bucket quantiles) are shared by utils/slo.py and tools/cfstop.py — one
implementation of "p99 from bucket deltas", so the health plane and the
dashboard can never disagree about what a latency window means.
"""

from __future__ import annotations

import collections
import os
import re
import threading
import time

from chubaofs_tpu.utils.locks import SanitizedLock

_ENV_PERIOD = "CFS_METRIC_HIST_S"
_ENV_LEN = "CFS_METRIC_HIST_LEN"
DEFAULT_LEN = 240


def env_period() -> float:
    """Armed snapshot period, 0.0 when disarmed/malformed (a typo'd env var
    must not kill daemon boot)."""
    try:
        p = float(os.environ.get(_ENV_PERIOD, "") or 0.0)
    except ValueError:
        return 0.0
    return p if p > 0.0 else 0.0


def enabled() -> bool:
    return env_period() > 0.0


def _env_len() -> int:
    try:
        n = int(os.environ.get(_ENV_LEN, "") or DEFAULT_LEN)
    except ValueError:
        return DEFAULT_LEN
    return max(2, n)


class MetricHistory:
    """The ring. Snapshots are dicts: ts (wall, display), mono (monotonic —
    every rate/window delta uses THIS, never the jumpable wall clock),
    metrics (key -> value), types (family -> kind, for monotonicity)."""

    def __init__(self, maxlen: int | None = None, period_s: float = 0.0):
        self.period_s = float(period_s)
        self._ring: collections.deque = collections.deque(
            maxlen=maxlen or _env_len())
        self._lock = SanitizedLock(name="metrichist.ring")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def armed(self) -> bool:
        return self._thread is not None

    # -- ingest ----------------------------------------------------------------

    def record(self) -> dict:
        """Snapshot the whole process registry set now; returns the record.
        Render+parse round-trips through the text exposition on purpose:
        the history's keys are BY CONSTRUCTION the keys a scraper sees."""
        from chubaofs_tpu.tools.cfsstat import parse_metrics, parse_types
        from chubaofs_tpu.utils.exporter import render_all

        text = render_all()
        snap = {"ts": time.time(), "mono": time.monotonic(),
                "metrics": parse_metrics(text), "types": parse_types(text)}
        with self._lock:
            self._ring.append(snap)
        return snap

    def start(self) -> "MetricHistory":
        """Start the periodic recorder (idempotent; restartable after
        stop() — a stale stop flag would spawn a thread that exits on its
        first wait while `armed` still read True, silently freezing the
        feed /health trusts)."""
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="cfs-methist")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.period_s or 15.0):
            try:
                self.record()
            except Exception:
                pass  # one bad render must not kill the recorder

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # -- queries ---------------------------------------------------------------

    def snapshots(self, n: int = 0) -> list[dict]:
        """The newest n snapshots (0 = all), oldest first."""
        with self._lock:
            snaps = list(self._ring)
        return snaps[-n:] if n > 0 else snaps

    def query(self, n: int = 30, flt: str = "", rate: bool = False) -> dict:
        """The /metrics/history response shape: snapshots (optionally
        name-filtered) and, with rate=True, per-adjacent-pair rates."""
        snaps = self.snapshots(n)

        def keep(metrics: dict) -> dict:
            if not flt:
                return metrics
            return {k: v for k, v in metrics.items() if flt in k}

        out = {
            "period_s": self.period_s,
            "count": len(snaps),
            "snapshots": [{"ts": s["ts"], "mono": s["mono"],
                           "metrics": keep(s["metrics"])} for s in snaps],
        }
        if rate:
            out["rates"] = [
                {"ts": r["ts"], "interval_s": r["interval_s"],
                 "rates": keep(r["rates"])} for r in rates(snaps)]
        return out


def rates(snaps: list[dict]) -> list[dict]:
    """Server-side rate(): per adjacent snapshot pair, per-second deltas of
    every MONOTONIC series (counters + histogram _bucket/_count/_sum) present
    in both. A negative delta means the daemon restarted between snapshots —
    the counter restarted from zero, so the whole post-restart value IS the
    delta (clamping, the same contract as cfs-stat's restart tag). Gauges
    are excluded: their current value is the signal, not their derivative."""
    out = []
    for prev, cur in zip(snaps, snaps[1:]):
        dt = cur["mono"] - prev["mono"]
        if dt <= 0:
            continue
        types = cur.get("types") or prev.get("types") or {}
        rr: dict[str, float] = {}
        pm = prev["metrics"]
        for key, v in cur["metrics"].items():
            if not is_monotonic(key, types) or key not in pm:
                continue
            d = v - pm[key]
            if d < 0:
                d = v  # restart: the series restarted from zero
            rr[key] = round(d / dt, 6)
        out.append({"ts": cur["ts"], "interval_s": round(dt, 6), "rates": rr})
    return out


# -- process-wide default ------------------------------------------------------

_default: MetricHistory | None = None
_lock = threading.Lock()


def default_history() -> MetricHistory:
    """The process history ring, created on first use (recorder NOT started
    — start() / activate_from_env() does that)."""
    global _default
    with _lock:
        if _default is None:
            _default = MetricHistory(period_s=env_period())
        return _default


def activate_from_env() -> MetricHistory | None:
    """Arm the periodic recorder iff CFS_METRIC_HIST_S asks for it — the
    daemon-boot hook. Unset env = return the existing object (maybe None)
    having started nothing: the zero-overhead gate."""
    if not enabled():
        return _default
    return default_history().start()


def deactivate() -> None:
    """Stop + forget the process ring (test isolation)."""
    global _default
    with _lock:
        h, _default = _default, None
    if h is not None:
        h.stop()


# -- exposition-key helpers (shared by slo.py and cfs-top) ---------------------

_LABELS = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_key(key: str) -> tuple[str, dict[str, str]]:
    """`name{a="x",b="y"}` -> (name, {a: x, b: y}); unescapes label values."""
    name, brace, rest = key.partition("{")
    if not brace:
        return key, {}
    labels = {m.group(1): m.group(2).replace('\\"', '"')
              .replace("\\n", "\n").replace("\\\\", "\\")
              for m in _LABELS.finditer(rest)}
    return name, labels


def family_sum(metrics: dict[str, float], family: str,
               label_in: tuple = ()) -> float:
    """Sum one family's value across its label sets (exact name match) —
    the shared flat-series aggregator slo.py and cfs-top both use, so the
    health plane and the dashboard can never disagree on what a counter
    family's total means. `label_in` = (label_key, (allowed values...))
    restricts the sum to matching series — the per-tenant SLO slice."""
    if not label_in:
        return sum(v for k, v in metrics.items() if parse_key(k)[0] == family)
    lk, allowed = label_in
    total = 0.0
    for k, v in metrics.items():
        name, labels = parse_key(k)
        if name == family and labels.get(lk) in allowed:
            total += v
    return total


def family_of(key: str) -> tuple[str, str]:
    """Series key -> (family, suffix): histogram children map back to their
    family name (`x_bucket`/`x_sum`/`x_count` -> `x`), everything else is
    its own family with no suffix."""
    name, _ = parse_key(key)
    for sfx in ("_bucket", "_sum", "_count"):
        if name.endswith(sfx):
            return name[: -len(sfx)], sfx
    return name, ""


def is_monotonic(key: str, types: dict[str, str]) -> bool:
    """Does this series only ever go up (modulo restarts)? Counters and
    histogram children are; gauges (incl. the `_max` companions) are not.
    Unknown families are NOT monotonic — never clamp what we can't type."""
    fam, sfx = family_of(key)
    if sfx:  # _bucket/_sum/_count of a histogram family
        return types.get(fam) == "histogram"
    return types.get(fam) == "counter"


def hist_totals(metrics: dict[str, float],
                family: str) -> tuple[dict[float, float], float]:
    """Aggregate one histogram family across its label sets: cumulative
    bucket totals by `le` (finite buckets only) and the total count."""
    buckets: dict[float, float] = {}
    count = 0.0
    bucket_name, count_name = family + "_bucket", family + "_count"
    for key, v in metrics.items():
        name, labels = parse_key(key)
        if name == bucket_name:
            le = labels.get("le", "")
            if le and le != "+Inf":
                try:
                    buckets[float(le)] = buckets.get(float(le), 0.0) + v
                except ValueError:
                    continue
        elif name == count_name:
            count += v
    return buckets, count


def hist_delta(m0: dict[str, float], m1: dict[str, float],
               family: str) -> tuple[dict[float, float], float]:
    """Window delta of a histogram family (m0 older, m1 newer). A count
    that went DOWN means the daemon restarted inside the window — the
    post-restart totals ARE the window's delta (the same restart contract
    as rates() and cfs-stat's `(restart)` tag; clamping to zero instead
    would blank the latency/error SLOs for a whole slow window right when
    a restarting daemon most needs watching). m0 may be empty ({}): the
    delta is then the all-time totals."""
    b0, c0 = hist_totals(m0, family)
    b1, c1 = hist_totals(m1, family)
    if c1 < c0:
        return b1, c1
    db = {le: max(0.0, v - b0.get(le, 0.0)) for le, v in b1.items()}
    return db, c1 - c0


def hist_quantile(buckets: dict[float, float], count: float,
                  q: float) -> float | None:
    """Bucket-resolution quantile over CUMULATIVE bucket deltas: the upper
    bound of the bucket holding the q-th sample (exporter.Summary.quantile's
    math, applied to a window delta). None when the window saw no samples;
    samples beyond the last finite bucket report that bucket's bound (the
    floor of the true value — still enough to breach any threshold below
    it)."""
    if count <= 0 or not buckets:
        return None
    rank = q * count
    last = None
    for le in sorted(buckets):
        last = le
        if buckets[le] >= rank:
            return le
    return last
