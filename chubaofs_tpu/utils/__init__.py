"""Shared utilities: CRC framing, config, logging, byte helpers."""
