"""Block-framed CRC32 codec for chunk datafiles.

Equivalent of reference blobstore/common/crc32block: payloads are framed as
fixed-size blocks, each followed by a 4-byte CRC32 of that block, so torn writes
and bit rot are detected at read time block-by-block (a full-payload CRC can't
say *where* corruption happened and forces whole-shard reads).

Frame layout for payload P split into blocks of BLOCK_SIZE:
    [block0][crc32(block0)][block1][crc32(block1)]...[blockN (short)][crc32]
"""

from __future__ import annotations

import struct
import zlib

BLOCK_SIZE = 64 * 1024
_CRC = struct.Struct("<I")


class CrcError(ValueError):
    """A framed block failed its CRC check."""


def encoded_len(payload_len: int, block_size: int = BLOCK_SIZE) -> int:
    if payload_len == 0:
        return 0
    nblocks = -(-payload_len // block_size)
    return payload_len + 4 * nblocks


def decoded_len(framed_len: int, block_size: int = BLOCK_SIZE) -> int:
    if framed_len == 0:
        return 0
    full = framed_len // (block_size + 4)
    rem = framed_len - full * (block_size + 4)
    if rem == 0:
        return full * block_size
    if rem <= 4:
        raise CrcError(f"framed length {framed_len} leaves a truncated block")
    return full * block_size + (rem - 4)


def encode(payload: bytes | bytearray | memoryview, block_size: int = BLOCK_SIZE) -> bytes:
    view = memoryview(payload)
    out = bytearray(encoded_len(len(view), block_size))
    pos = 0
    for off in range(0, len(view), block_size):
        block = view[off : off + block_size]
        out[pos : pos + len(block)] = block
        pos += len(block)
        _CRC.pack_into(out, pos, zlib.crc32(block))
        pos += 4
    return bytes(out)


def decode(framed: bytes | bytearray | memoryview, block_size: int = BLOCK_SIZE) -> bytes:
    view = memoryview(framed)
    out = bytearray(decoded_len(len(view), block_size))
    pos = 0
    stride = block_size + 4
    for off in range(0, len(view), stride):
        frame = view[off : off + stride]
        block, crc_raw = frame[:-4], frame[-4:]
        if len(crc_raw) != 4:
            raise CrcError("truncated frame")
        (want,) = _CRC.unpack(crc_raw)
        if zlib.crc32(block) != want:
            raise CrcError(f"crc mismatch in block at framed offset {off}")
        out[pos : pos + len(block)] = block
        pos += len(block)
    return bytes(out)


def block_range(offset: int, size: int, block_size: int = BLOCK_SIZE) -> tuple[int, int]:
    """Map a payload byte range to the framed byte range covering it.

    Returns (framed_start, framed_end) such that decoding that slice yields the
    blocks containing [offset, offset+size)."""
    first = offset // block_size
    last = -(-(offset + size) // block_size) if size else first
    stride = block_size + 4
    return first * stride, last * stride
