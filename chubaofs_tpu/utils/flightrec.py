"""Incident flight recorder (ISSUE 18): alert-triggered capture bundles.

When an alert transitions to firing — or an operator/harness asks
explicitly — freeze the pre-incident window this process already holds in
its observability rings into one on-disk *bundle* directory: the last K
metric-history snapshots, the recent event ring, recent traces + slowops,
the autopilot decision log, a bounded on-demand profile (or the continuous profiler's aggregate when one
is armed), the lock-sanitizer report, the CFS_* knob dump, and boot/build
info. The rings rotate in minutes; the bundle is the evidence that
survives to the postmortem.

Zero-overhead-when-disarmed, same discipline as the profiler: with
`CFS_FLIGHT` unset `activate_from_env()` touches nothing — no thread (the
recorder NEVER has one: captures run on the alert-eval thread or the HTTP
handler that asked), no alert hook, no directory, no hot-path cost.
`/debug/bundle` answers 400 with the arming hint. Explicit `capture()`
still works disarmed (the `/debug/prof?seconds=N` on-demand contract) —
the chaos-soak failure hook relies on that.

Flap safety: captures dedup by alert fingerprint inside a cooldown window
(`CFS_FLIGHT_COOLDOWN_S`) — a flapping rule returns the bundle it already
wrote instead of disk-storming — and the bundle root is size-budgeted
(`CFS_FLIGHT_MB`): oldest bundles are evicted first, never the one just
written.

Knobs: `CFS_FLIGHT` (truthy arms the alert hook), `CFS_FLIGHT_DIR`
(default a per-process tmpdir), `CFS_FLIGHT_MB` (bundle-root budget,
default 64), `CFS_FLIGHT_COOLDOWN_S` (per-fingerprint dedup window,
default 60).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
import threading
import time

from chubaofs_tpu.utils.config import env_float

DEFAULT_MB = 64
DEFAULT_COOLDOWN_S = 60.0
SNAPSHOT_K = 32         # metric-history snapshots frozen per bundle
EVENTS_N = 400          # event-ring window frozen per bundle
TRACE_RECORDS_N = 400   # span records frozen per bundle
SLOWOPS_N = 200
PROFILE_SECONDS = 0.25  # on-demand profile bound when none is armed

SECTIONS = ("meta", "alert", "metrics", "events", "traces", "slowops",
            "autopilot", "profile", "locks", "config")

_FALSEY = ("", "0", "false", "no")


def enabled() -> bool:
    return os.environ.get("CFS_FLIGHT", "").strip().lower() not in _FALSEY


def flight_dir() -> str:
    return os.environ.get("CFS_FLIGHT_DIR") or os.path.join(
        tempfile.gettempdir(), f"cfs-flight-{os.getpid()}")


def budget_bytes() -> int:
    # fractional MB is legal (hygiene tests pin tiny budgets); floor 4 KiB
    # so a typo'd 0 can't evict every bundle but the newest
    return max(4096, int(env_float("CFS_FLIGHT_MB", DEFAULT_MB)
                         * 1024 * 1024))


def cooldown_s() -> float:
    return max(0.0, env_float("CFS_FLIGHT_COOLDOWN_S", DEFAULT_COOLDOWN_S))


def _slug(s: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]+", "_", s).strip("_")[:80] or "incident"


# -- section gathers -----------------------------------------------------------
#
# Each pulls from a ring that already exists; every one is individually
# fault-isolated in capture() — a broken section degrades to an "error"
# stanza in the bundle, never a lost incident.


def _gather_meta(trigger: str, fp: str, ts: float) -> dict:
    import chubaofs_tpu
    from chubaofs_tpu.utils import events

    j = events.default_journal()
    return {"trigger": trigger, "fingerprint": fp, "ts": ts,
            "role": j.role, "addr": j.addr, "pid": os.getpid(),
            "version": getattr(chubaofs_tpu, "__version__", "?"),
            "boot_ts": events.BOOT_TS}


def _gather_metrics() -> dict:
    from chubaofs_tpu.utils import metrichist

    hist = metrichist.default_history()
    snaps = hist.snapshots(SNAPSHOT_K)
    if not snaps:
        # history disarmed or cold: one fresh snapshot beats an empty
        # section — cfs-doctor still gets the at-incident counter state
        snaps = [hist.record()]
    return {"snapshots": snaps}


def _gather_events() -> dict:
    from chubaofs_tpu.utils import events

    evs, cursor = events.recent_page(EVENTS_N)
    return {"events": evs, "cursor": cursor}


def _gather_traces() -> dict:
    from chubaofs_tpu.utils import tracesink

    sink = tracesink.default_sink()
    return {"records": sink.recent_records(TRACE_RECORDS_N),
            "traces": sink.recent_traces(50)}


def _gather_slowops() -> dict:
    from chubaofs_tpu.utils import auditlog

    return {"slowops": auditlog.recent_slowops(SLOWOPS_N)}


def _gather_autopilot() -> dict:
    # the controller's decision ring + arming state, frozen at incident
    # time — cfs-doctor names the actions the autopilot took (or refused)
    # inside the window. Disarmed processes freeze the stub status.
    from chubaofs_tpu.autopilot import controller

    return controller.autopilot_status()


def _gather_profile(profile_s: float) -> dict:
    from chubaofs_tpu.utils import profiler

    cont = profiler.active()
    if cont is not None:
        out = cont.profile.to_dict()
        out["source"] = "continuous"
        return out
    out = profiler.capture(profile_s).to_dict()
    out["source"] = "capture"
    return out


def _gather_locks() -> dict:
    from chubaofs_tpu.utils import locks

    return locks.report()


def _gather_config() -> dict:
    return {"env": {k: v for k, v in sorted(os.environ.items())
                    if k.startswith("CFS_")}}


# -- the recorder --------------------------------------------------------------


class FlightRecorder:
    """Per-process bundle writer. Threadless by design: `capture()` runs on
    whoever triggered it, serialized by `_lock` so a burst of distinct
    alerts can't interleave half-written bundles."""

    def __init__(self, root: str | None = None):
        self.root = root or flight_dir()
        self._lock = threading.Lock()
        self._recent: dict[str, tuple[float, str]] = {}  # fp -> (mono, path)
        self._seq = 0

    # -- capture ---------------------------------------------------------------

    def capture(self, trigger: str = "manual", fingerprint: str = "",
                alert: dict | None = None,
                profile_s: float = PROFILE_SECONDS) -> dict:
        """Freeze the window into a new bundle dir; returns its manifest.
        Same fingerprint inside the cooldown returns the EXISTING bundle's
        manifest with deduped=True and writes nothing."""
        from chubaofs_tpu.utils import events
        from chubaofs_tpu.utils.exporter import registry

        with self._lock:
            now_mono = time.monotonic()
            if fingerprint:
                hit = self._recent.get(fingerprint)
                if hit is not None and now_mono - hit[0] < cooldown_s() \
                        and os.path.isdir(hit[1]):
                    registry("flightrec").counter(
                        "captures", {"outcome": "deduped"}).add()
                    man = _read_json(os.path.join(hit[1], "manifest.json"))
                    man = man or {"bundle": hit[1]}
                    man["deduped"] = True
                    return man

            ts = time.time()
            self._seq += 1
            # pid in the name: daemons sharing one CFS_FLIGHT_DIR (the
            # harness arms a whole ProcCluster at once) must never collide
            name = (f"{_slug(fingerprint or trigger)}-{int(ts)}"
                    f"-{os.getpid()}-{self._seq:03d}")
            path = os.path.join(self.root, name)
            os.makedirs(path, exist_ok=True)

            gathers = {
                "meta": lambda: _gather_meta(trigger, fingerprint, ts),
                "alert": lambda: dict(alert or {}),
                "metrics": _gather_metrics,
                "events": _gather_events,
                "traces": _gather_traces,
                "slowops": _gather_slowops,
                "autopilot": _gather_autopilot,
                "profile": lambda: _gather_profile(profile_s),
                "locks": _gather_locks,
                "config": _gather_config,
            }
            sections: dict[str, str] = {}
            for sec in SECTIONS:
                try:
                    payload = gathers[sec]()
                    sections[sec] = "ok"
                except Exception as e:  # degrade, never lose the incident
                    payload = {"error": f"{type(e).__name__}: {e}"}
                    sections[sec] = "error"
                _write_json(os.path.join(path, f"{sec}.json"), payload)

            manifest = {"bundle": path, "name": name, "trigger": trigger,
                        "fingerprint": fingerprint, "ts": ts,
                        "sections": sections, "deduped": False,
                        "bytes": _dir_bytes(path)}
            _write_json(os.path.join(path, "manifest.json"), manifest)
            if fingerprint:
                self._recent[fingerprint] = (now_mono, path)
            self._evict_locked(keep=path)
            registry("flightrec").counter(
                "captures", {"outcome": "written"}).add()

        events.emit("incident_capture", events.SEV_WARNING,
                    entity=fingerprint or trigger,
                    detail={"bundle": path, "trigger": trigger,
                            "sections": sections})
        return manifest

    # -- hygiene ---------------------------------------------------------------

    def _evict_locked(self, keep: str) -> None:
        budget = budget_bytes()
        bundles = self.list_bundles()
        total = sum(b["bytes"] for b in bundles)
        for b in bundles:  # oldest first
            if total <= budget:
                break
            if os.path.abspath(b["path"]) == os.path.abspath(keep):
                continue  # never the bundle this capture just wrote
            shutil.rmtree(b["path"], ignore_errors=True)
            total -= b["bytes"]

    def list_bundles(self) -> list[dict]:
        """Bundle summaries under the root, oldest first."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for name in names:
            path = os.path.join(self.root, name)
            if not os.path.isdir(path):
                continue
            man = _read_json(os.path.join(path, "manifest.json")) or {}
            out.append({"name": name, "path": path,
                        "ts": man.get("ts", 0.0),
                        "trigger": man.get("trigger", "?"),
                        "fingerprint": man.get("fingerprint", ""),
                        "sections": man.get("sections", {}),
                        "bytes": _dir_bytes(path)})
        out.sort(key=lambda b: (b["ts"], b["name"]))
        return out


# -- bundle IO (shared with /debug/bundle, the console collector, cfs-doctor) --


def _write_json(path: str, payload: dict) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, separators=(",", ":"), default=str)


def _read_json(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _dir_bytes(path: str) -> int:
    total = 0
    for base, _dirs, files in os.walk(path):
        for fn in files:
            try:
                total += os.path.getsize(os.path.join(base, fn))
            except OSError:
                pass
    return total


def bundle_payload(path: str) -> dict:
    """A bundle dir loaded into one dict: {section: payload}. Missing or
    corrupt section files surface as {"error": ...} stanzas — the collector
    and cfs-doctor render what survived."""
    out: dict = {}
    for sec in SECTIONS + ("manifest",):
        p = os.path.join(path, f"{sec}.json")
        if not os.path.exists(p):
            continue
        out[sec] = _read_json(p) or {"error": f"unreadable {sec}.json"}
    return out


def write_payload(path: str, payload: dict) -> None:
    """Inverse of bundle_payload: materialize a fetched payload as a bundle
    dir (the console collector writing one target's sections)."""
    os.makedirs(path, exist_ok=True)
    for sec, body in payload.items():
        if isinstance(body, dict):
            _write_json(os.path.join(path, f"{sec}.json"), body)


# -- process singleton + arming ------------------------------------------------

_default: FlightRecorder | None = None
_mod_lock = threading.Lock()
_hooked = False


def default_recorder() -> FlightRecorder:
    global _default
    with _mod_lock:
        if _default is None:
            _default = FlightRecorder()
        return _default


def capture(trigger: str = "manual", fingerprint: str = "",
            alert: dict | None = None,
            profile_s: float = PROFILE_SECONDS) -> dict:
    """Module-level capture on the process recorder. Works even disarmed —
    explicit triggers (soak failure hooks, ?collect=1 side-doors) are
    on-demand, like /debug/prof?seconds=N."""
    return default_recorder().capture(trigger=trigger,
                                      fingerprint=fingerprint, alert=alert,
                                      profile_s=profile_s)


def _on_alert_firing(fp: str, inst_report: dict) -> None:
    capture(trigger="alert", fingerprint=fp, alert=inst_report)


def activate_from_env() -> FlightRecorder | None:
    """Arm the alert-firing hook iff CFS_FLIGHT asks for it — the daemon-
    boot hook (rpc/server.py calls it next to the other activate_from_env
    quartet). Unset env = return None having touched nothing: no recorder
    object, no hook, no directory."""
    global _hooked
    if not enabled():
        return None
    from chubaofs_tpu.utils import alerts

    with _mod_lock:
        if not _hooked:
            alerts.on_firing(_on_alert_firing)
            _hooked = True
    return default_recorder()


def deactivate() -> None:
    """Unhook + forget the process recorder (test isolation). Bundles
    already on disk are left alone — they are the evidence."""
    global _default, _hooked
    from chubaofs_tpu.utils import alerts

    with _mod_lock:
        if _hooked:
            alerts.remove_firing_hook(_on_alert_firing)
            _hooked = False
        _default = None
