"""Audit log — append-only, size-rotated op log shared by client and gateways.

Reference counterpart: util/auditlog/auditlog.go:74-161 (client fs-op audit —
timestamp, client addr, volume, op, path, error, latency, ino — written to a
rotating file set with a shrink-on-total-size policy) and the blobstore HTTP
auditlog middleware (common/rpc/auditlog). One implementation serves both: a
`AuditLog` with `log_fs_op` / `log_http` formatters over the same rotor.

Slow-op audit: any op slower than the `CFS_SLOWOP_MS` threshold emits one
STRUCTURED record — module, op, trace id, the span's whole track log, latency
— through the same rotor discipline, so a single slow FUSE create or access
PUT explains itself hop by hop (the blobstore access gateway's slow-request
track-log line, generalized to every entry point)."""

from __future__ import annotations

import json
import os
import tempfile
import time

from chubaofs_tpu.utils.locks import SanitizedLock


class RotatingFile:
    """Size-rotated append file ring: name.log, name.log.1 .. name.log.N.

    The one rotor shared by the fs audit log, the blobstore recordlog, and any
    other append-only trail (auditlog.go's total-size shrink policy, expressed
    as a bounded file ring). Thread-safe; lines are written whole."""

    def __init__(self, logdir: str, prefix: str, max_bytes: int, max_files: int):
        self.dir = logdir
        self.prefix = prefix
        self.max_bytes = max_bytes
        self.max_files = max_files
        # bounded name set: one rotor per trail kind (audit/slowop/traces/...)
        self._lock = SanitizedLock(name=f"auditlog.{prefix}")
        os.makedirs(logdir, exist_ok=True)
        self._fh = None
        self._open_locked()

    def path(self, n: int = 0) -> str:
        return os.path.join(self.dir, f"{self.prefix}.log" + (f".{n}" if n else ""))

    def _open_locked(self):
        self._fh = open(self.path(), "a", encoding="utf-8")
        self._size = self._fh.tell()

    def _rotate_locked(self):
        self._fh.close()
        for n in range(self.max_files - 1, 0, -1):
            src = self.path(n - 1) if n > 1 else self.path()
            if os.path.exists(src):
                os.replace(src, self.path(n))
        self._open_locked()

    def write_line(self, line: str):
        with self._lock:
            if self._fh is None:
                return
            self._fh.write(line + "\n")
            self._fh.flush()
            self._size += len(line) + 1
            if self._size >= self.max_bytes:
                self._rotate_locked()

    def read_lines(self) -> list[str]:
        """Every retained line, oldest first, across rotations. Reads race
        the writer's rotation (the trace/slowop HTTP side-doors read a LIVE
        rotor): a file that vanishes between listing and open — os.replace'd
        up the ring — is skipped, never a request-killing error."""
        out: list[str] = []
        for n in range(self.max_files, -1, -1):
            p = self.path(n)
            try:
                with open(p, encoding="utf-8") as f:
                    out.extend(line.rstrip("\n") for line in f if line.strip())
            except OSError:
                continue
        return out

    def close(self):
        with self._lock:
            if self._fh:
                self._fh.close()
                self._fh = None


class AuditLog:
    def __init__(self, logdir: str, prefix: str = "audit",
                 max_bytes: int = 4 << 20, max_files: int = 8):
        self._rotor = RotatingFile(logdir, prefix, max_bytes, max_files)

    def log_fs_op(self, client: str, volume: str, op: str, path: str,
                  err: str = "", latency_us: int = 0, ino: int = 0):
        ts = time.strftime("%Y-%m-%d %H:%M:%S")
        self._rotor.write_line(",".join([ts, client, volume, op, path,
                                         err or "nil", str(latency_us), str(ino)]))

    def log_http(self, method: str, path: str, status: int, latency_us: int,
                 remote: str = "-", req_bytes: int = 0, resp_bytes: int = 0):
        ts = time.strftime("%Y-%m-%d %H:%M:%S")
        self._rotor.write_line(",".join([ts, remote, method, path, str(status),
                                         str(req_bytes), str(resp_bytes),
                                         str(latency_us)]))

    def close(self):
        self._rotor.close()


# -- slow-op audit (CFS_SLOWOP_MS) ---------------------------------------------


class SlowOpLog:
    """Structured slow-op trail: one JSON line per over-threshold op, with
    the op's trace id and track log so the latency is attributable hop by
    hop. Threshold in milliseconds; <= 0 disables (the default)."""

    def __init__(self, logdir: str, threshold_ms: float = 0.0,
                 max_bytes: int = 4 << 20, max_files: int = 4):
        self.threshold_ms = threshold_ms
        self._rotor = RotatingFile(logdir, "slowop", max_bytes, max_files)

    def maybe_log(self, module: str, op: str, latency_s: float,
                  span=None, err: str = "") -> bool:
        """Record the op if it crossed the threshold; True when logged."""
        ms = latency_s * 1e3
        if self.threshold_ms <= 0 or ms < self.threshold_ms:
            return False
        rec = {"ts": time.strftime("%Y-%m-%d %H:%M:%S"),
               "module": module, "op": op, "latency_ms": round(ms, 3)}
        if span is not None:
            rec["trace_id"] = span.trace_id
            rec["track"] = span.track_log_string()
        if err:
            rec["err"] = err
        self._rotor.write_line(json.dumps(rec))
        return True

    def records(self) -> list[dict]:
        out = []
        for line in self._rotor.read_lines():
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
        return out

    def close(self):
        self._rotor.close()


_slowop: SlowOpLog | None = None
_slowop_lock = SanitizedLock(name="auditlog.slowop.default")


_env_ms_cache: float | None = None


def _env_threshold_ms() -> float:
    """CFS_SLOWOP_MS, parsed ONCE — the disabled fast path in every packet/
    fs-op dispatch must not pay an environ lookup per call. Overrides after
    startup go through configure_slowop()."""
    global _env_ms_cache
    if _env_ms_cache is None:
        try:
            _env_ms_cache = float(os.environ.get("CFS_SLOWOP_MS", "0") or 0)
        except ValueError:
            _env_ms_cache = 0.0
    return _env_ms_cache


def slowop_log() -> SlowOpLog:
    """The process-wide slow-op log. Directory from `CFS_SLOWOP_DIR` (default
    a per-process dir under the system tmpdir), threshold from
    `CFS_SLOWOP_MS` — both re-read on first use; tests reconfigure via
    configure_slowop()."""
    global _slowop
    with _slowop_lock:
        if _slowop is None:
            logdir = os.environ.get("CFS_SLOWOP_DIR") or os.path.join(
                tempfile.gettempdir(), f"cfs-slowop-{os.getpid()}")
            _slowop = SlowOpLog(logdir, threshold_ms=_env_threshold_ms())
        return _slowop


def configure_slowop(logdir: str | None = None,
                     threshold_ms: float | None = None) -> SlowOpLog:
    """(Re)bind the process slow-op log — daemons point it at their log dir,
    tests at a tmpdir with a tiny threshold."""
    global _slowop
    with _slowop_lock:
        if _slowop is not None and logdir is not None:
            _slowop.close()
            _slowop = None
        if _slowop is None:
            _slowop = SlowOpLog(
                logdir or os.environ.get("CFS_SLOWOP_DIR") or os.path.join(
                    tempfile.gettempdir(), f"cfs-slowop-{os.getpid()}"),
                threshold_ms=(_env_threshold_ms() if threshold_ms is None
                              else threshold_ms))
        elif threshold_ms is not None:
            _slowop.threshold_ms = threshold_ms
        return _slowop


def recent_slowops(n: int = 100) -> list[dict]:
    """The newest n slow-op records — the one accessor behind every HTTP
    face of the audit (RPCServer /slowops, the master's /api/slowops
    alias), so the windows can't drift apart. n<=0 is an empty window,
    never the [-0:] whole-log slice."""
    if n <= 0:
        return []
    return slowop_log().records()[-n:]


def record_slow_op(module: str, op: str, latency_s: float, span=None,
                   err: str = "") -> bool:
    """Entry-point hook: cheap when disabled (one cached float compare, no
    files ever opened), one JSON line + a metrics counter when the op
    crossed CFS_SLOWOP_MS. NEVER raises — it runs in serve loops' finally
    blocks (FUSE dispatch, packet dispatch), where a full disk or an
    unwritable CFS_SLOWOP_DIR must degrade to lost audit lines, not to a
    dead mount."""
    try:
        if _slowop is None and _env_threshold_ms() <= 0:
            return False  # disabled and never configured: no rotor to create
        log = slowop_log()
        if log.threshold_ms <= 0:
            return False
        if not log.maybe_log(module, op, latency_s, span=span, err=err):
            return False
        from chubaofs_tpu.utils.exporter import registry

        registry("slowop").counter("slow_ops_total",
                                   {"module": module, "op": op}).add()
        if span is not None:
            # slow ops are always-on for the trace sink: the span behind
            # every slowop line persists whatever CFS_TRACE_SAMPLE says
            from chubaofs_tpu.utils import tracesink

            tracesink.force(span)
        return True
    except Exception:
        return False
