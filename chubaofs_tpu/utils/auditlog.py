"""Audit log — append-only, size-rotated op log shared by client and gateways.

Reference counterpart: util/auditlog/auditlog.go:74-161 (client fs-op audit —
timestamp, client addr, volume, op, path, error, latency, ino — written to a
rotating file set with a shrink-on-total-size policy) and the blobstore HTTP
auditlog middleware (common/rpc/auditlog). One implementation serves both: a
`AuditLog` with `log_fs_op` / `log_http` formatters over the same rotor.
"""

from __future__ import annotations

import os
import threading
import time


class RotatingFile:
    """Size-rotated append file ring: name.log, name.log.1 .. name.log.N.

    The one rotor shared by the fs audit log, the blobstore recordlog, and any
    other append-only trail (auditlog.go's total-size shrink policy, expressed
    as a bounded file ring). Thread-safe; lines are written whole."""

    def __init__(self, logdir: str, prefix: str, max_bytes: int, max_files: int):
        self.dir = logdir
        self.prefix = prefix
        self.max_bytes = max_bytes
        self.max_files = max_files
        self._lock = threading.Lock()
        os.makedirs(logdir, exist_ok=True)
        self._fh = None
        self._open()

    def path(self, n: int = 0) -> str:
        return os.path.join(self.dir, f"{self.prefix}.log" + (f".{n}" if n else ""))

    def _open(self):
        self._fh = open(self.path(), "a", encoding="utf-8")
        self._size = self._fh.tell()

    def _rotate_locked(self):
        self._fh.close()
        for n in range(self.max_files - 1, 0, -1):
            src = self.path(n - 1) if n > 1 else self.path()
            if os.path.exists(src):
                os.replace(src, self.path(n))
        self._open()

    def write_line(self, line: str):
        with self._lock:
            if self._fh is None:
                return
            self._fh.write(line + "\n")
            self._fh.flush()
            self._size += len(line) + 1
            if self._size >= self.max_bytes:
                self._rotate_locked()

    def read_lines(self) -> list[str]:
        """Every retained line, oldest first, across rotations."""
        out: list[str] = []
        for n in range(self.max_files, -1, -1):
            p = self.path(n)
            if os.path.exists(p):
                with open(p, encoding="utf-8") as f:
                    out.extend(line.rstrip("\n") for line in f if line.strip())
        return out

    def close(self):
        with self._lock:
            if self._fh:
                self._fh.close()
                self._fh = None


class AuditLog:
    def __init__(self, logdir: str, prefix: str = "audit",
                 max_bytes: int = 4 << 20, max_files: int = 8):
        self._rotor = RotatingFile(logdir, prefix, max_bytes, max_files)

    def log_fs_op(self, client: str, volume: str, op: str, path: str,
                  err: str = "", latency_us: int = 0, ino: int = 0):
        ts = time.strftime("%Y-%m-%d %H:%M:%S")
        self._rotor.write_line(",".join([ts, client, volume, op, path,
                                         err or "nil", str(latency_us), str(ino)]))

    def log_http(self, method: str, path: str, status: int, latency_us: int,
                 remote: str = "-", req_bytes: int = 0, resp_bytes: int = 0):
        ts = time.strftime("%Y-%m-%d %H:%M:%S")
        self._rotor.write_line(",".join([ts, remote, method, path, str(status),
                                         str(req_bytes), str(resp_bytes),
                                         str(latency_us)]))

    def close(self):
        self._rotor.close()
