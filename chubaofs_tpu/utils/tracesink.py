"""Trace sink — sampled, bounded, persisted span records per daemon.

The collection half of the observability plane: trace.py's spans carry the
timing tree, but until they land somewhere a trace lives only as the
ephemeral response-header track log. The sink persists finished spans as one
JSON SpanRecord line each through the same `utils/auditlog.RotatingFile`
rotor discipline as the slow-op audit — so the byte budget is configured,
enforced, and shared-nothing — and keeps a bounded in-memory index of recent
records for the `/traces` HTTP side-door (rpc/server.py mounts it next to
/metrics).

Sampling (`CFS_TRACE_SAMPLE`, a 0..1 fraction, default 0 = off) is decided
per TRACE by a deterministic hash of the trace id, so every daemon a request
crosses keeps or drops the same traces and the collector always sees whole
trees. Unsampled spans cost one float compare in the finish hook — no record
is built, nothing is written. Slow ops are ALWAYS persisted: the slow-op
audit (utils/auditlog.record_slow_op) forces the span into the sink
regardless of the sample rate, so the trace behind every slowop line is
fetchable by id.

`tools/cfstrace.py` (`cfs-trace`) reassembles the hop tree from these
records and runs the critical-path analyzer over them.
"""

from __future__ import annotations

import collections
import json
import os
import tempfile
import zlib

from chubaofs_tpu.utils.auditlog import RotatingFile
from chubaofs_tpu.utils.locks import SanitizedLock


class TraceSink:
    """Bounded span-record store: RotatingFile ring + recent-record deque."""

    def __init__(self, logdir: str, sample: float = 0.0,
                 max_bytes: int = 4 << 20, max_files: int = 4,
                 recent_max: int = 1024):
        self.sample = float(sample)
        self.max_bytes = max_bytes
        self.max_files = max_files
        self.dir = logdir
        self._rotor = RotatingFile(logdir, "traces", max_bytes, max_files)
        self._recent: collections.deque = collections.deque(maxlen=recent_max)
        self._lock = SanitizedLock(name="tracesink.recent")

    # -- ingest ----------------------------------------------------------------

    def sampled(self, trace_id: str) -> bool:
        """Deterministic per-trace decision: every process hashing the same
        trace id reaches the same verdict (no coordination, whole trees)."""
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        h = zlib.crc32(trace_id.encode()) & 0xFFFFFFFF
        return h / 4294967296.0 < self.sample

    def on_span_finish(self, span) -> bool:
        """The trace.set_finish_hook target. Unsampled spans return after a
        float compare — no record building, no IO (the bounded-overhead
        contract)."""
        if getattr(span, "_sink_force", False):
            return self._persist(span)
        if self.sample <= 0.0:
            return False
        if not self.sampled(span.trace_id):
            return False
        return self._persist(span)

    def force(self, span) -> bool:
        """Persist regardless of sampling (the slow-op path). A span still
        running is flagged instead — its finish hook persists the COMPLETE
        record (entry points audit inside their span, before finish())."""
        if span.finished_us is None:
            span._sink_force = True
            return False
        return self._persist(span)

    def _persist(self, span) -> bool:
        if getattr(span, "_sink_recorded", False):
            return False  # force-after-finish meets the finish hook: once
        span._sink_recorded = True
        rec = span.to_record()
        with self._lock:
            self._recent.append(rec)
        self._rotor.write_line(json.dumps(rec, default=str))
        return True

    # -- queries ---------------------------------------------------------------

    def records(self, trace_id: str) -> list[dict]:
        """Every persisted span of one trace, oldest-start first — the rotor
        ring is scanned too, so a trace survives the recent-deque window (and
        a restart) as long as its lines haven't rotated out."""
        out: dict[str, dict] = {}
        for line in self._rotor.read_lines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("trace_id") == trace_id and rec.get("span_id"):
                out[rec["span_id"]] = rec
        with self._lock:
            recent = list(self._recent)
        for rec in recent:
            if rec.get("trace_id") == trace_id and rec.get("span_id"):
                out[rec["span_id"]] = rec
        return sorted(out.values(), key=lambda r: r.get("start", 0.0))

    def recent_records(self, n: int = 200) -> list[dict]:
        """The newest n span records (newest last) — the aggregation feed
        for per-hop p50/p99 (`cfs-trace --top`). n<=0 is an empty window."""
        if n <= 0:
            return []
        with self._lock:
            recent = list(self._recent)
        return recent[-n:]

    def recent_traces(self, n: int = 50) -> list[dict]:
        """Per-trace summaries of the recent window, newest last."""
        groups: dict[str, list[dict]] = {}
        for rec in self.recent_records(len(self._recent) or 1):
            groups.setdefault(rec["trace_id"], []).append(rec)
        out = []
        for tid, recs in groups.items():
            root = max(recs, key=lambda r: r.get("dur_us", 0))
            out.append({"trace_id": tid, "root_op": root.get("op", "?"),
                        "dur_us": root.get("dur_us", 0),
                        "start": root.get("start", 0.0), "spans": len(recs)})
        out.sort(key=lambda t: t["start"])
        return out[-n:]

    def close(self):
        self._rotor.close()


# -- process-wide default ------------------------------------------------------

_default: TraceSink | None = None
_lock = SanitizedLock(name="tracesink.default")


def _env_sample() -> float:
    try:
        return float(os.environ.get("CFS_TRACE_SAMPLE", "0") or 0)
    except ValueError:
        return 0.0


def _env_int(name: str, default: int) -> int:
    """Malformed byte/file budgets degrade to defaults — this parse runs
    inside RPCServer construction (activate_from_env), where a typo'd env
    var must not kill daemon boot. Canonical impl: utils.config.env_int."""
    from chubaofs_tpu.utils.config import env_int

    return env_int(name, default)


def default_sink() -> TraceSink:
    """The process trace sink, created on first use (like the slow-op log):
    directory from CFS_TRACE_DIR (default per-process tmpdir), sample rate
    from CFS_TRACE_SAMPLE, byte budget from CFS_TRACE_BYTES/CFS_TRACE_FILES.
    Creation installs the span-finish hook."""
    global _default
    with _lock:
        if _default is None:
            logdir = os.environ.get("CFS_TRACE_DIR") or os.path.join(
                tempfile.gettempdir(), f"cfs-traces-{os.getpid()}")
            _default = TraceSink(
                logdir, sample=_env_sample(),
                max_bytes=_env_int("CFS_TRACE_BYTES", 4 << 20),
                max_files=_env_int("CFS_TRACE_FILES", 4))
            from chubaofs_tpu.blobstore import trace

            trace.set_finish_hook(_default.on_span_finish)
        return _default


def configure(logdir: str | None = None, sample: float | None = None,
              max_bytes: int | None = None,
              max_files: int | None = None) -> TraceSink:
    """(Re)bind the process sink — daemons point it at their log dir, tests
    at a tmpdir with sample=1.0. Passing only `sample` retunes in place;
    a logdir or byte-budget change rebuilds the sink, carrying forward
    every setting the caller did NOT pass — an earlier explicit sample
    rate or budget is never silently reset to env defaults."""
    global _default
    with _lock:
        if _default is not None and (
                logdir is not None
                or (max_bytes is not None and max_bytes != _default.max_bytes)
                or (max_files is not None and max_files != _default.max_files)):
            logdir = logdir or _default.dir
            if sample is None:
                sample = _default.sample
            if max_bytes is None:
                max_bytes = _default.max_bytes
            if max_files is None:
                max_files = _default.max_files
            _default.close()
            _default = None
        if _default is None:
            _default = TraceSink(
                logdir or os.environ.get("CFS_TRACE_DIR") or os.path.join(
                    tempfile.gettempdir(), f"cfs-traces-{os.getpid()}"),
                sample=_env_sample() if sample is None else sample,
                max_bytes=(_env_int("CFS_TRACE_BYTES", 4 << 20)
                           if max_bytes is None else max_bytes),
                max_files=(_env_int("CFS_TRACE_FILES", 4)
                           if max_files is None else max_files))
            from chubaofs_tpu.blobstore import trace

            trace.set_finish_hook(_default.on_span_finish)
        elif sample is not None:
            _default.sample = float(sample)
        return _default


def activate_from_env() -> TraceSink | None:
    """Arm the sink iff CFS_TRACE_SAMPLE asks for sampling — the daemon-boot
    hook (RPCServer construction) that makes env-configured tracing live
    without any subsystem knowing about the sink."""
    if _env_sample() > 0.0:
        return default_sink()
    return _default


def force(span) -> bool:
    """Slow-op entry: persist this span whatever the sample rate says."""
    return default_sink().force(span)
