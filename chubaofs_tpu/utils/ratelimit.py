"""Token-bucket rate limiting (QoS primitives).

Reference counterparts: master/limiter.go (per-API op limits backed by
golang.org/x/time/rate buckets) and blobstore/access/limiter.go (read/write
bandwidth + concurrency gates on the gateway). One implementation serves both:
a monotonic-clock token bucket plus a keyed registry for per-op / per-client
buckets.
"""

from __future__ import annotations

import time

from chubaofs_tpu.utils.locks import SanitizedLock


class RateLimitExceeded(Exception):
    pass


class TokenBucket:
    """Thread-safe token bucket: `rate` tokens/sec, capacity `burst`.

    acquire() blocks up to `timeout` for tokens (None = forever); try_acquire()
    never blocks. rate <= 0 means unlimited.
    """

    def __init__(self, rate: float, burst: float | None = None):
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else max(rate, 1.0))
        self._tokens = self.burst
        self._last = time.monotonic()
        self._lock = SanitizedLock(name="ratelimit.bucket")

    def _refill_locked(self, now: float) -> None:
        self._tokens = min(self.burst, self._tokens + (now - self._last) * self.rate)
        self._last = now

    def try_acquire(self, n: float = 1.0) -> bool:
        if self.rate <= 0:
            return True
        with self._lock:
            now = time.monotonic()
            self._refill_locked(now)
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def debit(self, n: float = 1.0) -> None:
        """Post-hoc charge: subtract n tokens, allowing the balance to go
        NEGATIVE — the bandwidth-shaping pattern for response bytes whose
        size is only known after the handler ran (a GET's body). Future
        acquires wait until the debt refills; _refill_locked pays it down
        at the configured rate."""
        if self.rate <= 0:
            return
        with self._lock:
            self._refill_locked(time.monotonic())
            self._tokens -= n

    def wait_time(self, n: float = 1.0) -> float:
        """Seconds until n tokens COULD be available (0 when they already
        are) — the Retry-After estimate; no tokens are taken."""
        if self.rate <= 0:
            return 0.0
        with self._lock:
            self._refill_locked(time.monotonic())
            if self._tokens >= n:
                return 0.0
            return (n - self._tokens) / self.rate

    def acquire(self, n: float = 1.0, timeout: float | None = None) -> bool:
        """Take n tokens, sleeping while they accrue; False on timeout."""
        if self.rate <= 0:
            return True
        if n > self.burst:
            return False  # can never accrue n tokens — deny, don't wait
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                now = time.monotonic()
                self._refill_locked(now)
                if self._tokens >= n:
                    self._tokens -= n
                    return True
                wait = (n - self._tokens) / self.rate
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                wait = min(wait, remaining)
            time.sleep(min(wait, 0.05))


class KeyedLimiter:
    """Named buckets (per API op, per client, per volume...).

    rates maps key -> (rate, burst) or rate. Unknown keys pass through
    unlimited unless a `default` rate is given.
    """

    def __init__(self, rates: dict | None = None, default: float = 0.0):
        self._lock = SanitizedLock(name="ratelimit.keyed")
        self._buckets: dict[str, TokenBucket] = {}
        self._rates = dict(rates or {})
        self._default = default

    def _bucket(self, key: str) -> TokenBucket:
        with self._lock:
            b = self._buckets.get(key)
            if b is None:
                spec = self._rates.get(key, self._default)
                rate, burst = spec if isinstance(spec, tuple) else (spec, None)
                b = TokenBucket(rate, burst)
                self._buckets[key] = b
            return b

    def set_rate(self, key: str, rate: float, burst: float | None = None) -> None:
        """Runtime-mutable limits (the reference exposes these via admin API)."""
        with self._lock:
            self._rates[key] = (rate, burst)
            self._buckets.pop(key, None)

    def allow(self, key: str, n: float = 1.0) -> bool:
        return self._bucket(key).try_acquire(n)

    def wait(self, key: str, n: float = 1.0, timeout: float | None = None) -> bool:
        return self._bucket(key).acquire(n, timeout)

    def check(self, key: str, n: float = 1.0) -> None:
        """Raise RateLimitExceeded when the bucket is dry (fail-fast APIs)."""
        if not self.allow(key, n):
            raise RateLimitExceeded(f"rate limit exceeded for {key!r}")
