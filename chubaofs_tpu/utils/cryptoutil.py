"""Crypto primitives for the auth plane (util/cryptoutil analog).

Reference counterpart: util/cryptoutil — AES-256-GCM authenticated encryption
+ HMAC message auth + base64 key/ticket serialization, used by authnode and
its clients. This environment has no AES primitive in-tree, so the AEAD here
is the standard encrypt-then-MAC composition over stdlib hashes: an HMAC-
SHA256 counter-mode keystream for confidentiality and an HMAC-SHA256 tag over
nonce+ciphertext for integrity — same interface, same security role
(symmetric AEAD under a shared service key), swappable for AES-GCM where one
exists.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct


class AuthTagError(Exception):
    pass


def gen_key() -> bytes:
    return os.urandom(32)


def _keystream(key: bytes, nonce: bytes, length: int) -> bytes:
    out = bytearray()
    counter = 0
    while len(out) < length:
        out += hmac.new(key, nonce + struct.pack("<Q", counter),
                        hashlib.sha256).digest()
        counter += 1
    return bytes(out[:length])


def _subkeys(key: bytes) -> tuple[bytes, bytes]:
    enc = hmac.new(key, b"enc", hashlib.sha256).digest()
    mac = hmac.new(key, b"mac", hashlib.sha256).digest()
    return enc, mac


def seal(key: bytes, plaintext: bytes, aad: bytes = b"") -> bytes:
    """AEAD encrypt: nonce(16) || ciphertext || tag(32)."""
    enc_key, mac_key = _subkeys(key)
    nonce = os.urandom(16)
    ct = bytes(a ^ b for a, b in zip(plaintext,
                                     _keystream(enc_key, nonce, len(plaintext))))
    tag = hmac.new(mac_key, nonce + ct + aad, hashlib.sha256).digest()
    return nonce + ct + tag


def open_sealed(key: bytes, blob: bytes, aad: bytes = b"") -> bytes:
    """AEAD decrypt; raises AuthTagError on any tamper."""
    if len(blob) < 48:
        raise AuthTagError("sealed blob too short")
    nonce, ct, tag = blob[:16], blob[16:-32], blob[-32:]
    enc_key, mac_key = _subkeys(key)
    want = hmac.new(mac_key, nonce + ct + aad, hashlib.sha256).digest()
    if not hmac.compare_digest(want, tag):
        raise AuthTagError("auth tag mismatch")
    return bytes(a ^ b for a, b in zip(ct, _keystream(enc_key, nonce, len(ct))))


def hmac_sha256(key: bytes, msg: bytes) -> bytes:
    return hmac.new(key, msg, hashlib.sha256).digest()


def verify_hmac(key: bytes, msg: bytes, tag: bytes) -> bool:
    return hmac.compare_digest(hmac_sha256(key, msg), tag)
