"""Lock sanitizer — named locks with an opt-in runtime order/hold checker.

The runtime half of the concurrency plane (tools/racelint.py is the static
half). `SanitizedLock(name=...)` / `SanitizedRLock(name=...)` are drop-in
factories for `threading.Lock()` / `threading.RLock()`:

  * **Disabled (the default):** they return the plain threading primitive —
    zero wrapper, zero overhead, nothing imported on the hot path.
  * **`CFS_LOCK_SANITIZER=1`:** they return instrumented locks that record,
    per thread, the stack of locks currently held and a short acquisition
    site for each; every acquire while other locks are held adds
    `held -> acquired` edges to a process-global lock-ORDER graph. An edge
    whose reverse path already exists is a cycle — the classic A->B / B->A
    inversion that becomes a deadlock the day the two threads interleave the
    other way — and is reported ONCE per lock pair: a
    `cfs_lock_inversion` counter sample, one structured JSON audit line on
    stderr (daemon logs capture it), and an in-memory record that tests and
    `cfs-chaos-soak --sanitize` read via `inversions()`.
  * Hold times ride the same instrumentation: every release observes
    `cfs_lock_hold_ms{name=...}`, and holds longer than `CFS_LOCK_HOLD_MS`
    (default 100 ms) additionally emit a `lock_hold` audit line with the
    acquisition site — the "who slept inside a lock" answer that turns a
    p99 cliff into a file:line.

The activation check happens at lock CONSTRUCTION: daemons and tests that
set the env var before building their components (tier-1's conftest does,
so every MiniCluster e2e doubles as a race probe) get full coverage; a
process that never sets it pays nothing.

Names are part of the contract: `SanitizedLock(name="rpc.pool")` makes the
inversion report and the hold-time series readable. Same-name edges are NOT
tracked (two instances of one class sharing a name would self-cycle on
first contact); give distinct instances that can nest distinct names, as
raft does with `raft.node<N>`.

The sanitizer itself must not deadlock or recurse: the graph lock below is
a plain `threading.Lock`, metric emission happens OUTSIDE it, and the
exporter's internal micro-locks stay unsanitized (a sanitized counter lock
would re-enter the sanitizer from its own bookkeeping).
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import threading
import time

_ENV = "CFS_LOCK_SANITIZER"
_HOLD_ENV = "CFS_LOCK_HOLD_MS"

# hold-time histogram buckets, in MILLISECONDS (sub-0.1ms lock flashes up to
# multi-second stalls)
HOLD_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                100.0, 250.0, 1000.0)


def enabled() -> bool:
    """Is the sanitizer armed for locks constructed NOW?"""
    return os.environ.get(_ENV, "") not in ("", "0")


def hold_threshold_ms() -> float:
    try:
        return float(os.environ.get(_HOLD_ENV, "") or 100.0)
    except ValueError:
        return 100.0


def SanitizedLock(name: str = "anon"):
    """threading.Lock(), instrumented iff CFS_LOCK_SANITIZER is set."""
    if not enabled():
        return threading.Lock()
    return _SanLock(name, threading.Lock(), reentrant=False)


def SanitizedRLock(name: str = "anon"):
    """threading.RLock(), instrumented iff CFS_LOCK_SANITIZER is set."""
    if not enabled():
        return threading.RLock()
    return _SanLock(name, threading.RLock(), reentrant=True)


# -- process-global order graph ------------------------------------------------

# all four structures below are guarded by _graph_lock (a PLAIN lock: the
# sanitizer must never sanitize itself)
_graph_lock = threading.Lock()
_order: dict[str, set[str]] = {}  # name -> names acquired while it was held
_edge_site: dict[tuple[str, str], str] = {}  # first site that added each edge
_inversions: list[dict] = []
_reported_pairs: set[frozenset] = set()
_hold_outliers: list[dict] = []
_HOLD_OUTLIER_MAX = 256  # bounded: an audit trail, not a profile

_tls = threading.local()  # .held: list of [lock_obj, name, t0, site, token]
_acquire_tokens = itertools.count(1)  # unique token per tracked acquire


def _held_stack() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _site(skip: int = 2, frames: int = 4) -> str:
    """Short acquisition site: 'file:line:func < caller < ...'. Walks raw
    frames (no line-text formatting) so the per-acquire cost stays in the
    microseconds."""
    try:
        f = sys._getframe(skip)
    except ValueError:
        return "?"
    out = []
    while f is not None and len(out) < frames:
        co = f.f_code
        base = os.path.basename(co.co_filename)
        if base != "locks.py":
            out.append(f"{base}:{f.f_lineno}:{co.co_name}")
        f = f.f_back
    return " < ".join(out) or "?"


def _path_exists(src: str, dst: str) -> bool:
    """DFS over the order graph (called under _graph_lock)."""
    if src == dst:
        return True
    seen = {src}
    stack = [src]
    while stack:
        for nxt in _order.get(stack.pop(), ()):
            if nxt == dst:
                return True
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return False


def _audit_line(kind: str, rec: dict) -> None:
    """One structured audit line on stderr — daemon .log files and the
    harness capture it; never raises (the sanitizer must not break the
    locked path it watches)."""
    try:
        print(json.dumps({"audit": kind, **rec}),  # obslint: structured audit line; stderr is the captured daemon log
              file=sys.stderr, flush=True)
    except Exception:
        pass


def _metric_counter(name: str, labels: dict | None = None):
    from chubaofs_tpu.utils.exporter import registry

    return registry("lock").counter(name, labels)


def _note_edges_locked(acq_name: str, acq_site: str,
                       held: list) -> list[dict]:
    """Record held->acquired edges; returns inversion records to report.
    Caller holds _graph_lock (metric/audit emission happens OUTSIDE it)."""
    new_inversions: list[dict] = []
    for _, held_name, _, held_site, _ in held:
        if held_name == acq_name:
            continue  # reentrancy / same-name siblings: not an ordering
        after = _order.setdefault(held_name, set())
        if acq_name in after:
            continue  # known edge: fast path
        if _path_exists(acq_name, held_name):
            pair = frozenset((held_name, acq_name))
            if pair not in _reported_pairs:
                _reported_pairs.add(pair)
                rec = {
                    "first": held_name, "then": acq_name,
                    "held_site": held_site, "acquire_site": acq_site,
                    "reverse_site": _edge_site.get(
                        (acq_name, held_name), "?"),
                    "thread": threading.current_thread().name,
                }
                _inversions.append(rec)
                new_inversions.append(rec)
        after.add(acq_name)
        _edge_site.setdefault((held_name, acq_name), acq_site)
    return new_inversions


class _SanLock:
    """The instrumented lock: acquire/release/context-manager compatible
    with threading.Lock/RLock."""

    __slots__ = ("name", "_lock", "_reentrant", "_summary", "_holder",
                 "_stale")

    def __init__(self, name: str, lock, reentrant: bool):
        self.name = name
        self._lock = lock
        self._reentrant = reentrant
        self._summary = None
        # cross-thread handoff bookkeeping (plain Lock may legally be
        # released by a thread that never acquired it): _holder is the
        # TOKEN of the outermost live acquire, _stale the tokens whose
        # acquire was released from another thread — the acquirer's stack
        # entry is reconciled lazily, BY TOKEN, on its next acquire, so a
        # handoff can neither mint phantom order edges nor (the failure a
        # thread-agnostic counter had) evict a later legitimate holder's
        # entry
        self._holder = None
        self._stale: set[int] = set()

    # -- the instrumented path ---------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if not ok:
            return False
        held = _held_stack()
        site = _site()
        tok = next(_acquire_tokens)
        new_inversions: list[dict] = []
        # ONE critical section for reconcile + edges + holder: a concurrent
        # handoff release linearizes entirely before it (its stale mark is
        # seen and the dead entry dropped before edge-noting) or entirely
        # after (the entry was legitimately held when the edge was recorded)
        # — a half-applied release can't mint a phantom edge
        with _graph_lock:
            for i in range(len(held) - 1, -1, -1):
                lk = held[i][0]
                if held[i][4] in lk._stale:
                    lk._stale.discard(held[i][4])
                    held.pop(i)
            reentered = self._reentrant and any(e[0] is self for e in held)
            if held and not reentered:
                new_inversions = _note_edges_locked(self.name, site, held)
            if not reentered:
                self._holder = tok
        for rec in new_inversions:
            try:
                _metric_counter("inversion",
                                {"first": rec["first"],
                                 "then": rec["then"]}).add()
            except Exception:
                pass
            _audit_line("lock_inversion", rec)
            # the timeline record the alert plane's lock_inversion rule
            # watches. Lazy import + never-raises: the sanitizer must not
            # break (or import-cycle) the locked path it instruments
            try:
                from chubaofs_tpu.utils import events

                events.emit("lock_inversion", events.SEV_CRITICAL,
                            entity=f"{rec['first']}->{rec['then']}",
                            detail=dict(rec))
            except Exception:
                pass
        held.append([self, self.name, time.monotonic(), site, tok])
        return True

    def release(self) -> None:
        held = _held_stack()
        entry = None
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                entry = held.pop(i)
                break
        if entry is None:
            # cross-thread handoff release: mark the acquirer's live token
            # stale so ITS next acquire drops exactly that entry — and do it
            # BEFORE the primitive is released, while no fresh acquirer can
            # install a live token we would wrongly stale (the dead token
            # surviving instead would mint phantom order edges)
            with _graph_lock:
                victim, self._holder = self._holder, None
                if victim is not None:
                    self._stale.add(victim)
            try:
                self._lock.release()
            except BaseException:
                # un-acquired RLock etc: the release failed, so the holder
                # is NOT dead — restore its tracking before propagating
                with _graph_lock:
                    if victim is not None:
                        self._stale.discard(victim)
                        if self._holder is None:
                            self._holder = victim
                raise
            return
        self._lock.release()
        with _graph_lock:
            # atomic check-and-clear: self._lock is already released, so a
            # new holder's token may land concurrently and must survive
            if entry[4] == self._holder:
                self._holder = None
        dt_ms = (time.monotonic() - entry[2]) * 1e3
        try:
            if self._summary is None:
                from chubaofs_tpu.utils.exporter import registry

                self._summary = registry("lock").summary(
                    "hold_ms", {"name": self.name}, buckets=HOLD_BUCKETS)
            self._summary.observe(dt_ms)
        except Exception:
            pass
        if dt_ms >= hold_threshold_ms():
            rec = {"name": self.name, "hold_ms": round(dt_ms, 3),
                   "site": entry[3],
                   "thread": threading.current_thread().name}
            with _graph_lock:
                if len(_hold_outliers) < _HOLD_OUTLIER_MAX:
                    _hold_outliers.append(rec)
            _audit_line("lock_hold", rec)

    # -- lock API surface ---------------------------------------------------

    def locked(self) -> bool:
        return self._lock.locked() if hasattr(self._lock, "locked") else False

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, et, ev, tb):
        self.release()
        return False

    def __repr__(self):
        return f"<SanitizedLock {self.name!r} wrapping {self._lock!r}>"


# -- report surface ------------------------------------------------------------


def inversions() -> list[dict]:
    """Every lock-order inversion observed so far (one record per pair)."""
    with _graph_lock:
        return list(_inversions)


def hold_outliers() -> list[dict]:
    """Holds that crossed CFS_LOCK_HOLD_MS (bounded window)."""
    with _graph_lock:
        return list(_hold_outliers)


def report() -> dict:
    """The soak/test rollup: inversions + hold outliers + graph size."""
    with _graph_lock:
        return {
            "inversions": list(_inversions),
            "hold_outliers": list(_hold_outliers),
            "locks_tracked": len(_order),
            "edges": sum(len(v) for v in _order.values()),
        }


def reset() -> None:
    """Forget the graph and all records (tests isolate themselves with
    this; per-thread held stacks are live state and stay)."""
    with _graph_lock:
        _order.clear()
        _edge_site.clear()
        _inversions.clear()
        _reported_pairs.clear()
        _hold_outliers.clear()
