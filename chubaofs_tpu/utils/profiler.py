"""Sampling wall-clock profiler — stack-based "where does daemon CPU go".

The third leg of the observability plane: metrics say *how much*, traces say
*which request*, but neither says where a daemon's threads actually SPEND
wall time (cfs-trace flamegraphs are span-based — they only see what was
instrumented). This is the pprof-style answer: a timer thread samples
`sys._current_frames()` at `CFS_PROF_HZ` and aggregates whole stacks, so the
ROADMAP item-4 question — "is PUT bottlenecked on Python glue or device
encode?" — reads off a profile instead of being guessed.

Discipline (mirrors utils/locks.py's sanitizer):

  * **Disarmed (CFS_PROF_HZ unset, the default): strictly zero overhead.**
    `activate_from_env()` returns without creating anything; no thread, no
    hook, no import cost on any hot path. The tier-1 overhead gate asserts
    this stays true.
  * **Armed:** one daemon-wide sampler thread (`cfs-prof-cont`) keeps a
    rolling aggregate; `/debug/prof` (rpc/server.py mounts it next to
    /metrics) serves it. With `?seconds=N` the endpoint runs a fresh scoped
    capture instead — on-demand profiling works on ANY daemon, armed or
    not, because the cost is explicit and bounded by the request.

Aggregation is per THREAD-NAME bucket (digit runs collapsed, so
`evloop-pkt-0`/`evloop-pkt-1` fold into one `evloop-pkt-N` bucket while
staying distinct from `codec-svc`, `raft-tick`, `access-pipe_N`, ...): the
repo names every hot thread, which makes "which subsystem burns the CPU"
the profile's FIRST axis, before any stack is read. Output is collapsed-
stack text (`bucket;frame;frame count` — the flamegraph.pl/speedscope
format `cfs-trace --flame` also emits), root frame first.

Sampling bias note: `sys._current_frames()` needs the GIL, so samples land
at bytecode boundaries — C-extension/IO waits attribute to the Python frame
that entered them, which is exactly the "glue vs device dispatch" split the
codec roofline work needs.
"""

from __future__ import annotations

import os
import re
import sys
import threading
import time

_ENV = "CFS_PROF_HZ"

DEFAULT_HZ = 97.0       # prime: never phase-locks with periodic daemon work
MAX_HZ = 1000.0
MAX_SECONDS = 120.0     # on-demand capture bound (a typo'd ?seconds= must
                        # not pin a handler thread for an hour)
MAX_DEPTH = 48          # frames kept per stack, leaf-side truncated
MAX_STACKS = 4096       # distinct (bucket, stack) keys before lumping


def env_hz() -> float:
    """The armed sample rate, 0.0 when disarmed/malformed (a typo'd env var
    must not kill daemon boot — same contract as the trace sink's budgets)."""
    try:
        hz = float(os.environ.get(_ENV, "") or 0.0)
    except ValueError:
        return 0.0
    return min(hz, MAX_HZ) if hz > 0.0 else 0.0


def enabled() -> bool:
    """Is continuous profiling armed for THIS process?"""
    return env_hz() > 0.0


_DIGITS = re.compile(r"\d+")


def thread_bucket(name: str) -> str:
    """Thread name -> bounded bucket: digit runs collapse to `N` so pool
    members aggregate (`evw-pkt-3` -> `evw-pkt-N`) without erasing the
    subsystem (`evloop-pkt-N` vs `codec-svc` vs `raft-tick` stay apart)."""
    return _DIGITS.sub("N", name or "?")


class Profile:
    """One aggregation: (thread bucket, stack) -> sample count.

    `samples` counts every thread-sample taken; `attributed` the ones whose
    thread was nameable (a tid in `sys._current_frames()` with no live
    `threading` entry — foreign C threads, just-died threads — buckets as
    `?` and is NOT attributed). coverage = attributed / samples is the
    "per-thread-name buckets cover X% of sampled wall time" claim."""

    __slots__ = ("hz", "counts", "samples", "attributed", "sweeps",
                 "seconds", "_lock")

    def __init__(self, hz: float):
        self.hz = hz
        self.counts: dict[tuple[str, tuple[str, ...]], int] = {}
        self.samples = 0
        self.attributed = 0
        self.sweeps = 0
        self.seconds = 0.0
        self._lock = threading.Lock()

    # -- ingest (sampler thread only) ------------------------------------------

    def add_sweep(self, stacks: list[tuple[str, tuple[str, ...]]]) -> None:
        with self._lock:
            self.sweeps += 1
            for bucket, stack in stacks:
                self.samples += 1
                if bucket != "?":
                    self.attributed += 1
                key = (bucket, stack)
                if key not in self.counts and len(self.counts) >= MAX_STACKS:
                    # bounded cardinality: overflow stacks keep their thread
                    # bucket (the first axis survives) but lump the frames
                    key = (bucket, ("<other>",))
                self.counts[key] = self.counts.get(key, 0) + 1

    # -- report ----------------------------------------------------------------

    def thread_totals(self) -> dict[str, int]:
        with self._lock:
            out: dict[str, int] = {}
            for (bucket, _stack), n in self.counts.items():
                out[bucket] = out.get(bucket, 0) + n
            return dict(sorted(out.items(), key=lambda kv: -kv[1]))

    def collapsed(self) -> str:
        """Collapsed-stack lines, root frame first — what flamegraph.pl /
        speedscope ingest, and the same shape `cfs-trace --flame` emits for
        span trees. The thread bucket is the root frame."""
        with self._lock:
            items = sorted(self.counts.items(),
                           key=lambda kv: (-kv[1], kv[0]))
        return "\n".join(
            ";".join((bucket,) + stack) + f" {n}"
            for (bucket, stack), n in items)

    def coverage(self) -> float:
        with self._lock:
            return self.attributed / self.samples if self.samples else 0.0

    def to_dict(self) -> dict:
        with self._lock:
            samples, attributed = self.samples, self.attributed
            sweeps, stacks = self.sweeps, len(self.counts)
        return {
            "hz": self.hz,
            "seconds": round(self.seconds, 3),
            "sweeps": sweeps,
            "samples": samples,
            "attributed": attributed,
            "coverage": round(attributed / samples, 4) if samples else 0.0,
            "stacks": stacks,
            "threads": self.thread_totals(),
            "collapsed": self.collapsed(),
        }


def _sample_once(exclude: frozenset[int]) -> list[tuple[str, tuple[str, ...]]]:
    """One sweep over every live thread's current stack. `exclude` drops the
    profiler's own machinery (sampler thread + a blocked capture caller)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        if tid in exclude:
            continue
        stack: list[str] = []
        f = frame
        while f is not None and len(stack) < MAX_DEPTH:
            co = f.f_code
            stack.append(f"{os.path.basename(co.co_filename)}:{co.co_name}")
            f = f.f_back
        stack.reverse()  # root first: the collapsed-stack convention
        out.append((thread_bucket(names.get(tid, "?")) if tid in names
                    else "?", tuple(stack)))
    return out


class SamplingProfiler:
    """The sampler thread around a Profile. `rolling=True` keeps one
    process-lifetime aggregate (the continuous mode); capture() builds a
    fresh bounded one."""

    def __init__(self, hz: float, name: str = "cfs-prof-cont"):
        self.hz = max(0.1, min(float(hz), MAX_HZ))
        self.profile = Profile(self.hz)
        self._stop = threading.Event()
        self._started = 0.0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=name)
        self._extra_exclude: frozenset[int] = frozenset()

    def start(self) -> "SamplingProfiler":
        self._started = time.monotonic()
        self._thread.start()
        return self

    def _run(self) -> None:
        period = 1.0 / self.hz
        next_at = time.monotonic()
        while not self._stop.is_set():
            exclude = self._extra_exclude | {self._thread.ident}
            self.profile.add_sweep(_sample_once(frozenset(exclude)))
            self.profile.seconds = time.monotonic() - self._started
            next_at += period
            delay = next_at - time.monotonic()
            if delay > 0:
                self._stop.wait(delay)
            else:
                next_at = time.monotonic()  # overran: don't burst to catch up

    def stop(self) -> Profile:
        self._stop.set()
        self._thread.join(timeout=2.0)
        self.profile.seconds = time.monotonic() - self._started
        return self.profile


def capture(seconds: float, hz: float | None = None) -> Profile:
    """On-demand scoped capture: sample for `seconds` (bounded), return the
    Profile. Blocks the caller — that blocked frame is excluded from its own
    profile (it is profiler machinery, not workload)."""
    seconds = max(0.05, min(float(seconds), MAX_SECONDS))
    p = SamplingProfiler(hz or env_hz() or DEFAULT_HZ, name="cfs-prof-cap")
    caller = threading.current_thread().ident
    if caller is not None:
        p._extra_exclude = frozenset({caller})
    p.start()
    time.sleep(seconds)
    return p.stop()


# -- process-wide continuous profiler ------------------------------------------

_active: SamplingProfiler | None = None
_lock = threading.Lock()


def active() -> SamplingProfiler | None:
    return _active


def activate_from_env() -> SamplingProfiler | None:
    """Arm the continuous profiler iff CFS_PROF_HZ asks for it — the daemon-
    boot hook (rpc/server.py calls it next to tracesink.activate_from_env).
    Unset env = return None having touched nothing: the zero-overhead gate."""
    global _active
    if not enabled():
        return _active
    with _lock:
        if _active is None:
            _active = SamplingProfiler(env_hz()).start()
        return _active


def deactivate() -> None:
    """Stop + forget the continuous profiler (test isolation)."""
    global _active
    with _lock:
        p, _active = _active, None
    if p is not None:
        p.stop()
