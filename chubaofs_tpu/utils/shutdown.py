"""Graceful-shutdown idiom shared by every long-running entrypoint.

Reference counterpart: cmd/cmd.go's signal handling around server Shutdown —
one place defines the contract, every daemon reuses it. Two-phase on purpose:
handlers must be installed BEFORE the serving object boots (a supervisor that
signals the instant it sees the boot line must hit the graceful path, not the
default handler), while the wait happens after.
"""

from __future__ import annotations

import signal
import threading


def shutdown_event() -> threading.Event:
    """Install SIGTERM/SIGINT handlers that set the returned event.
    Event.wait has no handler/pause race (unlike signal.pause)."""
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    return stop


def await_shutdown(stop: threading.Event) -> None:
    """Block until a shutdown signal, then restore default SIGINT so a
    second ^C during a hung teardown still aborts the process (for the
    client role a SIGKILL would leak its kernel mount)."""
    stop.wait()
    signal.signal(signal.SIGINT, signal.SIG_DFL)
