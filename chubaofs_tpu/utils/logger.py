"""Leveled rotating logger (util/log analog).

Reference counterpart: util/log — per-module leveled logs written to a
directory of size-rotated files, with a runtime-mutable level (the reference
exposes /loglevel/set, cmd/cmd.go:282; here `set_level`). Built over the
stdlib logging package so third-party handlers compose; the module-level
`get_logger(module, dir)` mirrors log.InitLog's one-logger-per-daemon shape.
"""

from __future__ import annotations

import logging
import logging.handlers
import os

_loggers: dict[str, logging.Logger] = {}

LEVELS = {"debug": logging.DEBUG, "info": logging.INFO, "warn": logging.WARNING,
          "error": logging.ERROR, "critical": logging.CRITICAL}


def get_logger(module: str, logdir: str | None = None, level: str = "info",
               max_bytes: int = 8 << 20, backups: int = 4) -> logging.Logger:
    lg = _loggers.get(module)
    if lg is not None:
        return lg
    lg = logging.getLogger(f"cfs.{module}")
    lg.setLevel(LEVELS.get(level, logging.INFO))
    lg.propagate = False
    fmt = logging.Formatter(
        "%(asctime)s [%(levelname)s] %(name)s: %(message)s")
    if logdir:
        os.makedirs(logdir, exist_ok=True)
        h: logging.Handler = logging.handlers.RotatingFileHandler(
            os.path.join(logdir, f"{module}.log"),
            maxBytes=max_bytes, backupCount=backups)
    else:
        h = logging.NullHandler()
    h.setFormatter(fmt)
    lg.addHandler(h)
    _loggers[module] = lg
    return lg


def set_level(module: str, level: str) -> bool:
    """Runtime level mutation (the /loglevel/set endpoint's backing call)."""
    lg = _loggers.get(module)
    if lg is None or level not in LEVELS:
        return False
    lg.setLevel(LEVELS[level])
    return True
