"""JSON daemon config (util/config analog).

Reference counterpart: util/config — every daemon takes one JSON file via
`-c path` (cmd/cmd.go:85,138) and reads typed keys with defaults; blobstore
modules bind sub-structs (blobstore/cmd/cmd.go:46-62). Kept: typed getters
with defaults and a required-key check; added: dotted-path access for nested
module sections so one file can configure an in-process cluster.
"""

from __future__ import annotations

import json


class ConfigError(Exception):
    pass


class Config:
    def __init__(self, data: dict):
        self.data = dict(data)

    @classmethod
    def from_file(cls, path: str) -> "Config":
        with open(path, encoding="utf-8") as f:
            return cls(json.load(f))

    @classmethod
    def from_string(cls, s: str) -> "Config":
        return cls(json.loads(s))

    def _lookup(self, key: str):
        node = self.data
        for part in key.split("."):
            if not isinstance(node, dict) or part not in node:
                return None, False
            node = node[part]
        return node, True

    def get_string(self, key: str, default: str = "") -> str:
        v, ok = self._lookup(key)
        return str(v) if ok else default

    def get_int(self, key: str, default: int = 0) -> int:
        v, ok = self._lookup(key)
        return int(v) if ok else default

    def get_float(self, key: str, default: float = 0.0) -> float:
        v, ok = self._lookup(key)
        return float(v) if ok else default

    def get_bool(self, key: str, default: bool = False) -> bool:
        v, ok = self._lookup(key)
        if not ok:
            return default
        if isinstance(v, bool):
            return v
        return str(v).lower() in ("1", "true", "yes")

    def get_slice(self, key: str, default=None) -> list:
        v, ok = self._lookup(key)
        return list(v) if ok else (default or [])

    def sub(self, key: str) -> "Config":
        v, ok = self._lookup(key)
        return Config(v if ok and isinstance(v, dict) else {})

    def check_required(self, *keys: str):
        missing = [k for k in keys if not self._lookup(k)[1]]
        if missing:
            raise ConfigError(f"missing required config keys: {missing}")


# -- env knob parsing (the CFS_* idiom shared by tools/daemons) ----------------
#
# The unclamped canonical pair: a malformed value degrades to the default
# (these parses often run during daemon boot, where a typo'd env var must
# not kill the process). Callers needing a floor (evloop's >=1 shard count,
# slo's window sizes) keep their own clamped wrappers.


def env_int(name: str, default: int) -> int:
    import os

    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def env_float(name: str, default: float) -> float:
    import os

    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default
