"""Chaos — deterministic fault injection for the whole stack.

Three layers (ARCHITECTURE.md `## Chaos`):

  * `chaos.failpoint(name)` call sites woven through the hot failure paths
    (rpc, raft transport, datanode disk IO, extent-store CRC, blobnode shard
    IO, access hedged gather, FUSE dispatch, meta submit, rs encode) — armed
    per-name with error / delay / hang-until-released / drop / corrupt /
    return-value actions, globally or per-node, with hit counters, budgets
    and probabilities. Zero-overhead no-ops while nothing is armed.
  * a seeded `ChaosScheduler` that drives fault plans (node wedge, slow
    disk, link drop, shard bit-rot, process crash/restart) against a live
    MiniCluster on a virtual timeline with a reproducible event log.
  * the soak harness (`chaos.soak.run_soak`, `tools/chaos_soak.py`) that
    proves PUT -> fault -> degraded GET -> heal -> converge with zero data
    loss under each plan.

Env-var control: `CFS_FAILPOINTS=blobnode.get_shard=delay(2.0);raft.send=
drop@0.1` is parsed on first import, so daemon subprocesses inherit faults
from the harness environment.
"""

from chubaofs_tpu.chaos.failpoints import (  # noqa: F401
    Dropped,
    FailpointError,
    arm,
    armed,
    corrupt_bytes,
    disarm,
    failpoint,
    fired,
    hits,
    load_env,
    load_spec,
    release,
    reset,
)
from chubaofs_tpu.chaos.inject import corrupt_shard_on_disk  # noqa: F401
from chubaofs_tpu.chaos.scheduler import (  # noqa: F401
    ChaosScheduler,
    Fault,
    FaultPlan,
    builtin_plan,
)

load_env()  # arm anything the harness put in CFS_FAILPOINTS
