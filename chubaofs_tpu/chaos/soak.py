"""Chaos soak — PUT -> fault -> degraded GET -> heal -> converge, seeded.

The acceptance cycle behind `tools/chaos_soak.py` and tests/test_chaos.py:
a MiniCluster takes writes, a ChaosScheduler injects a fault plan on the
virtual timeline, every ACKED blob must read back byte-identical in every
phase (degraded included) with bounded tail latency, and once the faults
lift the repair planes must converge to a quiet inspector sweep with zero
data loss. Everything is driven off seeded RNGs, so the injection event
log is reproducible run-over-run.

PUTs issued while a fault window is ACTIVE may be rejected by the put
quorum (EC quorums tolerate one lost unit; a wedged two-disk node can
legitimately hold two units of a stripe). A rejected PUT is correct
degraded behavior — the data was never acked — and the soak retries it
until it lands; an unacked blob is never counted against data loss. A
rejection while NO fault is active fails the soak.
"""

from __future__ import annotations

import random
import time

from chubaofs_tpu.chaos import failpoints as fp
from chubaofs_tpu.chaos.scheduler import (
    ChaosScheduler,
    Fault,
    FaultPlan,
    builtin_plan,
)

SIZES = [8_000, 120_000, 700_000, 2_000_000]


class SoakFailure(AssertionError):
    """A soak gate tripped. When the flight recorder captured an incident
    bundle for it, `bundle` carries the directory path (cfs-chaos-soak
    prints it in the failure report)."""

    bundle: str | None = None


def _capture_on_failure(fn):
    """Freeze an incident bundle the moment a soak gate trips — the rings
    the postmortem needs (events, slowops, metric history, traces) are
    in-process and still warm right here; by the time an operator reruns
    anything they've rotated. Explicit capture works even with CFS_FLIGHT
    unset (the on-demand contract); a capture error must never mask the
    soak failure itself."""
    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except SoakFailure as e:
            try:
                from chubaofs_tpu.utils import flightrec

                man = flightrec.capture(trigger="soak_failure",
                                        fingerprint=f"soak:{fn.__name__}",
                                        alert={"name": fn.__name__,
                                               "error": str(e)})
                e.bundle = man.get("bundle")
            except Exception:
                pass
            raise

    return wrapped


class _AlertProbe:
    """The soak's alert-plane gate: a PRIVATE AlertManager + metric-history
    ring (never the process defaults — the probe neither inherits instance
    state from, nor clobbers the cfs_alerts_firing gauge of, whatever
    serving manager exists in this process; its slo_failing rule evaluates
    with track_flips=False for the same reason), ticked by the soak loop.
    Its alert_firing/alert_resolved transition events DO land on the
    journal — in a MiniCluster soak the probe IS the alert plane, and the
    lifecycle is exactly the timeline evidence the acceptance reads.
    `fired`/`firing` are what the gates assert on."""

    def __init__(self, infra_only: bool = False):
        from chubaofs_tpu.utils.alerts import AlertManager, default_rules
        from chubaofs_tpu.utils.metrichist import MetricHistory

        rules = default_rules()
        if infra_only:
            # the kill soak's exactly-one-alert contract: SLO burn windows
            # legitimately flip while a node is dead (PUT quorums reject,
            # p99 inflates — that's detection, and the capacity harness
            # owns gating it); the deterministic lifecycle this soak proves
            # is the INFRASTRUCTURE alert: broken disks fire, then resolve
            rules = [r for r in rules if r.kind != "slo_failing"]
        self.hist = MetricHistory(maxlen=64)
        self.am = AlertManager(rules=rules, private=True)

    def tick(self) -> None:
        self.hist.record()
        self.am.evaluate(self.hist.snapshots())

    def fired(self) -> list[str]:
        return self.am.fired_names()

    def firing(self) -> list[str]:
        return sorted({a["name"] for a in self.am.firing()})


def _timeline_events(journal, seq0: int) -> list[dict]:
    evs, _ = journal.query(since=seq0, n=10 ** 6)
    return evs


def _assert_causal_order(evs: list[dict], seed: int) -> list[dict]:
    """The kill soak's timeline acceptance: the injected kill, the broken-
    disk detection, the repair lease, and the rebuild-finished terminal
    event must all be PRESENT and in causal (monotonic) order — and the
    rebuild-finished event must carry the repair trace id so `cfs-events
    --correlate` can join it to the repair spans. Returns the four anchor
    events, in order."""

    def first(pred, what: str) -> dict:
        for e in evs:
            if pred(e):
                return e
        raise SoakFailure(
            f"kill soak seed {seed}: timeline has no {what} event "
            f"({len(evs)} events on the journal)")

    kill = first(lambda e: e["type"] == "chaos_inject"
                 and e["entity"] == "node_kill", "chaos_inject/node_kill")
    broken = first(lambda e: e["type"] == "disk_status"
                   and e["detail"].get("to") == "broken", "disk_broken")
    lease = first(lambda e: e["type"] == "lease_acquired"
                  and e["detail"].get("kind") == "disk_repair",
                  "disk-repair lease_acquired")
    finishes = [e for e in evs if e["type"] == "task_finished"
                and e["detail"].get("kind") == "disk_repair"]
    if not finishes:
        raise SoakFailure(f"kill soak seed {seed}: timeline has no "
                          f"disk-repair task_finished (rebuild-finished)")
    done = finishes[-1]
    chain = [kill, broken, lease, done]
    monos = [e["mono"] for e in chain]
    if monos != sorted(monos):
        raise SoakFailure(
            f"kill soak seed {seed}: timeline out of causal order: "
            + " -> ".join(f"{e['type']}@{e['mono']:.3f}" for e in chain))
    if not done.get("trace_id"):
        raise SoakFailure(
            f"kill soak seed {seed}: rebuild-finished event carries no "
            f"trace id (cfs-events --correlate would find nothing)")
    return chain


@_capture_on_failure
def run_soak(root: str, plan: FaultPlan | str, seed: int, rounds: int = 6,
             puts_per_round: int = 2, n_nodes: int = 9, disks_per_node: int = 2,
             sizes: list[int] | None = None, read_deadline: float = 0.5,
             write_deadline: float = 4.0, converge_sweeps: int = 12) -> dict:
    """One full soak cycle; returns {events, puts, gets, max_get_s, ok, ...}.
    Raises SoakFailure on data loss, latency-bound violation, or a cluster
    that will not converge after the faults lift."""
    import numpy as np

    from chubaofs_tpu.blobstore.access import Access, AccessError
    from chubaofs_tpu.blobstore.cluster import MiniCluster

    if isinstance(plan, str):
        plan = builtin_plan(plan, steps=rounds)
    sizes = sizes or SIZES
    rnd = random.Random(seed)          # op schedule
    rng = np.random.default_rng(seed)  # payload bytes
    c = MiniCluster(root, n_nodes=n_nodes, disks_per_node=disks_per_node)
    # alert-plane probe: a CLEAN cluster (pre-fault) must evaluate quiet —
    # that's the gate; alerts firing while a fault window is ACTIVE are the
    # plane WORKING (a wedged node legitimately burns put_p99) and are
    # reported as evidence, not failed on
    probe = _AlertProbe()
    # soak-tuned gateway: a wedged node must cost fractions of a second, not
    # the production 3s/10s windows, and hung reads pin pool workers until
    # the fault lifts — size the pools for that (the displaced stock gateway
    # gives up its executors first: MiniCluster.close only sees the new one)
    c.access.close()
    c.access = Access(c.cm, c.proxy, c.nodes, codec=c.codec, max_workers=64,
                      read_deadline=read_deadline,
                      write_deadline=write_deadline)
    sched = ChaosScheduler(c, plan, seed=seed + 1)
    live = sched.blobs  # blob idx -> (Location, payload); shared by bitrot
    # degraded GETs must finish inside the hedged-gather budget even with
    # wedged replicas; generous margin for CI thread scheduling
    get_bound = write_deadline + read_deadline + 5.0
    stats = {"puts": 0, "puts_rejected": 0, "gets": 0, "max_get_s": 0.0}
    next_id = 0
    pending: list[bytes] = []  # payloads rejected under faults, to retry
    try:
        gated_clean = False
        for _ in range(rounds):
            for _ in range(puts_per_round):
                size = rnd.choice(sizes)
                pending.append(
                    rng.integers(0, 256, size, dtype=np.uint8).tobytes())
            retry = []
            for data in pending:
                try:
                    live[next_id] = (c.access.put(data), data)
                    next_id += 1
                    stats["puts"] += 1
                except AccessError:
                    if sched.quiesced():
                        raise SoakFailure(
                            f"t={sched.vtime}: PUT rejected with no fault "
                            f"active under plan {plan.name} seed {seed}")
                    stats["puts_rejected"] += 1
                    retry.append(data)  # never acked: retry after heal
            pending = retry

            # the clean-cluster gate: before the FIRST injection, the rule
            # set must evaluate quiet (plans inject at step >= 1, so round
            # 0 always exercises this)
            if not gated_clean and sched.quiesced():
                probe.tick()
                if probe.fired():
                    raise SoakFailure(
                        f"plan {plan.name} seed {seed}: alerts fired on a "
                        f"clean pre-fault cluster: {probe.fired()}")
                gated_clean = True

            sched.step()

            # pump the repair planes between faults
            for _ in range(4):
                s = c.run_background_once()
                if (s["repair_msgs"] == 0 and s["disk_tasks"] == 0
                        and s["tasks_ran"] == 0):
                    break
            probe.tick()

            # THE invariant: every acked blob reads byte-identical, degraded
            # or healed, inside the latency bound
            for idx, (loc, data) in live.items():
                t0 = time.monotonic()
                got = c.access.get(loc)
                dt = time.monotonic() - t0
                stats["gets"] += 1
                stats["max_get_s"] = max(stats["max_get_s"], dt)
                if got != data:
                    raise SoakFailure(
                        f"t={sched.vtime}: blob {idx} corrupted under "
                        f"plan {plan.name} seed {seed}")
                if dt > get_bound:
                    raise SoakFailure(
                        f"t={sched.vtime}: blob {idx} GET took {dt:.2f}s "
                        f"(bound {get_bound:.2f}s) under plan {plan.name}")

        # lift anything still active, land the retries, then CONVERGE:
        # repair planes drain and a full inspector sweep goes quiet
        sched.close()
        for data in pending:
            live[next_id] = (c.access.put(data), data)
            next_id += 1
            stats["puts"] += 1
        converged = False
        for _ in range(converge_sweeps):
            c.run_background_once()
            if c.scheduler.inspect_volumes(max_volumes=1000) == 0:
                converged = True
                break
        if not converged:
            raise SoakFailure(
                f"plan {plan.name} seed {seed}: inspector never went quiet "
                f"after faults lifted")
        for idx, (loc, data) in live.items():
            if c.access.get(loc) != data:
                raise SoakFailure(
                    f"post-heal: blob {idx} lost under plan {plan.name}")
        # final evaluation after convergence; fault-window alerts ride the
        # result as evidence (a wedge burning put_p99 is detection, not a
        # soak failure — the kill soak owns the fire-then-resolve contract)
        probe.tick()
        # how often each injection actually bit (anti-vacuous-green signal:
        # a soak whose faults never fire has tested nothing)
        fired = {n: fp.fired(n) for n in
                 ("access.read_shard", "access.write_shard", "raft.send")}
        return {"plan": plan.name, "seed": seed, "events": list(sched.events),
                "ok": True, "fired": {k: v for k, v in fired.items() if v},
                "alerts_fired": probe.fired(), **stats}
    finally:
        sched.close()
        fp.reset()  # never leak armings into the next soak/test
        c.close()


@_capture_on_failure
def run_kill_soak(root: str, seed: int, n_nodes: int = 9,
                  disks_per_node: int = 2, warm_puts: int = 10,
                  live_puts: int = 8, hb_timeout: float = 0.75,
                  wire_ms: float = 2.0, read_deadline: float = 0.5,
                  write_deadline: float = 4.0, max_wait_s: float = 120.0,
                  sizes: list[int] | None = None,
                  mode: int | str | None = None) -> dict:
    """Kill a blobnode under live PUT load; the repair plane must notice and
    rebuild (the ISSUE-7 acceptance scenario).

    `mode` pins every PUT to one CodeMode (name or value; None = cluster
    default) — the ISSUE-19 axis: soaking RG6P6 drives the rebuild through
    the beta-fetch plane (and its multi-loss full-gather fallback when the
    killed node held two units of a stripe), under the SAME byte-identical
    read-back and convergence invariants as the default mode.

    Phases: warm PUTs land acked blobs -> a seeded node_kill closes one
    engine and removes it from routing (its heartbeats stop) -> the
    clustermgr heartbeat expiry must mark the dead node's disks broken, the
    scheduler must turn them into disk-repair tasks, and the windowed
    rebuild pipeline must re-home every affected stripe onto the survivors
    — all while fresh PUTs keep arriving. During the rebuild a
    deterministic `wire_ms` delay rides every shard read (the deployment's
    gateway->blobnode RTT, as in perfbench's _wire regime) so the
    download/decode overlap the pipeline exists for is measurable; the
    repair spans are captured and analyzed with the cfs-trace library.

    Fails (SoakFailure) on: detection/rebuild timeout, any acked blob not
    byte-identical after rebuild, zero rebuild throughput, or a stranded
    WORKING task at soak end. Returns rebuild throughput, repair-traffic
    accounting (bytes per repaired shard), the download/decode overlap
    ratio, and the seeded event log."""
    import numpy as np

    from chubaofs_tpu.blobstore import trace
    from chubaofs_tpu.blobstore.access import Access, AccessError
    from chubaofs_tpu.blobstore.cluster import MiniCluster
    from chubaofs_tpu.blobstore.clustermgr import DISK_NORMAL
    from chubaofs_tpu.blobstore.proxy import TOPIC_SHARD_REPAIR
    from chubaofs_tpu.blobstore.scheduler import TASK_PREPARED, TASK_WORKING
    from chubaofs_tpu.blobstore.taskswitch import SWITCH_VOL_INSPECT
    from chubaofs_tpu.tools.cfstrace import critical_path, stage_overlap
    from chubaofs_tpu.utils.exporter import registry

    from chubaofs_tpu.utils import events as ev

    from chubaofs_tpu.codec.codemode import CodeMode

    sizes = sizes or SIZES
    if isinstance(mode, str):
        mode = CodeMode[mode]
    rnd = random.Random(seed)
    rng = np.random.default_rng(seed)
    c = MiniCluster(root, n_nodes=n_nodes, disks_per_node=disks_per_node)
    c.access.close()
    c.access = Access(c.cm, c.proxy, c.nodes, codec=c.codec, max_workers=64,
                      read_deadline=read_deadline,
                      write_deadline=write_deadline)
    c.scheduler.hb_timeout_s = hb_timeout
    # event-timeline + alert-plane acceptance (ISSUE 13): everything this
    # soak injects and everything the repair plane does about it must land
    # on ONE queryable timeline, and the broken-disk alert must FIRE during
    # the outage and RESOLVE once the rebuild converges
    journal = ev.default_journal()
    seq0 = journal.last_seq()
    probe = _AlertProbe(infra_only=True)
    # capture every repair span for the cfs-trace overlap proof (restore
    # whatever hook — trace sink or none — was installed before us)
    records: list[dict] = []
    prev_hook = trace.finish_hook()

    def _collect(span):
        if span.operation == "scheduler.repair":
            records.append(span.to_record())
        if prev_hook is not None:
            # chain: an installed trace sink must keep seeing EVERY span
            # finished during the soak, not lose them to our capture
            prev_hook(span)

    trace.set_finish_hook(_collect)
    reg = registry("scheduler")
    shards0 = reg.counter("repaired_shards").value
    bytes0 = reg.counter("repair_bytes_downloaded").value
    beta0 = reg.counter("repair_beta_shards").value
    live: dict[int, tuple] = {}
    next_id = 0
    stats = {"puts": 0, "puts_rejected": 0, "live_puts": 0}

    def put_one(data: bytes) -> bool:
        nonlocal next_id
        try:
            live[next_id] = (c.access.put(data, code_mode=mode), data)
            next_id += 1
            stats["puts"] += 1
            return True
        except AccessError:
            stats["puts_rejected"] += 1
            return False

    try:
        for _ in range(warm_puts):
            data = rng.integers(0, 256, rnd.choice(sizes),
                                dtype=np.uint8).tobytes()
            while not put_one(data):
                pass  # pre-kill: a healthy cluster must ack every PUT
        # settle heartbeats once so no disk is stale at kill time
        c.run_background_once()
        # the clean half of the alert acceptance: before any fault, the
        # rule set evaluates quiet
        probe.tick()
        if probe.fired():
            raise SoakFailure(
                f"kill soak seed {seed}: alerts firing BEFORE the kill "
                f"(stale state or broken rules): {probe.fired()}")

        plan = FaultPlan("node_kill", [Fault("node_kill", at=0)])
        sched = ChaosScheduler(c, plan, seed=seed + 1)
        sched.step()  # the seeded kill; the victim choice is in the log
        killed = sched.events[-1]["node"]
        victim_disks = [d.disk_id for d in c.cm.disks.values()
                        if d.node_id == killed]

        # rebuild under the deployment's latency shape: every shard read
        # pays wire_ms, so download width is real and overlap measurable.
        # The inspector sweep is paused for the rebuild window (it reads
        # every shard of every volume per tick — detection here is
        # heartbeat-driven, not inspector-driven) and re-enabled for the
        # convergence proof below.
        c.scheduler.switches.set(SWITCH_VOL_INSPECT, False)
        if wire_ms > 0:
            fp.arm("blobnode.get_shard", f"delay({wire_ms / 1000.0})")
        t_kill = time.monotonic()
        t_detect = None
        rebuild_busy = 0.0  # wall time the worker actually spent rebuilding
        pending_live = [
            rng.integers(0, 256, rnd.choice(sizes), dtype=np.uint8).tobytes()
            for _ in range(live_puts)]
        try:
            while True:
                if time.monotonic() - t_kill > max_wait_s:
                    raise SoakFailure(
                        f"kill soak seed {seed}: rebuild did not finish in "
                        f"{max_wait_s:.0f}s (victim node {killed})")
                if pending_live:  # live PUT load rides the rebuild
                    if put_one(pending_live[0]):
                        stats["live_puts"] += 1
                        pending_live.pop(0)
                # the detection->repair chain, stepped discretely so the
                # worker drain's wall time is measurable on its own (the
                # rebuild-throughput denominator)
                for n in list(c.nodes.values()):
                    try:
                        n.heartbeat(c.cm)
                    except Exception:
                        pass
                c.scheduler.check_node_health()
                c.scheduler.reap_expired()
                c.scheduler.poll_repair_topic()
                c.scheduler.check_disks()
                statuses = {c.cm.disks[d].status for d in victim_disks}
                if t_detect is None and statuses != {DISK_NORMAL}:
                    t_detect = time.monotonic()
                # the outage window: evaluated BEFORE the worker drains, so
                # the broken->repairing state is observable (one drain pass
                # can take a small cluster all the way to DROPPED)
                probe.tick()
                t0w = time.monotonic()
                ran = 0
                while c.worker.run_once():
                    ran += 1
                if ran:
                    rebuild_busy += time.monotonic() - t0w
                open_tasks = (c.scheduler.tasks(state=TASK_PREPARED)
                              + c.scheduler.tasks(state=TASK_WORKING))
                if (t_detect is not None and DISK_NORMAL not in statuses
                        and not open_tasks
                        and c.proxy.topics[TOPIC_SHARD_REPAIR].lag(
                            "scheduler") == 0):
                    break
                time.sleep(0.05)  # let the heartbeat-silence clock advance
        finally:
            if wire_ms > 0:
                fp.disarm("blobnode.get_shard")
            c.scheduler.switches.set(SWITCH_VOL_INSPECT, True)
        t_done = time.monotonic()

        # recovery is confirmed (rebuild finished): drop the punish windows
        # the dead node earned so post-rebuild PUTs trust the healed layout
        c.access.clear_punishments()
        # land any live PUTs the quorum rejected mid-rebuild
        for data in pending_live:
            for _ in range(50):
                if put_one(data):
                    break
                c.run_background_once()
            else:
                raise SoakFailure(f"kill soak seed {seed}: PUT still "
                                  f"rejected after the rebuild converged")

        # converge: repair planes drain and a FULL inspector sweep is quiet
        converged = False
        for _ in range(16):
            c.run_background_once()
            if c.scheduler.inspect_volumes(max_volumes=1000) == 0:
                converged = True
                break
        if not converged:
            raise SoakFailure(f"kill soak seed {seed}: inspector never went "
                              f"quiet after the rebuild")

        # THE invariants: every acked blob byte-identical on the survivors,
        # no unit still mapped to a dead disk, zero stranded WORKING tasks
        for idx, (loc, data) in live.items():
            if c.access.get(loc) != data:
                raise SoakFailure(
                    f"kill soak seed {seed}: blob {idx} miscompares after "
                    f"rebuild of node {killed}")
        for vol in c.cm.volumes.values():
            for u in vol.units:
                if u.disk_id in victim_disks:
                    raise SoakFailure(
                        f"kill soak seed {seed}: unit {u.vuid} still on dead "
                        f"disk {u.disk_id}")
        stranded = c.scheduler.tasks(state=TASK_WORKING)
        if stranded:
            raise SoakFailure(
                f"kill soak seed {seed}: {len(stranded)} WORKING tasks "
                f"stranded at soak end")

        rebuilt = reg.counter("repaired_shards").value - shards0
        dl_bytes = reg.counter("repair_bytes_downloaded").value - bytes0
        rebuild_s = max(1e-9, rebuild_busy)
        if rebuilt <= 0:
            raise SoakFailure(
                f"kill soak seed {seed}: zero rebuild throughput "
                f"(no shards repaired after killing node {killed})")

        # the chaos half of the alert acceptance: the outage fired EXACTLY
        # one named alert (broken_disks) and, now that every victim disk is
        # DROPPED, it resolves
        probe.tick()
        if probe.fired() != ["broken_disks"]:
            raise SoakFailure(
                f"kill soak seed {seed}: expected exactly the broken_disks "
                f"alert to fire during the outage, got {probe.fired()}")
        if probe.firing():
            raise SoakFailure(
                f"kill soak seed {seed}: alerts still firing after the "
                f"rebuild converged: {probe.firing()}")

        # timeline acceptance: kill -> disk_broken -> repair lease ->
        # rebuild finished, causally ordered and trace-correlated
        tl = _timeline_events(journal, seq0)
        chain = _assert_causal_order(tl, seed)
        timeline = [{"t": round(e["mono"] - chain[0]["mono"], 3),
                     "type": e["type"], "entity": e["entity"],
                     "severity": e["severity"],
                     **({"trace_id": e["trace_id"]}
                        if e.get("trace_id") else {})}
                    for e in chain]
        # the cfs-trace proof: per-repair-trace download/decode overlap
        overlap, best_report = 0.0, None
        for rec in records:
            ov = stage_overlap([rec], "download", "codec.")
            if ov["ratio"] > overlap or best_report is None:
                overlap = max(overlap, ov["ratio"])
                best_report = critical_path([rec])
        return {
            "plan": "kill_blobnode", "seed": seed, "ok": True,
            "code_mode": CodeMode(mode).name if mode is not None else None,
            "beta_shards": int(
                reg.counter("repair_beta_shards").value - beta0),
            "events": list(sched.events), "killed_node": killed,
            "detect_s": round((t_detect or t_done) - t_kill, 3),
            "rebuild_s": round(rebuild_s, 3),
            "rebuilt_shards": int(rebuilt),
            "rebuild_shards_per_s": round(rebuilt / rebuild_s, 1),
            "bytes_per_repaired_shard": round(dl_bytes / rebuilt, 1),
            "repair_overlap_ratio": round(overlap, 3),
            "repair_traces": len(records),
            "critical_path": best_report,
            "timeline": timeline,
            "repair_trace_id": chain[-1].get("trace_id"),
            "alerts_fired": probe.fired(),
            "alerts_firing": probe.firing(),
            **stats,
        }
    finally:
        trace.set_finish_hook(prev_hook)
        fp.reset()
        c.close()


@_capture_on_failure
def run_meta_split_soak(root: str, seed: int, metanodes: int = 5,
                        dirs: int = 8, seed_files: int = 12,
                        creator_threads: int = 3, files_per_thread: int = 4000,
                        kill_delay_s: tuple = (0.05, 0.4),
                        settle_timeout_s: float = 120.0) -> dict:
    """Metadata scale-out chaos soak (ISSUE 15): crash-restart a metanode
    MID-SPLIT and MID-MIGRATION under live create load, over real daemon
    processes (ProcCluster — SIGKILL is the fault, WAL recovery + the
    master's resume/heal sweeps are the cure).

    Phases:
      1. seed a directory-heavy namespace (dirs interleaved with files so
         the median split balances directories);
      2. start creator threads (every ACKED create lands in a ledger);
      3. trigger a mid-range LOAD SPLIT of the dirs-heavy partition and,
         after a seeded delay, SIGKILL a metanode hosting it; respawn it;
         the split must finish — either the synchronous call won the race
         or the master's resume sweep drives it from the partition's
         replicated freeze record (heartbeat split reports);
      4. trigger a cross-metanode MIGRATION (rebalance_meta moves the
         hottest partition's replica to the spare metanode) and SIGKILL
         another metanode mid-dance; respawn; the master's
         ensure_replica_counts sweep heals any partial move;
      5. verify: ZERO created-file loss (every acked path stats and its
         dentry appears exactly once), NO double-owned inode (per-leader
         namespace dumps: every ino in exactly one partition, inside its
         view range), membership healed (3 peers per partition), and the
         kill timeline is visible via meta_split / meta_migrate events on
         the master journal (freeze -> commit -> complete causally
         ordered around the kill stamps).

    Raises SoakFailure on any violation; returns stats + the timeline."""
    import json as _json
    import threading

    from chubaofs_tpu.master.api_service import MasterClient
    from chubaofs_tpu.meta.service import RemoteMetaNode
    from chubaofs_tpu.sdk.cluster import RemoteCluster
    from chubaofs_tpu.testing.harness import ProcCluster
    from chubaofs_tpu.tools.cfsstat import scrape

    rnd = random.Random(seed)
    vol = "soakvol"
    cluster = ProcCluster(root, masters=1, metanodes=metanodes, datanodes=0)
    stats = {"seed": seed, "creates_acked": 0, "creates_failed": 0,
             "kills": []}
    try:
        mc = cluster.client_master()
        mc.create_volume(vol, cold=True)
        fs0 = cluster.fs(vol)
        dir_inos = []
        for d in range(dirs):
            dir_inos.append(fs0.mkdirs(f"/d{d}"))
            for i in range(seed_files):
                fs0.create(f"/d{d}/seed{i}")
        ledger: list[str] = [f"/d{d}/seed{i}" for d in range(dirs)
                             for i in range(seed_files)]
        ledger_lock = threading.Lock()
        stop = threading.Event()

        def creator(t: int):
            fs = cluster.fs(vol)
            i = 0
            # runs until phase 5 stops it (the migrate phase needs LIVE
            # load in the heartbeat windows); files_per_thread is the
            # per-thread runaway cap bounding the ledger on a slow host
            while not stop.is_set() and i < files_per_thread:
                path = f"/d{(t + i) % dirs}/t{t}_f{i}"
                i += 1
                try:
                    fs.create(path)
                except Exception:
                    # NOT acked: never counted against data loss (the
                    # run_soak contract); a metanode kill can legitimately
                    # fail an op mid-election past the retry window
                    with ledger_lock:
                        stats["creates_failed"] += 1
                    continue
                with ledger_lock:
                    ledger.append(path)
                    stats["creates_acked"] += 1

        threads = [threading.Thread(target=creator, args=(t,), daemon=True)
                   for t in range(creator_threads)]
        for t in threads:
            t.start()

        def mps():
            return sorted(mc.meta_partitions(vol), key=lambda m: m["start"])

        def frozen_reported() -> bool:
            return any(n.get("splits")
                       for n in mc.get_cluster()["nodes"]
                       if n["kind"] == "meta")

        def await_settled(want_parts: int, what: str):
            deadline = time.monotonic() + settle_timeout_s
            last_view, last_frozen = None, None
            while time.monotonic() < deadline:
                try:
                    view = mps()
                    last_view, last_frozen = view, frozen_reported()
                    if len(view) >= want_parts and not last_frozen \
                            and all(len(m["peers"]) == 3 for m in view):
                        return view
                except Exception:
                    pass  # master mid-failover: poll again
                time.sleep(0.5)
            # diagnose from the LAST GOOD poll: the master may still be
            # flaky here, and a fresh RPC raising would replace this
            # SoakFailure with an unrelated ConnectionError
            raise SoakFailure(
                f"meta-split soak seed {seed}: {what} did not settle in "
                f"{settle_timeout_s:.0f}s (view: {last_view}, "
                f"frozen={last_frozen})")

        def kill_and_respawn(name: str, phase: str,
                             delay_range: tuple) -> None:
            delay = rnd.uniform(*delay_range)
            time.sleep(delay)
            t_kill = time.time()
            cluster.kill(name)
            stats["kills"].append({"phase": phase, "node": name,
                                   "delay_s": round(delay, 3),
                                   "ts": t_kill})
            time.sleep(rnd.uniform(0.2, 0.6))
            nid = int(name.replace("metanode", ""))
            cluster.spawn(name, cluster.metanode_cfg(nid))

        # -- phase 3: kill mid-split --------------------------------------
        target = mps()[0]
        peers = list(target["peers"])
        victim_id = rnd.choice(peers)
        split_res: dict = {}

        def do_split():
            try:
                split_res["new_pid"] = mc.split_meta_partition(
                    vol, target["partition_id"])["new_pid"]
            except Exception as e:  # the resume sweep owns completion
                split_res["error"] = str(e)

        splitter = threading.Thread(target=do_split, daemon=True)
        splitter.start()
        kill_and_respawn(f"metanode{victim_id}", "split", kill_delay_s)
        splitter.join(timeout=60)
        # a TAIL split chains a cursor split: expect >= 3 partitions
        view = await_settled(3, "split")
        stats["partitions_after_split"] = len(view)

        # -- phase 4: kill mid-migration ----------------------------------
        # make one partition's load dominate so rebalance_meta picks it,
        # then race the membership dance against a kill of a SURVIVOR peer
        mig_res: dict = {}

        def do_migrate():
            try:
                mig_res["moved"] = mc.rebalance_meta(
                    factor=0.5, max_moves=1)["moved"]
            except Exception as e:
                mig_res["error"] = str(e)

        migrator = threading.Thread(target=do_migrate, daemon=True)
        migrator.start()
        view = mps()
        peers_now = {p for m in view for p in m["peers"]}
        victim2 = rnd.choice(sorted(peers_now))
        kill_and_respawn(f"metanode{victim2}", "migrate", kill_delay_s)
        migrator.join(timeout=90)
        stats["migrate_moved"] = mig_res.get("moved", 0)
        stats["migrate_error"] = mig_res.get("error", "")
        view = await_settled(len(view), "migration heal")
        # the killed-mid-dance call may have moved nothing (raced the kill
        # or an empty load window): the migration half must still be
        # EXERCISED, so retry on the healed cluster until a replica moves
        # (creators keep the leaders' load windows nonzero)
        last_loads = None
        for _ in range(20):
            if stats["migrate_moved"]:
                break
            time.sleep(1.5)  # a heartbeat window of load accumulates
            try:
                res = mc.rebalance_meta(factor=0.5, max_moves=1)
                stats["migrate_moved"] = res["moved"]
                last_loads = res.get("loads")
            except Exception:
                continue
        if not stats["migrate_moved"]:
            # diagnose from the LAST GOOD attempt: a fresh RPC here could
            # raise against a still-flaky master and replace this
            # SoakFailure with an unrelated transport error
            raise SoakFailure(
                f"meta-split soak seed {seed}: rebalance_meta never moved "
                f"a replica (loads {last_loads})")
        view = await_settled(len(view), "post-retry migration heal")

        # -- phase 5: verification ----------------------------------------
        stop.set()
        for t in threads:
            t.join(timeout=120)
        with ledger_lock:
            acked = list(ledger)

        # zero created-file loss + exactly-once dentries
        census = RemoteCluster(cluster.master_addrs).client(vol)
        by_dir: dict[int, list[str]] = {}
        for path in acked:
            d = int(path.split("/")[1][1:])
            by_dir.setdefault(d, []).append(path.rsplit("/", 1)[1])
        for d, names in by_dir.items():
            listed = census.readdir(f"/d{d}")
            if len(listed) != len(set(listed)):
                raise SoakFailure(
                    f"meta-split soak seed {seed}: duplicate dentries "
                    f"in /d{d}")
            missing = set(names) - set(listed)
            if missing:
                raise SoakFailure(
                    f"meta-split soak seed {seed}: {len(missing)} acked "
                    f"file(s) LOST in /d{d}: {sorted(missing)[:5]}")
            for name in names[:: max(1, len(names) // 20)]:
                census.stat(f"/d{d}/{name}")  # resolvable end to end

        # no double-owned inode: per-leader namespace dumps
        view = mps()
        handles = {n["node_id"]: RemoteMetaNode(n["addr"])
                   for n in mc.get_cluster()["nodes"]
                   if n["kind"] == "meta" and n["addr"]}
        owner: dict[int, int] = {}
        try:
            for m in view:
                pid = m["partition_id"]
                end = m["end"] if m["end"] > 0 else (1 << 63)
                dump = None
                for _ in range(10):  # a fresh election may be settling
                    for p in m["peers"]:
                        try:
                            dump = handles[p].dump_namespace(pid)
                            break
                        except Exception:
                            continue
                    if dump is not None:
                        break
                    time.sleep(0.5)
                if dump is None:
                    raise SoakFailure(
                        f"meta-split soak seed {seed}: no leader dump for "
                        f"partition {pid}")
                for inode in dump["inodes"]:
                    ino = inode.ino
                    if not (m["start"] <= ino < end):
                        raise SoakFailure(
                            f"meta-split soak seed {seed}: partition {pid} "
                            f"holds out-of-range ino {ino} "
                            f"[{m['start']},{end})")
                    if ino in owner:
                        raise SoakFailure(
                            f"meta-split soak seed {seed}: ino {ino} "
                            f"DOUBLE-OWNED by partitions {owner[ino]} "
                            f"and {pid}")
                    owner[ino] = pid
        finally:
            for h in handles.values():
                h.close()
        stats["inodes_census"] = len(owner)

        # the kill timeline: meta_split freeze -> commit -> complete and
        # meta_migrate add_peer/remove_peer on the master journal
        evs = _json.loads(scrape(cluster.master_addrs[0],
                                 "/events?n=2000"))["events"]
        split_phases = [e["detail"].get("phase") for e in evs
                        if e["type"] == "meta_split"]
        for phase in ("freeze", "commit", "complete"):
            if phase not in split_phases:
                raise SoakFailure(
                    f"meta-split soak seed {seed}: no meta_split "
                    f"phase={phase} event on the master journal "
                    f"(saw {split_phases})")
        if stats["migrate_moved"]:
            mig_phases = [e["detail"].get("phase") for e in evs
                          if e["type"] == "meta_migrate"]
            for phase in ("add_peer", "remove_peer"):
                if phase not in mig_phases:
                    raise SoakFailure(
                        f"meta-split soak seed {seed}: no meta_migrate "
                        f"phase={phase} event (saw {mig_phases})")
        timeline = [{"t": e["ts"], "type": e["type"], "entity": e["entity"],
                     "phase": e["detail"].get("phase", "")}
                    for e in evs if e["type"] in ("meta_split",
                                                  "meta_migrate")]
        if stats["creates_acked"] == 0:
            raise SoakFailure(
                f"meta-split soak seed {seed}: zero creates acked under "
                f"chaos — the soak tested nothing")
        return {"plan": "meta_split", "ok": True, "timeline": timeline,
                "partitions": len(view), **stats}
    finally:
        cluster.close()


@_capture_on_failure
def run_cache_soak(root: str, seed: int, rounds: int = 4, objects: int = 12,
                   obj_kb: int = 32, gets_per_round: int = 24,
                   invalidate_delay: float = 0.05, promote_hits: int = 4,
                   cache_mb: int = 8) -> dict:
    """Cache-plane correctness soak (ISSUE 12 satellite): read-after-
    overwrite and read-after-delete through the tiered read cache, with the
    `cache.invalidate` failpoint DELAYING every punch-out — the write-
    through ordering (invalidate completes before the backend delete fans
    out) must carry correctness even when invalidation is slow.

    Per seeded round: zipfian GETs crc-verified against a per-key ledger
    (a cache or hot-tier read serving stale/torn bytes fails the soak),
    overwrites (new location PUT + old location delete, ledger re-keyed),
    hard deletes (every post-delete GET must error, never serve cached
    bytes), and a background tick so the deleter, scrubber, and tier
    promoter/demoter all run against the same traffic. promote_hits is
    tuned low so blobs cross into (and fall out of) the Replica3 hot
    engine DURING the soak — the crc ledger then also proves tier
    migration never changes bytes."""
    import os as _os
    import zlib

    from chubaofs_tpu.blobstore.access import AccessError
    from chubaofs_tpu.blobstore.cache import BlobCache
    from chubaofs_tpu.blobstore.cluster import MiniCluster

    rnd = random.Random(seed)
    cache = BlobCache(_os.path.join(root, "cache"), mem_mb=cache_mb,
                      promote_hits=promote_hits)
    c = MiniCluster(root, n_nodes=6, cache=cache)
    stats = {"gets": 0, "overwrites": 0, "deletes": 0, "delete_errors": 0}
    fp.arm("cache.invalidate", f"delay({invalidate_delay})")
    try:
        ledger: dict[int, tuple] = {}  # key -> (loc, crc)
        for k in range(objects):
            data = rnd.randbytes(obj_kb * 1024)
            ledger[k] = (c.access.put(data), zlib.crc32(data))
        weights = [1.0 / (r + 1) ** 1.1 for r in range(objects)]
        deleted: dict[int, object] = {}  # key -> dead location
        for rd in range(rounds):
            keys = sorted(ledger)
            for k in rnd.choices(keys, weights=weights[: len(keys)],
                                 k=gets_per_round):
                loc, crc = ledger[k]
                got = c.access.get(loc)
                stats["gets"] += 1
                if zlib.crc32(got) != crc:
                    raise SoakFailure(
                        f"cache soak seed {seed} round {rd}: key {k} served "
                        f"stale/corrupt bytes (crc mismatch)")
            # overwrite: the new location must serve the NEW bytes from its
            # first read — its fresh bids can never alias a cached entry
            for k in rnd.sample(sorted(ledger), k=min(2, len(ledger))):
                old_loc, _ = ledger[k]
                data = rnd.randbytes(obj_kb * 1024)
                new_loc = c.access.put(data)
                c.access.delete(old_loc)  # delayed punch-out via failpoint
                ledger[k] = (new_loc, zlib.crc32(data))
                stats["overwrites"] += 1
                if zlib.crc32(c.access.get(new_loc)) != ledger[k][1]:
                    raise SoakFailure(
                        f"cache soak seed {seed} round {rd}: key {k} read "
                        f"stale bytes immediately after overwrite")
            # hard delete: after the deleter punches the shards, the old
            # location must ERROR — cached bytes must not outlive the blob
            if len(ledger) > objects // 2:
                k = rnd.choice(sorted(ledger))
                loc, _ = ledger.pop(k)
                c.access.delete(loc)
                deleted[k] = loc
                stats["deletes"] += 1
            c.run_background_once()
            c.run_background_once()  # deleter + tier sweep both settle
            for k, loc in deleted.items():
                try:
                    c.access.get(loc)
                    raise SoakFailure(
                        f"cache soak seed {seed} round {rd}: deleted key {k} "
                        f"still readable (stale cache/tier copy)")
                except AccessError:
                    stats["delete_errors"] += 1
        return {
            "plan": "cache", "seed": seed, "ok": True, "rounds": rounds,
            "promoted_peak": len(c.cm.hot_blobs()),
            "cache_stats": cache.stats(), **stats,
        }
    finally:
        fp.disarm("cache.invalidate")
        c.close()
