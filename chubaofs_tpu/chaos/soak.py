"""Chaos soak — PUT -> fault -> degraded GET -> heal -> converge, seeded.

The acceptance cycle behind `tools/chaos_soak.py` and tests/test_chaos.py:
a MiniCluster takes writes, a ChaosScheduler injects a fault plan on the
virtual timeline, every ACKED blob must read back byte-identical in every
phase (degraded included) with bounded tail latency, and once the faults
lift the repair planes must converge to a quiet inspector sweep with zero
data loss. Everything is driven off seeded RNGs, so the injection event
log is reproducible run-over-run.

PUTs issued while a fault window is ACTIVE may be rejected by the put
quorum (EC quorums tolerate one lost unit; a wedged two-disk node can
legitimately hold two units of a stripe). A rejected PUT is correct
degraded behavior — the data was never acked — and the soak retries it
until it lands; an unacked blob is never counted against data loss. A
rejection while NO fault is active fails the soak.
"""

from __future__ import annotations

import random
import time

from chubaofs_tpu.chaos import failpoints as fp
from chubaofs_tpu.chaos.scheduler import ChaosScheduler, FaultPlan, builtin_plan

SIZES = [8_000, 120_000, 700_000, 2_000_000]


class SoakFailure(AssertionError):
    pass


def run_soak(root: str, plan: FaultPlan | str, seed: int, rounds: int = 6,
             puts_per_round: int = 2, n_nodes: int = 9, disks_per_node: int = 2,
             sizes: list[int] | None = None, read_deadline: float = 0.5,
             write_deadline: float = 4.0, converge_sweeps: int = 12) -> dict:
    """One full soak cycle; returns {events, puts, gets, max_get_s, ok, ...}.
    Raises SoakFailure on data loss, latency-bound violation, or a cluster
    that will not converge after the faults lift."""
    import numpy as np

    from chubaofs_tpu.blobstore.access import Access, AccessError
    from chubaofs_tpu.blobstore.cluster import MiniCluster

    if isinstance(plan, str):
        plan = builtin_plan(plan, steps=rounds)
    sizes = sizes or SIZES
    rnd = random.Random(seed)          # op schedule
    rng = np.random.default_rng(seed)  # payload bytes
    c = MiniCluster(root, n_nodes=n_nodes, disks_per_node=disks_per_node)
    # soak-tuned gateway: a wedged node must cost fractions of a second, not
    # the production 3s/10s windows, and hung reads pin pool workers until
    # the fault lifts — size the pools for that (the displaced stock gateway
    # gives up its executors first: MiniCluster.close only sees the new one)
    c.access.close()
    c.access = Access(c.cm, c.proxy, c.nodes, codec=c.codec, max_workers=64,
                      read_deadline=read_deadline,
                      write_deadline=write_deadline)
    sched = ChaosScheduler(c, plan, seed=seed + 1)
    live = sched.blobs  # blob idx -> (Location, payload); shared by bitrot
    # degraded GETs must finish inside the hedged-gather budget even with
    # wedged replicas; generous margin for CI thread scheduling
    get_bound = write_deadline + read_deadline + 5.0
    stats = {"puts": 0, "puts_rejected": 0, "gets": 0, "max_get_s": 0.0}
    next_id = 0
    pending: list[bytes] = []  # payloads rejected under faults, to retry
    try:
        for _ in range(rounds):
            for _ in range(puts_per_round):
                size = rnd.choice(sizes)
                pending.append(
                    rng.integers(0, 256, size, dtype=np.uint8).tobytes())
            retry = []
            for data in pending:
                try:
                    live[next_id] = (c.access.put(data), data)
                    next_id += 1
                    stats["puts"] += 1
                except AccessError:
                    if sched.quiesced():
                        raise SoakFailure(
                            f"t={sched.vtime}: PUT rejected with no fault "
                            f"active under plan {plan.name} seed {seed}")
                    stats["puts_rejected"] += 1
                    retry.append(data)  # never acked: retry after heal
            pending = retry

            sched.step()

            # pump the repair planes between faults
            for _ in range(4):
                s = c.run_background_once()
                if (s["repair_msgs"] == 0 and s["disk_tasks"] == 0
                        and s["tasks_ran"] == 0):
                    break

            # THE invariant: every acked blob reads byte-identical, degraded
            # or healed, inside the latency bound
            for idx, (loc, data) in live.items():
                t0 = time.monotonic()
                got = c.access.get(loc)
                dt = time.monotonic() - t0
                stats["gets"] += 1
                stats["max_get_s"] = max(stats["max_get_s"], dt)
                if got != data:
                    raise SoakFailure(
                        f"t={sched.vtime}: blob {idx} corrupted under "
                        f"plan {plan.name} seed {seed}")
                if dt > get_bound:
                    raise SoakFailure(
                        f"t={sched.vtime}: blob {idx} GET took {dt:.2f}s "
                        f"(bound {get_bound:.2f}s) under plan {plan.name}")

        # lift anything still active, land the retries, then CONVERGE:
        # repair planes drain and a full inspector sweep goes quiet
        sched.close()
        for data in pending:
            live[next_id] = (c.access.put(data), data)
            next_id += 1
            stats["puts"] += 1
        converged = False
        for _ in range(converge_sweeps):
            c.run_background_once()
            if c.scheduler.inspect_volumes(max_volumes=1000) == 0:
                converged = True
                break
        if not converged:
            raise SoakFailure(
                f"plan {plan.name} seed {seed}: inspector never went quiet "
                f"after faults lifted")
        for idx, (loc, data) in live.items():
            if c.access.get(loc) != data:
                raise SoakFailure(
                    f"post-heal: blob {idx} lost under plan {plan.name}")
        # how often each injection actually bit (anti-vacuous-green signal:
        # a soak whose faults never fire has tested nothing)
        fired = {n: fp.fired(n) for n in
                 ("access.read_shard", "access.write_shard", "raft.send")}
        return {"plan": plan.name, "seed": seed, "events": list(sched.events),
                "ok": True, "fired": {k: v for k, v in fired.items() if v},
                **stats}
    finally:
        sched.close()
        fp.reset()  # never leak armings into the next soak/test
        c.close()
