"""Direct on-disk fault injectors (bypassing every API layer).

These model media faults — the bytes under the service change, the service
is not told. The CRC framing / scrub planes are what must notice.
"""

from __future__ import annotations

import os


def corrupt_shard_on_disk(node, vuid: int, bid: int, flip_at: int = 10) -> None:
    """Flip one payload byte inside a blobnode chunk's crc32block framing,
    bypassing the API (the shared bit-rot injector for the hygiene, soak and
    chaos suites — byte-offset-sensitive, keep the one copy)."""
    from chubaofs_tpu.blobstore.blobnode import HEADER_LEN

    chunk = node._chunk(vuid)
    meta = chunk.shards[bid]
    with open(chunk._data_path, "r+b") as f:
        f.seek(meta.offset + HEADER_LEN + 4 + flip_at)  # into block 0 payload
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
