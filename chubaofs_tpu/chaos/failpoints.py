"""Failpoint registry — named fault hooks with per-name actions.

Reference analog: the `github.com/pingcap/failpoint` pattern CubeFS uses in
its tests (mock-injected error codes, SURVEY §4) plus freebsd's
`fail_point(9)` action grammar. A call site is one line:

    chaos.failpoint("blobnode.get_shard", node=self.node_id)

and stays a near-free no-op (one empty-dict lookup) until a test or the
`CFS_FAILPOINTS` env spec arms the name with an action:

    off                 disarm
    error[(msg)]        raise FailpointError (a ConnectionError, so IO call
                        sites route it down their existing failure paths)
    drop                raise Dropped (fire-and-forget sites catch + skip)
    delay(seconds)      sleep, then proceed
    hang[(max_s)]       block until release() (bounded by max_s, default 300)
    corrupt             flip one payload byte (corrupt_bytes call sites)
    return(json)        hand the call site a value override

Each action takes optional suffixes: `@p` fires with probability p from a
per-arming seeded RNG (deterministic given the call sequence), `*n` fires
for the first n matching hits only, `#node` restricts to one node id.
Example spec: `raft.send=drop@0.1;blobnode.get_shard=hang*5#2` (suffix
order matters: `@prob`, then `*times`, then `#node`).
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import zlib

_HANG_MAX_S = 300.0  # safety net: a forgotten release() must not wedge CI


class FailpointError(ConnectionError):
    """Injected failure. Subclasses ConnectionError so IO call sites that
    already tolerate connection loss route the injection down their real
    failure paths without chaos-specific handling."""

    def __init__(self, name: str, msg: str = ""):
        super().__init__(f"failpoint {name}: {msg or 'injected error'}")
        self.name = name


class Dropped(FailpointError):
    """Injected message loss — fire-and-forget sites catch this and skip."""


class _Arming:
    __slots__ = ("kind", "arg", "prob", "times", "node", "hits", "fired",
                 "rng", "gate")

    def __init__(self, kind: str, arg=None, prob: float = 1.0,
                 times: int | None = None, node: int | None = None,
                 seed: int | None = None, name: str = ""):
        self.kind = kind
        self.arg = arg
        self.prob = prob
        self.times = times
        self.node = node
        self.hits = 0   # call sites that matched this arming
        self.fired = 0  # times the action actually triggered
        # deterministic by default: the name itself seeds the RNG, so a
        # given call sequence makes identical probability decisions run-
        # over-run (the chaos scheduler's reproducibility contract)
        self.rng = random.Random(zlib.adler32(name.encode())
                                 if seed is None else seed)
        self.gate = threading.Event()  # hang-until-released

    def describe(self) -> str:
        s = self.kind
        if self.arg is not None:
            s += f"({self.arg})"
        if self.prob < 1.0:
            s += f"@{self.prob}"
        if self.times is not None:
            s += f"*{self.times}"
        if self.node is not None:
            s += f"#{self.node}"
        return s


# name -> [armings]. The EMPTY dict is the entire unarmed fast path:
# failpoint() does one .get() against it and returns.
_ARMS: dict[str, list[_Arming]] = {}
_LOCK = threading.Lock()
# cumulative per-name counters, surviving disarm (a lifted fault's evidence
# must outlive the fault); cleared only by reset()
_TOTAL_HITS: dict[str, int] = {}
_TOTAL_FIRED: dict[str, int] = {}


def failpoint(name: str, node: int | None = None):
    """Evaluate a failpoint site. Returns None (proceed), raises
    FailpointError/Dropped, sleeps, hangs, or returns the matched _Arming
    for `corrupt`/`return` kinds (the call site interprets those)."""
    arms = _ARMS.get(name)
    if arms is None:
        return None
    return _fire(name, arms, node)


def _fire(name: str, arms: list[_Arming], node: int | None):
    act = None
    with _LOCK:
        for a in arms:
            if a.node is not None and a.node != node:
                continue
            a.hits += 1
            _TOTAL_HITS[name] = _TOTAL_HITS.get(name, 0) + 1
            if a.times is not None and a.fired >= a.times:
                continue
            if a.prob < 1.0 and a.rng.random() >= a.prob:
                continue
            a.fired += 1
            _TOTAL_FIRED[name] = _TOTAL_FIRED.get(name, 0) + 1
            act = a
            break
    if act is None:
        return None
    kind = act.kind
    if kind == "error":
        raise FailpointError(name, str(act.arg or ""))
    if kind == "drop":
        raise Dropped(name, "dropped")
    if kind == "delay":
        time.sleep(float(act.arg or 0.0))
        return None
    if kind == "hang":
        act.gate.wait(timeout=float(act.arg) if act.arg else _HANG_MAX_S)
        return None
    return act  # corrupt / return: the call site interprets


def corrupt_bytes(name: str, data: bytes, node: int | None = None) -> bytes:
    """Payload-corruption site: returns `data` with one byte flipped when
    the name is armed with `corrupt` (deterministic offset from the
    arming's RNG), `data` unchanged otherwise. Other kinds (error/delay/
    hang/drop) fire exactly as at a plain failpoint."""
    arms = _ARMS.get(name)
    if arms is None:
        return data
    act = _fire(name, arms, node)
    if act is None or act.kind != "corrupt" or not data:
        return data
    with _LOCK:
        pos = act.rng.randrange(len(data))
    out = bytearray(data)
    out[pos] ^= 0xFF
    return bytes(out)


# -- arming control ------------------------------------------------------------

_KINDS = {"off", "error", "drop", "delay", "hang", "corrupt", "return"}


def arm(name: str, action: str, node: int | None = None,
        times: int | None = None, prob: float | None = None,
        seed: int | None = None) -> None:
    """Arm `name` with an action spec (e.g. "delay(0.5)", "drop@0.1",
    "hang", "error(wedged)*3"). Explicit kwargs override spec suffixes.
    Arming the same name again stacks (first matching arming wins), so a
    per-node arming can coexist with a global one."""
    kind, arg, sprob, stimes, snode = _parse_action(action)
    if kind == "off":
        disarm(name, node=node if node is not None else snode)
        return
    a = _Arming(kind, arg=arg,
                prob=prob if prob is not None else sprob,
                times=times if times is not None else stimes,
                node=node if node is not None else snode,
                seed=seed, name=name)
    with _LOCK:
        _ARMS.setdefault(name, []).append(a)
    # arming is a state transition the forensics timeline needs: an injected
    # fault and its downstream detections then sort onto ONE timeline.
    # Lazy import keeps the unarmed failpoint() fast path untouched.
    from chubaofs_tpu.utils import events

    events.emit("failpoint_armed", events.SEV_WARNING, entity=name,
                detail={"name": name, "action": a.describe()})


def disarm(name: str | None = None, node: int | None = None) -> None:
    """Disarm one name (optionally only its per-`node` armings) or, with no
    name, everything. Hung waiters of removed armings are released."""
    removed: list[str] = []
    with _LOCK:
        names = [name] if name is not None else list(_ARMS)
        for n in names:
            arms = _ARMS.get(n)
            if arms is None:
                continue
            keep = [] if node is None else [a for a in arms if a.node != node]
            for a in arms:
                if a not in keep:
                    a.gate.set()
            if len(keep) < len(arms):
                removed.append(n)
            if keep:
                _ARMS[n] = keep
            else:
                _ARMS.pop(n, None)
    if removed:
        from chubaofs_tpu.utils import events

        for n in removed:
            events.emit("failpoint_disarmed", entity=n,
                        detail={"name": n,
                                **({"node": node} if node is not None
                                   else {})})


def release(name: str | None = None) -> None:
    """Release hang-until-released waiters (the arming stays armed; later
    hits pass straight through the opened gate)."""
    with _LOCK:
        for n, arms in _ARMS.items():
            if name is None or n == name:
                for a in arms:
                    a.gate.set()


def hits(name: str) -> int:
    """Call-site evaluations that matched an arming of `name` (including
    budget/probability misses) since the last reset() — cumulative across
    disarms, so a lifted fault's evidence survives its lift."""
    with _LOCK:
        return _TOTAL_HITS.get(name, 0)


def fired(name: str) -> int:
    """Times an action of `name` actually triggered since the last reset()."""
    with _LOCK:
        return _TOTAL_FIRED.get(name, 0)


def armed() -> dict[str, list[str]]:
    with _LOCK:
        return {n: [a.describe() for a in arms] for n, arms in _ARMS.items()}


def reset() -> None:
    """Disarm everything, release all waiters, zero counters (teardown)."""
    disarm()
    with _LOCK:
        _TOTAL_HITS.clear()
        _TOTAL_FIRED.clear()


# -- spec grammar --------------------------------------------------------------


def _parse_action(spec: str):
    """`kind[(arg)][@prob][*times][#node]` -> (kind, arg, prob, times, node)."""
    s = spec.strip()
    node = times = None
    prob = 1.0
    if "#" in s:
        s, _, tail = s.rpartition("#")
        node = int(tail)
    if "*" in s:
        s, _, tail = s.rpartition("*")
        times = int(tail)
    if "@" in s:
        s, _, tail = s.rpartition("@")
        prob = float(tail)
        if not 0.0 <= prob <= 1.0:
            raise ValueError(f"failpoint probability {prob} outside [0, 1]")
    arg = None
    if "(" in s:
        if not s.endswith(")"):
            raise ValueError(f"unterminated action args in {spec!r}")
        s, _, inner = s.partition("(")
        arg = inner[:-1]
    kind = s.strip()
    if kind not in _KINDS:
        raise ValueError(f"unknown failpoint action {kind!r} in {spec!r}")
    if kind == "delay":
        arg = float(arg if arg is not None else 0.0)
    elif kind == "hang" and arg is not None:
        arg = float(arg)
    elif kind == "return":
        arg = json.loads(arg) if arg else None
    return kind, arg, prob, times, node


def load_spec(spec: str) -> int:
    """Parse a `name=action[;name=action...]` spec and arm every entry;
    returns the number of entries armed."""
    n = 0
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if "=" not in entry:
            raise ValueError(f"failpoint entry {entry!r} has no '=' action")
        name, _, action = entry.partition("=")
        arm(name.strip(), action)
        n += 1
    return n


def load_env(env_var: str = "CFS_FAILPOINTS") -> int:
    """Arm the spec in `env_var` (daemon subprocesses inherit harness
    faults this way). Silent no-op when unset."""
    spec = os.environ.get(env_var, "")
    return load_spec(spec) if spec else 0
