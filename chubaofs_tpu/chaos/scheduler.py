"""Seeded chaos scheduler — fault plans on a virtual timeline.

Drives injections against a live MiniCluster deterministically: given the
same (seed, plan) the scheduler makes identical target choices and emits an
identical event log, run over run — the log never contains wall-clock
times, realized volume/bid ids, process pids or anything else the thread
scheduler could perturb; only virtual time plus the RNG-chosen coordinates.

Fault kinds (all lift automatically after `duration` virtual steps):

  node_wedge     shard IO to the node hangs silently (no error, no RST) —
                 the degraded-GET / punish-window paths must carry the load
  slow_disk      every shard IO on the node pays a delay
  link_drop      shard IO to the node fails fast with probability `arg`
                 (flapping link); also arms raft.send drops for daemons
  shard_bitrot   one byte of one live shard flips on disk (instantaneous;
                 nothing to lift — the scrub/repair plane must heal it)
  crash_restart  the node's in-process engine is closed and rebuilt from
                 its disks at lift time (process crash + restart)
  node_kill      the node's engine is closed and REMOVED from the routing
                 dict, permanently (a dead host). Nothing lifts: its
                 heartbeats stop, the clustermgr expiry must mark its disks
                 broken, and the repair plane must rebuild every affected
                 stripe onto the survivors

node_wedge/slow_disk/link_drop arm the ACCESS-layer call sites
(`access.read_shard` / `access.write_shard`), not the blobnode ones: the
MiniCluster's repair planes call blobnode engines in-process on the soak
thread, and a blobnode-level hang would wedge the very loop that has to
lift the fault. Daemon-cluster chaos wedges the blobnode sites directly
via CFS_FAILPOINTS instead.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

from chubaofs_tpu.chaos import failpoints as fp


@dataclass
class Fault:
    kind: str
    at: int                  # virtual step of injection
    duration: int = 1        # steps until lifted (ignored by shard_bitrot)
    target: int | None = None  # node id; None = scheduler picks (seeded)
    arg: float | None = None   # kind-specific knob (delay s / drop prob)


@dataclass
class FaultPlan:
    name: str
    faults: list[Fault] = field(default_factory=list)
    steps: int = 6


def builtin_plan(name: str, steps: int = 6) -> FaultPlan:
    """The named plans the soak acceptance runs: one mid-run fault window
    per plan, lifted with steps to spare so convergence is observable."""
    mid, dur = 1, max(2, steps // 2)
    plans = {
        "node_wedge": [Fault("node_wedge", at=mid, duration=dur)],
        "slow_disk": [Fault("slow_disk", at=mid, duration=dur, arg=0.15)],
        "link_drop": [Fault("link_drop", at=mid, duration=dur, arg=0.7)],
        "shard_bitrot": [Fault("shard_bitrot", at=mid),
                         Fault("shard_bitrot", at=mid + 1),
                         Fault("shard_bitrot", at=mid + 2)],
        "crash_restart": [Fault("crash_restart", at=mid, duration=dur)],
        "node_kill": [Fault("node_kill", at=mid)],
    }
    if name not in plans:
        raise ValueError(f"unknown plan {name!r}; have {sorted(plans)}")
    return FaultPlan(name=name, faults=plans[name], steps=steps)


class ChaosScheduler:
    """Applies one FaultPlan to a MiniCluster as virtual time advances.

    The soak harness calls `step()` once per round; faults whose `at`
    equals the current step inject, faults whose window expired lift.
    `events` is the reproducible log. `blobs` maps blob index ->
    (Location, payload) and feeds shard_bitrot target choice — the
    CHOICE is logged as (blob index, unit index), never the realized
    vid/vuid, which thread timing could perturb."""

    def __init__(self, cluster, plan: FaultPlan, seed: int):
        self.cluster = cluster
        self.plan = plan
        self.rng = random.Random(seed)
        self.vtime = 0
        self.events: list[dict] = []
        self.blobs: dict[int, tuple] = {}  # soak harness registers live blobs
        self._active: list[tuple[Fault, int, int]] = []  # (fault, node, lift_at)
        self._crashed: dict[int, list[str]] = {}  # node -> disk roots

    # -- timeline -------------------------------------------------------------

    def step(self) -> list[dict]:
        """Advance one virtual step: lift expired faults, inject due ones.
        Returns the events this step appended."""
        before = len(self.events)
        for fault, node, lift_at in list(self._active):
            if self.vtime >= lift_at:
                self._lift(fault, node)
                self._active.remove((fault, node, lift_at))
        for fault in self.plan.faults:
            if fault.at == self.vtime:
                self._inject(fault)
        self.vtime += 1
        return self.events[before:]

    def close(self) -> None:
        """Lift everything still active (test teardown / end of soak)."""
        for fault, node, _ in self._active:
            self._lift(fault, node)
        self._active.clear()

    def quiesced(self) -> bool:
        return not self._active

    def _log(self, event: str, fault: Fault, **details) -> None:
        self.events.append({"t": self.vtime, "event": event,
                            "fault": fault.kind, **details})
        # mirror the plan step onto the cluster event timeline so the
        # injected fault sorts against its detections/reactions in
        # `cfs-events` output. The SEEDED log above stays the determinism
        # contract; the journal record adds wall/mono stamps for the merge.
        # A 'skip' step injected NOTHING — it stays in the seeded log only,
        # never as a chaos_inject record a timeline consumer could anchor on.
        if event not in ("inject", "lift"):
            return
        from chubaofs_tpu.utils import events as ev

        ev.emit("chaos_lift" if event == "lift" else "chaos_inject",
                ev.SEV_INFO if event == "lift" else ev.SEV_WARNING,
                entity=fault.kind,
                detail={"step": event, "t": self.vtime,
                        "plan": self.plan.name, **details})

    def _pick_node(self, fault: Fault) -> int:
        if fault.target is not None:
            return fault.target
        return self.rng.choice(sorted(self.cluster.nodes))

    # -- inject / lift --------------------------------------------------------

    def _inject(self, fault: Fault) -> None:
        kind = fault.kind
        if kind == "shard_bitrot":
            self._inject_bitrot(fault)
            return
        node = self._pick_node(fault)
        if kind == "node_wedge":
            # bounded hang as a backstop; the lift path releases much sooner
            fp.arm("access.read_shard", "hang(45)", node=node)
            fp.arm("access.write_shard", "hang(45)", node=node)
        elif kind == "slow_disk":
            d = fault.arg if fault.arg is not None else 0.15
            fp.arm("access.read_shard", f"delay({d})", node=node)
            fp.arm("access.write_shard", f"delay({d})", node=node)
        elif kind == "link_drop":
            p = fault.arg if fault.arg is not None else 0.7
            fp.arm("access.read_shard", "error(link down)", node=node, prob=p)
            fp.arm("access.write_shard", "error(link down)", node=node, prob=p)
            fp.arm("raft.send", "drop", node=node, prob=p)
        elif kind == "crash_restart":
            self._crash(node)
        elif kind == "node_kill":
            self._kill(node)
            self._log("inject", fault, node=node)
            return  # permanent: nothing to lift, never enters _active
        else:
            raise ValueError(f"unknown fault kind {kind!r}")
        self._log("inject", fault, node=node)
        self._active.append((fault, node, self.vtime + max(1, fault.duration)))

    def _lift(self, fault: Fault, node: int) -> None:
        if fault.kind in ("node_wedge", "slow_disk", "link_drop"):
            fp.disarm("access.read_shard", node=node)
            fp.disarm("access.write_shard", node=node)
            if fault.kind == "link_drop":
                fp.disarm("raft.send", node=node)
        elif fault.kind == "crash_restart":
            self._restart(node)
        # a lifted fault is a CONFIRMED recovery: drop the punish windows so
        # writes trust the healed node again (clear_punishments contract)
        try:
            self.cluster.access.clear_punishments()
        except Exception:
            pass
        self._log("lift", fault, node=node)

    def _inject_bitrot(self, fault: Fault) -> None:
        from chubaofs_tpu.chaos.inject import corrupt_shard_on_disk

        if not self.blobs:
            self._log("skip", fault, reason="no live blobs")
            return
        blob_idx = self.rng.choice(sorted(self.blobs))
        loc, _ = self.blobs[blob_idx]
        blob = loc.blobs[0]
        vol = self.cluster.cm.get_volume(blob.vid)
        unit_idx = self.rng.randrange(len(vol.units))
        unit = vol.units[unit_idx]
        try:
            corrupt_shard_on_disk(self.cluster.nodes[unit.node_id],
                                  unit.vuid, blob.bid)
            outcome = "flipped"
        except Exception:
            # the shard may not be materialized on that unit (failed write,
            # mid-migration): the plan's CHOICE is still logged identically
            outcome = "absent"
        self._log("inject", fault, blob=blob_idx, unit=unit_idx,
                  outcome=outcome)

    def _crash(self, node: int) -> None:
        eng = self.cluster.nodes[node]
        roots = [d.root for d in eng.disks.values()]
        self._crashed[node] = roots
        try:
            eng.close()
        except Exception:
            pass
        # a crashed process answers nothing: error (not hang) like a RST
        fp.arm("access.read_shard", "error(crashed)", node=node)
        fp.arm("access.write_shard", "error(crashed)", node=node)

    def _kill(self, node: int) -> None:
        """Permanent kill: close the engine and REMOVE it from the routing
        dict. No failpoints needed — reads see an unknown node, writes fail
        and punish, and the stopped heartbeats are exactly the detection
        signal the repair plane has to catch."""
        eng = self.cluster.nodes.pop(node, None)
        if eng is not None:
            try:
                eng.close()
            except Exception:
                pass

    def _restart(self, node: int) -> None:
        from chubaofs_tpu.blobstore.blobnode import BlobNode

        roots = self._crashed.pop(node, None)
        fp.disarm("access.read_shard", node=node)
        fp.disarm("access.write_shard", node=node)
        if roots is None:
            return
        # rebuilt from its superblock + metadb, exactly a process restart;
        # the shared nodes dict makes access/scheduler see the new engine
        self.cluster.nodes[node] = BlobNode(node_id=node, disk_roots=roots)
