"""Test harnesses (blobstore/testing + docker/ compose-scripts analog)."""
