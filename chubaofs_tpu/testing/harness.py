"""Subprocess cluster harness — the docker-compose bring-up as a library.

Reference counterpart: docker/docker-compose.yml + docker/run_docker.sh
(3 masters, 4 metanodes, 4 datanodes, objectnode, console; SURVEY §4) and
blobstore/testing's reusable fixtures. This spins the same topology as REAL
OS processes via the cmd entry (`python -m chubaofs_tpu.cmd`), waits for
registration, and hands back typed clients. Every control and data path
crosses real sockets and process boundaries — the strongest non-TPU-specific
integration surface the repo has.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ProcCluster:
    """A full cluster of daemon subprocesses."""

    @classmethod
    def shell(cls, root: str, env: dict | None = None,
              jax_platform: str | None = None) -> "ProcCluster":
        """An empty harness (spawn/await/close machinery, no daemons) for
        tests that compose their own role mix."""
        self = cls.__new__(cls)
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.env = dict(os.environ)
        self.env["PYTHONPATH"] = REPO + os.pathsep + self.env.get("PYTHONPATH", "")
        self.env.setdefault("JAX_PLATFORMS", "cpu")
        self.env.update(env or {})
        self.jax_platform = jax_platform
        self.procs = {}
        return self

    def __init__(self, root: str, masters: int = 3, metanodes: int = 3,
                 datanodes: int = 3, blobstore: bool = False,
                 objectnode: bool = False, env: dict | None = None,
                 master_extra: dict | None = None,
                 jax_platform: str | None = None):
        shell = ProcCluster.shell(root, env, jax_platform)
        self.root = shell.root
        self.env = shell.env
        self.jax_platform = shell.jax_platform
        self.procs: dict[str, subprocess.Popen] = shell.procs
        try:
            self._boot(masters, metanodes, datanodes, blobstore, objectnode,
                       master_extra)
        except BaseException:
            # partial boot must not orphan daemons: the constructor is also an
            # OPERATOR entry (tools/localcluster), and a leader-election or
            # port-bind failure here would otherwise leak every spawned proc
            self.close()
            raise

    def _boot(self, masters, metanodes, datanodes, blobstore, objectnode,
              master_extra):
        root = self.root
        # masters need static raft + api ports so peers can dial each other
        raft_ports = {i: free_port() for i in range(1, masters + 1)}
        api_ports = {i: free_port() for i in range(1, masters + 1)}
        raft_peers = {str(i): f"127.0.0.1:{raft_ports[i]}" for i in raft_ports}
        peer_apis = {str(i): f"127.0.0.1:{api_ports[i]}" for i in api_ports}
        self.master_addrs = list(peer_apis.values())
        for i in range(1, masters + 1):
            self.spawn(f"master{i}", {
                "role": "master", "id": i, "raftPeers": raft_peers,
                "peerApis": peer_apis, "listen": peer_apis[str(i)],
                "walDir": os.path.join(root, f"m{i}"),
                **(master_extra or {}),
            })
        self._await_leader()

        # the blobstore goes first so metanode configs carry the access
        # address (their orphan-purge hook needs it for cold extents)
        self.access_addr = None
        if blobstore:
            port = free_port()
            self.access_addr = f"127.0.0.1:{port}"
            self.spawn("blobstore", {
                "role": "blobstore", "root": os.path.join(root, "blob"),
                "listen": self.access_addr, "nodes": 6, "disksPerNode": 2,
            })

        meta_base = masters + 1
        for k in range(metanodes):
            i = meta_base + k
            self.spawn(f"metanode{i}", self.metanode_cfg(i))
        data_base = 100
        for k in range(datanodes):
            i = data_base + 1 + k
            self.spawn(f"datanode{i}", self.datanode_cfg(i))
        self.s3_addr = None
        if objectnode:
            port = free_port()
            self.s3_addr = f"127.0.0.1:{port}"
            cfg = {"role": "objectnode", "masterAddrs": self.master_addrs,
                   "listen": self.s3_addr}
            if self.access_addr:
                cfg["accessAddrs"] = [self.access_addr]
            self.spawn("objectnode", cfg)

        self.await_nodes(metanodes + datanodes)
        # blobstore/objectnode bind after slow imports; wait for the sockets
        for addr in (self.access_addr, self.s3_addr):
            if addr:
                self._await_listen(addr)

    # -- process management ----------------------------------------------------

    def metanode_cfg(self, i: int) -> dict:
        cfg = {"role": "metanode", "id": i, "masterAddrs": self.master_addrs,
               "walDir": os.path.join(self.root, f"mn{i}")}
        if self.access_addr:
            cfg["accessAddrs"] = [self.access_addr]
        return cfg

    def datanode_cfg(self, i: int) -> dict:
        return {"role": "datanode", "id": i, "masterAddrs": self.master_addrs,
                "disks": [os.path.join(self.root, f"dn{i}", "d0"),
                          os.path.join(self.root, f"dn{i}", "d1")],
                "walDir": os.path.join(self.root, f"dn{i}", "wal")}

    def spawn(self, name: str, cfg: dict) -> subprocess.Popen:
        # the platform request rides the CONFIG, not the env: a sitecustomize-
        # registered accelerator plugin rewrites JAX_PLATFORMS before main()
        # runs, so env-only requests are silently lost (test daemons must run
        # on CPU, never on a proxied accelerator's health)
        cfg.setdefault("jaxPlatform", self.jax_platform or "cpu")
        path = os.path.join(self.root, f"{name}.json")
        with open(path, "w") as f:
            json.dump(cfg, f)
        log = open(os.path.join(self.root, f"{name}.log"), "w")
        p = subprocess.Popen(
            [sys.executable, "-m", "chubaofs_tpu.cmd", "-c", path],
            stdout=log, stderr=subprocess.STDOUT, env=self.env)
        self.procs[name] = p
        return p

    def kill(self, name: str, sig=None) -> None:
        """SIGKILL (default) a daemon — the fault-injection hammer."""
        import signal as _signal

        p = self.procs.pop(name, None)
        if p is None:
            return
        p.send_signal(sig or _signal.SIGKILL)
        p.wait(timeout=10)

    def close(self):
        for p in self.procs.values():
            p.terminate()
        for p in self.procs.values():
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        self.procs.clear()

    # -- boot-line introspection ----------------------------------------------

    def boot_info(self, name: str, timeout: float = 60.0) -> dict:
        """The daemon's boot JSON line, parsed off its captured stdout log
        (cmd.main prints it as the stdout protocol). This is how a harness
        learns ephemeral side-door ports (statsListen's /metrics address).
        stderr shares the log file, so scan for the first line that parses
        as the boot record rather than trusting line one."""
        path = os.path.join(self.root, f"{name}.log")
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if not line.startswith("{"):
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if isinstance(rec, dict) and "role" in rec:
                            return rec
            except OSError:
                pass
            time.sleep(0.1)
        raise TimeoutError(f"{name} printed no boot line")

    def stats_addrs(self, timeout: float = 60.0) -> list[str]:
        """Every running metanode/datanode/objectnode's /metrics side-door
        address — the extra scrape targets a console rollup needs beyond
        the masters and the blobstore gateway. The objectnode side-door is
        where the QoS plane's per-tenant metrics and throttle SLOs live
        (its PUBLIC listener mounts no /metrics: an S3 bucket named
        "metrics" must stay routable), so `cfs-capacity --s3`'s gate
        cannot see fairness without it."""
        out = []
        for name in list(self.procs):
            if not name.startswith(("metanode", "datanode", "objectnode")):
                continue
            addr = self.boot_info(name, timeout=timeout).get("stats_addr")
            if addr:
                out.append(addr)
        return out

    def spawn_console(self, metrics_addrs: list[str] | None = None,
                      timeout: float = 60.0) -> str:
        """Spawn a console daemon over this cluster's masters (plus any
        extra /metrics targets) and return its address once it listens."""
        addr = f"127.0.0.1:{free_port()}"
        self.spawn("console", {
            "role": "console", "masterAddrs": self.master_addrs,
            "listen": addr, "metricsAddrs": list(metrics_addrs or []),
        })
        self._await_listen(addr, timeout=timeout)
        return addr

    # -- cluster waiting -------------------------------------------------------

    def client_master(self):
        from chubaofs_tpu.master.api_service import MasterClient

        return MasterClient(self.master_addrs)

    def _await_leader(self, timeout: float = 30.0):
        mc = self.client_master()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                if mc.get_cluster()["leader_id"] is not None:
                    return
            except Exception:
                pass
            time.sleep(0.25)
        raise TimeoutError("no master leader elected")

    def _await_listen(self, addr: str, timeout: float = 120.0):
        host, port = addr.rsplit(":", 1)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                with socket.create_connection((host, int(port)), timeout=2):
                    return
            except OSError:
                time.sleep(0.25)
        raise TimeoutError(f"{addr} never started listening")

    def await_nodes(self, count: int, timeout: float = 30.0):
        mc = self.client_master()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                nodes = mc.get_cluster()["nodes"]
                if sum(1 for n in nodes if n["addr"]) >= count:
                    return
            except Exception:
                pass
            time.sleep(0.25)
        raise TimeoutError(f"{count} nodes did not register")

    def remote(self):
        from chubaofs_tpu.sdk.cluster import RemoteCluster

        access = [self.access_addr] if self.access_addr else None
        return RemoteCluster(self.master_addrs, access_addrs=access)

    def fs(self, volume: str):
        return self.remote().client(volume)
