"""Blockcache — node-local read cache daemon over a unix socket.

Reference: blockcache/ (bcache/service.go:132 unix listener, manage.go:130
bcacheManager, bcache/client.go).
"""

from chubaofs_tpu.blockcache.bcache import BcacheClient, BcacheManager, BcacheService

__all__ = ["BcacheClient", "BcacheManager", "BcacheService"]
