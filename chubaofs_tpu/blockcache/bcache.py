"""Node-local block cache: frequency-admitted two-tier LRU + unix-socket service.

Reference counterpart: blockcache/bcache — service.go:132 (unix domain socket
listener shared by every client process on the node), manage.go:130
(bcacheManager: blocks cached as local files keyed `volume_inode_offset`,
size-capped LRU with free-ratio eviction), client.go (Get/Put/Evict RPCs).
Wire format here: one JSON header line + raw data bytes, length-prefixed.
The cold-read path docks via FsClient (sdk/data/blobstore/reader.go:30,66
bcache hooks): read-through GET, async-ish PUT after a blobstore read.

The cache-plane growth (ISSUE 12): zipfian GET traffic is mostly one-hit
wonders at the tail and a small sustained-hot head, so a plain LRU lets one
cold scan flush the whole hot set. The manager now runs TinyLFU-style
admission (arxiv's W-TinyLFU shape, simplified): a counting sketch estimates
every key's access frequency, a ghost list remembers recently-evicted keys,
and a candidate is admitted past a FULL cache only when it is provably
hotter than the LRU victim it would displace (or it just got evicted —
re-reference is the strongest hotness proof there is). Two tiers with
separate budgets: a byte-bounded in-memory overlay (hit = no file IO at
all) over the disk LRU; disk stays authoritative so a daemon restart
rebuilds the index (now in true recency order — file mtimes).
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import struct
import threading
import zlib
from collections import OrderedDict

from chubaofs_tpu.utils.exporter import registry
from chubaofs_tpu.utils.locks import SanitizedLock


class FrequencySketch:
    """Count-min sketch with saturating 4-bit-style counters and periodic
    aging (the TinyLFU "reset" operation): after `sample` recorded accesses
    every counter halves, so the estimate tracks RECENT frequency and a
    formerly-hot key decays instead of squatting on its peak forever."""

    DEPTH = 4
    CAP = 15  # saturation: 4-bit counters, the TinyLFU sweet spot

    def __init__(self, width: int = 4096):
        width = max(64, width)
        self._width = 1 << (width - 1).bit_length()  # power of two
        self._mask = self._width - 1
        self._rows = [bytearray(self._width) for _ in range(self.DEPTH)]
        self._adds = 0
        self._sample = self._width * 8
        self.ages = 0

    def _indexes(self, key: str):
        raw = key.encode()
        h1 = zlib.crc32(raw)
        h2 = zlib.crc32(raw, 0x9E3779B9) | 1  # odd: full-period double hash
        return [(h1 + d * h2) & self._mask for d in range(self.DEPTH)]

    def add(self, key: str) -> None:
        for row, i in zip(self._rows, self._indexes(key)):
            if row[i] < self.CAP:
                row[i] += 1
        self._adds += 1
        if self._adds >= self._sample:
            self._age()

    def _age(self) -> None:
        for row in self._rows:
            for i in range(self._width):
                row[i] >>= 1
        self._adds //= 2
        self.ages += 1

    def estimate(self, key: str) -> int:
        return min(row[i] for row, i in zip(self._rows, self._indexes(key)))


class GhostList:
    """Bounded FIFO of recently-EVICTED keys. A key that comes back while
    its ghost is warm was evicted too early — admission lets it straight
    back in (the ARC/2Q ghost trick grafted onto TinyLFU admission)."""

    def __init__(self, capacity: int = 2048):
        self.capacity = max(16, capacity)
        self._keys: OrderedDict[str, None] = OrderedDict()

    def remember(self, key: str) -> None:
        self._keys.pop(key, None)
        self._keys[key] = None
        while len(self._keys) > self.capacity:
            self._keys.popitem(last=False)

    _MISS = object()

    def recall(self, key: str) -> bool:
        """True (and forgets the ghost) when key was recently evicted."""
        return self._keys.pop(key, self._MISS) is not self._MISS

    def __len__(self) -> int:
        return len(self._keys)


class BcacheManager:
    """Frequency-admitted two-tier cache (manage.go:130 analog, grown).

    Disk tier: blocks as local files, size-capped LRU with free-ratio
    eviction (authoritative — survives restarts). Memory tier: a separately
    byte-bounded LRU overlay holding the bytes of the hottest resident
    blocks, so a mem hit costs zero file IO. Admission: TinyLFU sketch +
    ghost list in front of the disk LRU; `admit="always"` disables the
    policy (the pre-ISSUE-12 behavior, kept for A/B and for write-heavy
    callers that want pure recency)."""

    def __init__(self, cache_dir: str, capacity_bytes: int = 256 << 20,
                 free_ratio: float = 0.15,
                 mem_capacity_bytes: int = 32 << 20,
                 admit: str = "tinylfu"):
        self.dir = cache_dir
        self.capacity = capacity_bytes
        self.free_ratio = free_ratio
        self.mem_capacity = max(0, mem_capacity_bytes)
        self.admit = admit
        self._lock = SanitizedLock(name="bcache.lru")
        self._lru: OrderedDict[str, int] = OrderedDict()  # key -> size, LRU order
        self._mem: OrderedDict[str, bytes] = OrderedDict()  # hot-byte overlay
        self.used = 0
        self.mem_used = 0
        self.sketch = FrequencySketch(width=max(1024, capacity_bytes >> 16))
        self.ghost = GhostList(capacity=max(256, capacity_bytes >> 18))
        # instance tallies back stats() (several managers per process must
        # not share one series); the registry mirror feeds /metrics
        self.hits = 0
        self.misses = 0
        self.admit_rejects = 0
        self.evictions = 0
        self._mem_hits = 0  # amortized mtime-refresh clock (see get())
        self._reg = registry("bcache")
        os.makedirs(cache_dir, exist_ok=True)
        self._load()

    def _path(self, key: str) -> str:
        h = hashlib.sha256(key.encode()).hexdigest()
        return os.path.join(self.dir, h[:2], h)

    def _load(self):
        """Rebuild the index from cache files surviving a daemon restart,
        ordered by file mtime — directory/hash order would randomize the
        LRU, and the first post-restart eviction would evict an arbitrary
        survivor instead of the actual least-recently-used tail."""
        found: list[tuple[float, str, int]] = []
        for sub in sorted(os.listdir(self.dir)):
            subdir = os.path.join(self.dir, sub)
            if not os.path.isdir(subdir):
                continue
            for name in sorted(os.listdir(subdir)):
                p = os.path.join(subdir, name)
                keyfile = p + ".key"
                if not os.path.exists(keyfile):
                    continue
                with open(keyfile, encoding="utf-8") as f:
                    key = f.read()
                found.append((os.path.getmtime(p), key, os.path.getsize(p)))
        for _, key, size in sorted(found):
            self._lru[key] = size
            self.used += size

    # -- read path -------------------------------------------------------------

    def get(self, key: str, offset: int = 0, size: int | None = None) -> bytes | None:
        with self._lock:
            self.sketch.add(key)  # every lookup is a frequency sample
            entry_size = self._lru.get(key)
            if entry_size is None:
                self.misses += 1
                self._reg.counter("misses").add()
                return None
            self._lru.move_to_end(key)  # touch: MRU
            blk = self._mem.get(key)
            if blk is not None:
                self._mem.move_to_end(key)
                self.hits += 1
                self._mem_hits += 1
                # every Nth mem hit refreshes the backing file's mtime: the
                # restart rebuild orders by mtime, and a block served from
                # the overlay for hours must not restart at the LRU tail.
                # Amortized so the overlay keeps its (near-)zero-IO hits.
                touch = (self._mem_hits & 31) == 0
                self._reg.counter("hits", {"tier": "mem"}).add()
                out = blk[offset:offset + size] if size is not None \
                    else blk[offset:]
            else:
                touch = out = None
        if out is not None:
            if touch:
                try:
                    os.utime(self._path(key))
                except OSError:
                    pass  # recency refresh is best-effort
            return out
        try:
            p = self._path(key)
            with open(p, "rb") as f:
                f.seek(offset)
                data = f.read(size if size is not None else -1)
            # refresh recency where _load can see it: the restart rebuild
            # orders by mtime, so a disk hit must count as a touch (mem-
            # overlay hits skip the syscall — their blocks are by
            # construction the recently-written/hit set already)
            try:
                os.utime(p)
            except OSError:
                pass  # read succeeded; a failed touch must not fake a miss
        except OSError:
            # stale index entry (file vanished out-of-band): this lookup
            # returned nothing, so it IS a miss — hits+misses must account
            # for every lookup or scraped hit ratios over-report
            with self._lock:
                size_gone = self._lru.pop(key, 0)
                self.used -= size_gone
                self._drop_mem_locked(key)
                self.misses += 1
                self._reg.counter("misses").add()
            return None
        with self._lock:
            self.hits += 1
            self._reg.counter("hits", {"tier": "disk"}).add()
            # whole-block disk hits promote into the memory overlay: the
            # next hit on this (evidently warm) block skips the file read.
            # An explicit size covering the whole entry counts — BlobCache
            # always passes the blob's exact size, and `size is None` alone
            # would leave its hottest blocks paying file IO forever.
            # Re-checks under the lock: an evict that raced the unlocked
            # file read must not get its bytes resurrected into the overlay
            # (unreachable, but they would squat on the mem budget), and
            # the bytes must match the entry's CURRENT size — a re-put that
            # truncated/rewrote the file mid-read would otherwise pin a
            # torn prefix into the overlay, served IO-free forever
            if self._lru.get(key) == len(data) and offset == 0 \
                    and (size is None or size >= entry_size):
                self._fill_mem_locked(key, data)
        return data

    # -- write path ------------------------------------------------------------

    def _admit_locked(self, key: str, size: int) -> bool:
        """TinyLFU admission against a FULL cache: the candidate must beat
        the recent frequency of EVERY victim its size would displace (one
        tail comparison would let a single large barely-warmer-than-the-
        coldest-block candidate evict a run of hot blocks — the W-TinyLFU
        victim walk), or hold a warm ghost (it was just evicted and came
        back — admission error, let it in). Rejected candidates still left
        their frequency sample in the sketch, so a key that keeps knocking
        eventually builds the estimate to enter."""
        if self.admit == "always":
            return True
        if self.ghost.recall(key):
            return True
        cand = self.sketch.estimate(key)
        freed = 0
        for victim, vsize in self._lru.items():
            if self.used - freed + size <= self.capacity:
                return True  # enough displaceable-cold space found
            if self.sketch.estimate(victim) > cand:
                return False  # would displace a hotter block
            freed += vsize
        return True

    def put(self, key: str, data: bytes) -> bool:
        """Admission-gated insert; returns False when the policy rejected
        the block (a one-hit wonder must not flush the hot set)."""
        with self._lock:
            self.sketch.add(key)
            would_overflow = key not in self._lru and \
                self.used + len(data) > self.capacity
            if would_overflow and not self._admit_locked(key, len(data)):
                self.admit_rejects += 1
                self._reg.counter("admit_rejects").add()
                return False
        p = self._path(key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "wb") as f:
            f.write(data)
        with open(p + ".key", "w", encoding="utf-8") as f:
            f.write(key)
        with self._lock:
            old = self._lru.pop(key, 0)
            self._lru[key] = len(data)
            self.used += len(data) - old
            self._fill_mem_locked(key, data)
            evict = self._plan_eviction_locked()
            self._reg.counter("fills").add()
        for k in evict:
            self._delete_files(k)
        return True

    def _fill_mem_locked(self, key: str, data: bytes) -> None:
        if len(data) > self.mem_capacity:
            return
        old = self._mem.pop(key, None)
        if old is not None:
            self.mem_used -= len(old)
        self._mem[key] = data
        self.mem_used += len(data)
        while self.mem_used > self.mem_capacity and self._mem:
            # mem eviction only drops the overlay copy — the block stays
            # resident (and servable) from its disk file
            _, dropped = self._mem.popitem(last=False)
            self.mem_used -= len(dropped)

    def _drop_mem_locked(self, key: str) -> None:
        blk = self._mem.pop(key, None)
        if blk is not None:
            self.mem_used -= len(blk)

    def _plan_eviction_locked(self) -> list[str]:
        """When over capacity, free down to (1 - free_ratio) * capacity."""
        if self.used <= self.capacity:
            return []
        target = int(self.capacity * (1 - self.free_ratio))
        out = []
        for k in list(self._lru):
            if self.used <= target:
                break
            self.used -= self._lru.pop(k)
            self._drop_mem_locked(k)
            self.ghost.remember(k)
            self.evictions += 1
            self._reg.counter("evictions").add()
            out.append(k)
        return out

    def evict(self, key: str):
        with self._lock:
            size = self._lru.pop(key, None)
            if size is None:
                return
            self.used -= size
            self._drop_mem_locked(key)
        self._delete_files(key)

    def _delete_files(self, key: str):
        p = self._path(key)
        for path in (p, p + ".key"):
            try:
                os.unlink(path)
            except OSError:
                pass

    def stats(self) -> dict:
        with self._lock:
            return {"used": self.used, "capacity": self.capacity,
                    "mem_used": self.mem_used,
                    "mem_capacity": self.mem_capacity,
                    "blocks": len(self._lru), "mem_blocks": len(self._mem),
                    "hits": self.hits, "misses": self.misses,
                    "admit_rejects": self.admit_rejects,
                    "evictions": self.evictions}


# -- wire: 4-byte header length + JSON header + raw data -----------------------

def _send_msg(sock: socket.socket, header: dict, data: bytes = b""):
    h = json.dumps(header).encode()
    sock.sendall(struct.pack("<II", len(h), len(data)) + h + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_msg(sock: socket.socket) -> tuple[dict, bytes]:
    hlen, dlen = struct.unpack("<II", _recv_exact(sock, 8))
    header = json.loads(_recv_exact(sock, hlen).decode()) if hlen else {}
    data = _recv_exact(sock, dlen) if dlen else b""
    return header, data


class BcacheService:
    """Unix-socket daemon fronting one BcacheManager (service.go:132)."""

    def __init__(self, sock_path: str, manager: BcacheManager):
        self.sock_path = sock_path
        self.manager = manager
        if os.path.exists(sock_path):
            os.unlink(sock_path)
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(sock_path)
        self._listener.listen(64)
        self._stopping = threading.Event()
        self._thread: threading.Thread | None = None

    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            while not self._stopping.is_set():
                try:
                    header, data = _recv_msg(conn)
                except (ConnectionError, OSError):
                    return
                mgr = self.manager
                try:
                    op = header.get("op")
                    if op == "get":
                        blk = mgr.get(header["key"], header.get("offset", 0),
                                      header.get("size"))
                        if blk is None:
                            _send_msg(conn, {"ok": False})
                        else:
                            _send_msg(conn, {"ok": True}, blk)
                    elif op == "put":
                        ok = mgr.put(header["key"], data)
                        _send_msg(conn, {"ok": bool(ok)})
                    elif op == "evict":
                        mgr.evict(header["key"])
                        _send_msg(conn, {"ok": True})
                    elif op == "stats":
                        _send_msg(conn, {"ok": True, **mgr.stats()})
                    else:
                        _send_msg(conn, {"ok": False, "err": "bad op"})
                except (ConnectionError, OSError):
                    return

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed by stop()
            t = threading.Thread(target=self._serve_conn, args=(conn,),  # racelint: host-local unix socket, fan-in bounded by same-node client processes (not user traffic) — the evloop's thousands-of-conns economics don't apply; daemon threads die with the conn
                                 name="bcache-conn", daemon=True)
            t.start()

    def start(self):
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="bcache", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stopping.set()
        try:
            self._listener.close()
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if os.path.exists(self.sock_path):
            os.unlink(self.sock_path)


class BcacheClient:
    """Per-process client with one pooled connection (client.go analog).

    cache_key(volume, ino, offset) mirrors the reference's
    `volume_inode_offset` naming."""

    def __init__(self, sock_path: str):
        self.sock_path = sock_path
        self._lock = SanitizedLock(name="bcache.client")
        self._sock: socket.socket | None = None

    @staticmethod
    def cache_key(volume: str, ino: int, offset: int) -> str:
        return f"{volume}_{ino}_{offset}"

    def _conn_locked(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.settimeout(5.0)
            self._sock.connect(self.sock_path)
        return self._sock

    def _call(self, header: dict, data: bytes = b"") -> tuple[dict, bytes]:
        with self._lock:
            try:
                sock = self._conn_locked()
                _send_msg(sock, header, data)
                return _recv_msg(sock)
            except (ConnectionError, OSError):
                if self._sock is not None:
                    self._sock.close()
                    self._sock = None
                raise

    def get(self, key: str, offset: int = 0, size: int | None = None) -> bytes | None:
        try:
            header, data = self._call({"op": "get", "key": key,
                                       "offset": offset, "size": size})
        except (ConnectionError, OSError):
            return None  # cache daemon down == cache miss
        return data if header.get("ok") else None

    def put(self, key: str, data: bytes) -> bool:
        try:
            header, _ = self._call({"op": "put", "key": key}, data)
            return bool(header.get("ok"))
        except (ConnectionError, OSError):
            return False

    def evict(self, key: str) -> None:
        try:
            self._call({"op": "evict", "key": key})
        except (ConnectionError, OSError):
            pass

    def stats(self) -> dict | None:
        try:
            header, _ = self._call({"op": "stats"})
        except (ConnectionError, OSError):
            return None
        return header if header.get("ok") else None

    def close(self):
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None
