"""Node-local block cache: LRU-on-disk manager + unix-socket service + client.

Reference counterpart: blockcache/bcache — service.go:132 (unix domain socket
listener shared by every client process on the node), manage.go:130
(bcacheManager: blocks cached as local files keyed `volume_inode_offset`,
size-capped LRU with free-ratio eviction), client.go (Get/Put/Evict RPCs).
Wire format here: one JSON header line + raw data bytes, length-prefixed.
The cold-read path docks via FsClient (sdk/data/blobstore/reader.go:30,66
bcache hooks): read-through GET, async-ish PUT after a blobstore read.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import socketserver
import struct
import threading


class BcacheManager:
    """Disk-backed LRU of cache blocks (manage.go:130 analog)."""

    def __init__(self, cache_dir: str, capacity_bytes: int = 256 << 20,
                 free_ratio: float = 0.15):
        self.dir = cache_dir
        self.capacity = capacity_bytes
        self.free_ratio = free_ratio
        self._lock = threading.Lock()
        self._lru: dict[str, int] = {}  # key -> size, insertion order = LRU
        self.used = 0
        self.hits = 0
        self.misses = 0
        os.makedirs(cache_dir, exist_ok=True)
        self._load()

    def _path(self, key: str) -> str:
        h = hashlib.sha256(key.encode()).hexdigest()
        return os.path.join(self.dir, h[:2], h)

    def _load(self):
        """Rebuild the index from cache files surviving a daemon restart."""
        for sub in sorted(os.listdir(self.dir)):
            subdir = os.path.join(self.dir, sub)
            if not os.path.isdir(subdir):
                continue
            for name in sorted(os.listdir(subdir)):
                p = os.path.join(subdir, name)
                keyfile = p + ".key"
                if os.path.exists(keyfile):
                    with open(keyfile, encoding="utf-8") as f:
                        key = f.read()
                    size = os.path.getsize(p)
                    self._lru[key] = size
                    self.used += size

    def get(self, key: str, offset: int = 0, size: int | None = None) -> bytes | None:
        with self._lock:
            if key not in self._lru:
                self.misses += 1
                return None
            # touch: move to MRU end
            self._lru[key] = self._lru.pop(key)
            self.hits += 1
        try:
            with open(self._path(key), "rb") as f:
                f.seek(offset)
                return f.read(size if size is not None else -1)
        except OSError:
            with self._lock:
                size_gone = self._lru.pop(key, 0)
                self.used -= size_gone
            return None

    def put(self, key: str, data: bytes):
        p = self._path(key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "wb") as f:
            f.write(data)
        with open(p + ".key", "w", encoding="utf-8") as f:
            f.write(key)
        with self._lock:
            old = self._lru.pop(key, 0)
            self._lru[key] = len(data)
            self.used += len(data) - old
            evict = self._plan_eviction_locked()
        for k in evict:
            self._delete_files(k)

    def _plan_eviction_locked(self) -> list[str]:
        """When over capacity, free down to (1 - free_ratio) * capacity."""
        if self.used <= self.capacity:
            return []
        target = int(self.capacity * (1 - self.free_ratio))
        out = []
        for k in list(self._lru):
            if self.used <= target:
                break
            self.used -= self._lru.pop(k)
            out.append(k)
        return out

    def evict(self, key: str):
        with self._lock:
            size = self._lru.pop(key, None)
            if size is None:
                return
            self.used -= size
        self._delete_files(key)

    def _delete_files(self, key: str):
        p = self._path(key)
        for path in (p, p + ".key"):
            try:
                os.unlink(path)
            except OSError:
                pass

    def stats(self) -> dict:
        with self._lock:
            return {"used": self.used, "capacity": self.capacity,
                    "blocks": len(self._lru), "hits": self.hits,
                    "misses": self.misses}


# -- wire: 4-byte header length + JSON header + raw data -----------------------

def _send_msg(sock: socket.socket, header: dict, data: bytes = b""):
    h = json.dumps(header).encode()
    sock.sendall(struct.pack("<II", len(h), len(data)) + h + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_msg(sock: socket.socket) -> tuple[dict, bytes]:
    hlen, dlen = struct.unpack("<II", _recv_exact(sock, 8))
    header = json.loads(_recv_exact(sock, hlen).decode()) if hlen else {}
    data = _recv_exact(sock, dlen) if dlen else b""
    return header, data


class BcacheService:
    """Unix-socket daemon fronting one BcacheManager (service.go:132)."""

    def __init__(self, sock_path: str, manager: BcacheManager):
        self.sock_path = sock_path
        self.manager = manager
        if os.path.exists(sock_path):
            os.unlink(sock_path)
        mgr = manager

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        header, data = _recv_msg(self.request)
                    except (ConnectionError, OSError):
                        return
                    op = header.get("op")
                    if op == "get":
                        blk = mgr.get(header["key"], header.get("offset", 0),
                                      header.get("size"))
                        if blk is None:
                            _send_msg(self.request, {"ok": False})
                        else:
                            _send_msg(self.request, {"ok": True}, blk)
                    elif op == "put":
                        mgr.put(header["key"], data)
                        _send_msg(self.request, {"ok": True})
                    elif op == "evict":
                        mgr.evict(header["key"])
                        _send_msg(self.request, {"ok": True})
                    elif op == "stats":
                        _send_msg(self.request, {"ok": True, **mgr.stats()})
                    else:
                        _send_msg(self.request, {"ok": False, "err": "bad op"})

        class Server(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True

        self.server = Server(sock_path, Handler)
        self._thread: threading.Thread | None = None

    def start(self):
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        name="bcache", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self.server.shutdown()
        self.server.server_close()
        if os.path.exists(self.sock_path):
            os.unlink(self.sock_path)


class BcacheClient:
    """Per-process client with one pooled connection (client.go analog).

    cache_key(volume, ino, offset) mirrors the reference's
    `volume_inode_offset` naming."""

    def __init__(self, sock_path: str):
        self.sock_path = sock_path
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None

    @staticmethod
    def cache_key(volume: str, ino: int, offset: int) -> str:
        return f"{volume}_{ino}_{offset}"

    def _conn_locked(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.settimeout(5.0)
            self._sock.connect(self.sock_path)
        return self._sock

    def _call(self, header: dict, data: bytes = b"") -> tuple[dict, bytes]:
        with self._lock:
            try:
                sock = self._conn_locked()
                _send_msg(sock, header, data)
                return _recv_msg(sock)
            except (ConnectionError, OSError):
                if self._sock is not None:
                    self._sock.close()
                    self._sock = None
                raise

    def get(self, key: str, offset: int = 0, size: int | None = None) -> bytes | None:
        try:
            header, data = self._call({"op": "get", "key": key,
                                       "offset": offset, "size": size})
        except (ConnectionError, OSError):
            return None  # cache daemon down == cache miss
        return data if header.get("ok") else None

    def put(self, key: str, data: bytes) -> bool:
        try:
            header, _ = self._call({"op": "put", "key": key}, data)
            return bool(header.get("ok"))
        except (ConnectionError, OSError):
            return False

    def evict(self, key: str) -> None:
        try:
            self._call({"op": "evict", "key": key})
        except (ConnectionError, OSError):
            pass

    def stats(self) -> dict | None:
        try:
            header, _ = self._call({"op": "stats"})
        except (ConnectionError, OSError):
            return None
        return header if header.get("ok") else None

    def close(self):
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None
