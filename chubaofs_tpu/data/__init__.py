"""Replicated data plane: chain-repl + raft datanodes (datanode/, repl/)."""

from chubaofs_tpu.data.datanode import (  # noqa: F401
    DataNode, DataPartition, DataPartitionSM, SpaceManager,
)
from chubaofs_tpu.data.repl import FollowerAckError, ReplError, ReplServer  # noqa: F401
