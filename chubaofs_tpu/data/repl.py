"""Chain-replication packet pipeline (repl/repl_protocol.go:35-66 analog).

The reference's ReplProtocol: the leader reads a packet from the client
connection, Prepares it, forwards to every follower through pooled
FollowerTransports, Operates locally, and acks the client only after all
follower acks arrive (repl_protocol.go:190-219, follower check :155-160).

Kept here: the same leader pipeline with the forward overlapped against the
local operate (send to all followers first, operate, then collect acks — the
goroutine-pair overlap collapsed to one worker task per client connection),
pooled follower connections, and the RemainingFollowers byte cleared on
forwarded packets. The operator itself is injected by the datanode.

Serving rides the rpc/evloop.py event-loop core by default (ISSUE 8): loop
shards own the sockets, the blocking dispatch runs on the bounded worker
pool, per-connection order is preserved. `CFS_EVLOOP=0` restores the
thread-per-connection accept loop below for A/B and rollback."""

from __future__ import annotations

import socket
import threading

from chubaofs_tpu.proto.packet import (
    Packet, RES_OK, recv_packet, send_packet,
)
from chubaofs_tpu.rpc.evloop import EvloopServer, evloop_enabled
from chubaofs_tpu.utils.conn_pool import ConnPool


class ReplError(Exception):
    pass


class FollowerAckError(ReplError):
    def __init__(self, addr: str, detail: str):
        super().__init__(f"follower {addr}: {detail}")
        self.addr = addr


class ReplServer:
    """TCP packet server + follower forwarding for one datanode."""

    def __init__(self, addr: str, dispatch, pool: ConnPool | None = None):
        """dispatch(pkt: Packet) -> Packet runs the node-local operate step
        (datanode/wrap_operator.go:80 analog) and decides replication itself
        via self.replicate()."""
        self.addr = addr
        self.dispatch = dispatch
        self.pool = pool or ConnPool()
        host, port = addr.rsplit(":", 1)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        if int(port) == 0:
            self.addr = f"{host}:{self._listener.getsockname()[1]}"
        self._stop = threading.Event()
        self._accept_thread: threading.Thread | None = None
        self._evloop: EvloopServer | None = None

    # -- server side -----------------------------------------------------------

    def start(self) -> None:
        self._listener.listen(128)
        if evloop_enabled():
            self._evloop = EvloopServer(self._listener, self.dispatch,
                                        name="repl")
            self._evloop.start()
            return
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name=f"repl-{self.addr}")
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        """CFS_EVLOOP=0 shim: the pre-evloop thread-per-connection path."""
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(  # racelint: CFS_EVLOOP=0 rollback shim — evloop is the default serving path
                target=self._serve_conn, args=(conn,), daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        """ServerConn analog (repl_protocol.go:219): packets in order per conn."""
        try:
            while not self._stop.is_set():
                pkt = recv_packet(conn)
                reply = self.dispatch(pkt)
                send_packet(conn, reply)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def stop(self) -> None:
        self._stop.set()
        if self._evloop is not None:
            self._evloop.stop()
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._listener.close()
        self.pool.close()

    # -- leader-side forwarding ------------------------------------------------

    def replicate(self, pkt: Packet, operate) -> Packet:
        """Forward to pkt.arg['followers'], operate locally, collect acks.

        Overlap discipline of OperatorAndForwardPktGoRoutine
        (repl_protocol.go:205): all follower sends go out before the local
        operate runs; acks are collected after. Any follower failure fails the
        whole op — the client retries on a fresh extent, and repair reconciles
        (the reference's behavior on follower error)."""
        followers: list[str] = list(pkt.arg.get("followers", []))
        if not followers:
            return operate(pkt)

        fwd = Packet(
            opcode=pkt.opcode, partition_id=pkt.partition_id,
            extent_id=pkt.extent_id, extent_offset=pkt.extent_offset,
            kernel_offset=pkt.kernel_offset, data=pkt.data,
            arg={k: v for k, v in pkt.arg.items() if k != "followers"},
            req_id=pkt.req_id, crc=pkt.crc,
        )
        sent: list[tuple[str, socket.socket]] = []
        try:
            for addr in followers:
                sock = self.pool.get(addr)
                try:
                    send_packet(sock, fwd)
                except OSError as e:
                    self.pool.put(addr, sock, ok=False)
                    raise FollowerAckError(addr, f"send: {e}") from None
                sent.append((addr, sock))

            reply = operate(pkt)  # local op overlaps follower network+disk

            for addr, sock in sent:
                try:
                    ack = recv_packet(sock)
                except (OSError, ConnectionError) as e:
                    self.pool.put(addr, sock, ok=False)
                    sent.remove((addr, sock))
                    raise FollowerAckError(addr, f"recv: {e}") from None
                if ack.result != RES_OK:
                    raise FollowerAckError(addr, ack.error())
            for addr, sock in sent:
                self.pool.put(addr, sock)
            return reply
        except FollowerAckError:
            for addr, sock in sent:
                self.pool.put(addr, sock, ok=False)
            raise

    # -- client-side one-shot --------------------------------------------------

    def request(self, addr: str, pkt: Packet) -> Packet:
        """Send one packet to a peer and await its reply (repair/admin path)."""
        sock = self.pool.get(addr)
        try:
            send_packet(sock, pkt)
            reply = recv_packet(sock)
        except (OSError, ConnectionError):
            self.pool.put(addr, sock, ok=False)
            raise
        self.pool.put(addr, sock)
        return reply
