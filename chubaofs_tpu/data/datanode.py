"""DataNode — hosts replicated data partitions over disks.

Reference counterpart: datanode/ (doStart server.go:178, dispatch
wrap_operator.go:80, write :479, random-write via raft :562,594 +
partition_op_by_raft.go, SpaceManager space_manager.go, repair
data_partition_repair.go:80-481) over storage/'s ExtentStore.

Dual replication kept exactly as the reference splits it (SURVEY §2.4):
  * append writes + extent create/delete ride CHAIN replication — the client
    sends to the partition leader with the follower address list, the leader
    forwards before operating locally (chubaofs_tpu/data/repl.py);
  * random in-place overwrites ride RAFT (one group per partition, group id =
    partition id, hosted on the node's MultiRaft) because overwrite order must
    be total (datanode/partition_op_by_raft.go).

Repair follows data_partition_repair.go:80: the leader gathers every
replica's watermarks, computes the per-extent max, streams missing suffixes
from the most advanced replica to laggards (streamRepairExtent :481), and
replays extent deletes + tiny punch-hole records."""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import threading

import time

from chubaofs_tpu import chaos
from chubaofs_tpu.blobstore import trace
from chubaofs_tpu.data.repl import FollowerAckError, ReplError, ReplServer
from chubaofs_tpu.proto.packet import (
    OP_CREATE_EXTENT, OP_CREATE_PARTITION, OP_GET_PARTITION_METRICS,
    OP_RAFT_CONFIG, OP_REMOVE_PARTITION,
    OP_GET_WATERMARKS, OP_HEARTBEAT, OP_MARK_DELETE, OP_RANDOM_WRITE,
    OP_REPAIR_READ, OP_REPAIR_WRITE, OP_STREAM_READ, OP_TINY_DELETE_RECORD,
    OP_WRITE, Packet, RES_DISK_ERR, RES_ERR, RES_NOT_EXIST, RES_NOT_LEADER,
    RES_OK, TRACE_ARG_KEY, is_tiny_extent, op_name, trace_extract,
    trace_reply,
)
from chubaofs_tpu.utils.auditlog import record_slow_op
from chubaofs_tpu.utils.exporter import registry
from chubaofs_tpu.utils.locks import SanitizedLock
from chubaofs_tpu.raft.server import MultiRaft, NotLeaderError, StateMachine
from chubaofs_tpu.storage.extent_store import (
    ExtentNotFound, ExtentStore, MIN_NORMAL_EXTENT_ID, StorageError,
)

REPAIR_CHUNK = 1 << 20  # repair stream granularity


class DataPartitionSM(StateMachine):
    """Raft state machine for the random-write path.

    The extent files ARE the durable state (SURVEY §5: 'datanode — extents are
    the state; raft WAL for random writes'), so snapshots carry no payload and
    recovery = WAL replay over the on-disk extents (idempotent overwrites)."""

    def __init__(self, store: ExtentStore):
        self.store = store

    def apply(self, data, index: int):
        op = data[0]
        try:
            if op == "rw":
                _, eid, off, blob = data
                self.store.write(eid, off, blob, overwrite=True)
            elif op == "tiny_del":
                _, eid, off, size = data
                self.store.mark_delete(eid, off, size)
            return ("ok", None)
        except (StorageError, OSError) as e:
            return ("err", str(e))

    def snapshot(self) -> bytes:
        return b""

    def restore(self, data: bytes) -> None:
        pass


class DataPartition:
    """One replica of a data partition: extent store + peers + raft group."""

    def __init__(self, pid: int, root: str, peers: list[int], hosts: list[str],
                 raft: MultiRaft | None):
        self.pid = pid
        self.root = root
        self.peers = peers  # datanode node ids (raft membership)
        self.hosts = hosts  # datanode repl addresses, hosts[0] = leader
        self.raft = raft
        self.store = ExtentStore(root)
        self._id_lock = threading.Lock()
        self._meta_path = os.path.join(root, "meta.json")
        self._eid_path = os.path.join(root, "eid_counter")
        self._write_meta()
        # monotonic, persisted, never reused — concurrent OP_CREATE_EXTENT
        # handlers must not hand out the same id
        self._next_eid = self._load_eid_counter()
        if raft is not None:
            raft.create_group(pid, peers, DataPartitionSM(self.store))

    def _write_meta(self) -> None:
        with open(self._meta_path, "w") as f:
            json.dump({"pid": self.pid, "peers": self.peers, "hosts": self.hosts}, f)

    def update_membership(self, peers: list[int], hosts: list[str]) -> None:
        """Refresh replica addresses (hosts change across node restarts)."""
        self.peers = peers
        self.hosts = hosts
        self._write_meta()

    def _load_eid_counter(self) -> int:
        floor = MIN_NORMAL_EXTENT_ID
        if os.path.exists(self._eid_path):
            with open(self._eid_path) as f:
                floor = max(floor, int(f.read().strip() or 0))
        ids = set(self.store.extent_ids()) | self.store._deleted
        return max([floor - 1, *ids]) + 1

    @classmethod
    def load(cls, root: str, raft: MultiRaft | None) -> "DataPartition":
        with open(os.path.join(root, "meta.json")) as f:
            meta = json.load(f)
        return cls(meta["pid"], root, meta["peers"], meta["hosts"], raft)

    def alloc_extent_id(self) -> int:
        with self._id_lock:
            eid = self._next_eid
            self._next_eid += 1
            with open(self._eid_path, "w") as f:
                f.write(str(self._next_eid))
            return eid

    @property
    def is_raft_leader(self) -> bool:
        return self.raft is not None and self.raft.is_leader(self.pid)


class SpaceManager:
    """Disk set → partition placement (datanode/space_manager.go analog):
    a new partition lands on the disk with the most free space."""

    def __init__(self, disks: list[str]):
        self.disks = disks
        for d in disks:
            os.makedirs(d, exist_ok=True)
        self.partitions: dict[int, DataPartition] = {}
        # guards partition create/load: concurrent OP_CREATE_PARTITION
        # packets for one pid must not double-create the DataPartition (and
        # its raft group) — racelint check-then-act
        self._lock = SanitizedLock(name="datanode.space")

    def _pick_disk(self) -> str:
        # most free space, fewest hosted partitions as the tiebreak
        def key(d: str):
            hosted = sum(1 for p in self.partitions.values() if p.root.startswith(d))
            return (shutil.disk_usage(d).free, -hosted)

        return max(self.disks, key=key)

    def create_partition(self, pid: int, peers: list[int], hosts: list[str],
                         raft: MultiRaft | None) -> DataPartition:
        with self._lock:
            if pid in self.partitions:
                self.partitions[pid].update_membership(peers, hosts)
                return self.partitions[pid]
            root = os.path.join(self._pick_disk(), f"dp_{pid}")
            os.makedirs(root, exist_ok=True)
            dp = DataPartition(pid, root, peers, hosts, raft)
            self.partitions[pid] = dp
            return dp

    def load_all(self, raft: MultiRaft | None) -> None:
        with self._lock:
            for disk in self.disks:
                for name in os.listdir(disk):
                    if name.startswith("dp_"):
                        pid = int(name[3:])
                        if pid not in self.partitions:
                            self.partitions[pid] = DataPartition.load(
                                os.path.join(disk, name), raft)


class DataNode:
    """TCP packet server + partitions + repair loops."""

    # repair/migrate traffic class: bulk streams that must never starve
    # client IO. The reference isolates them on separate smux ports
    # (datanode/server.go:99-103); here the same isolation is an explicit
    # PRIORITY LANE — repair-class packets share a small concurrency budget
    # per node, so any repair fan-in queues against itself while client
    # reads/writes keep their own unthrottled threads.
    REPAIR_CLASS = frozenset({OP_REPAIR_READ, OP_REPAIR_WRITE,
                              OP_GET_WATERMARKS})

    def __init__(self, node_id: int, addr: str, disks: list[str],
                 raft: MultiRaft | None = None, repair_lanes: int = 2):
        self.node_id = node_id
        self.space = SpaceManager(disks)
        self.raft = raft
        self.repair_lanes = repair_lanes
        self._repair_sem = threading.BoundedSemaphore(repair_lanes)
        self._reg = registry("datanode")  # bound once: dispatch is per-packet
        # per-partition op tally since the last take_loads() — the heartbeat
        # payload the master's hot-volume rebalancer reads. A plain dict on
        # purpose: partition ids are unbounded, so this must never become a
        # metric label (obslint rule 1); the aggregate ops ride the `op` TP.
        self._loads_lock = SanitizedLock(name="datanode.loads")
        self._op_loads: dict[int, int] = {}
        self.server = ReplServer(addr, self._dispatch)
        self.space.load_all(raft)

    @property
    def addr(self) -> str:
        return self.server.addr

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self.server.stop()

    def take_loads(self) -> dict[int, int]:
        """Per-partition ops served since the last call, then reset — each
        heartbeat reports one window's delta, so the master's NodeInfo.loads
        is always a recent-load snapshot, not a lifetime total."""
        with self._loads_lock:
            out, self._op_loads = self._op_loads, {}
        return out

    def refund_loads(self, loads: dict[int, int]) -> None:
        """Fold a taken-but-unreported window back in (heartbeat send
        failed) so a transient master hiccup never erases observed load."""
        with self._loads_lock:
            for pid, c in loads.items():
                self._op_loads[pid] = self._op_loads.get(pid, 0) + c

    # -- dispatch (wrap_operator.go:80 analog) ---------------------------------

    def _dispatch(self, pkt: Packet) -> Packet:
        """Op dispatch wrapped in the observability plane: per-op TP metrics
        into the datanode role registry, a span continuing the packet's
        trace (its track rides back in the reply arg — only for requests
        that CARRIED a trace id, so pipelined write bursts whose acks drain
        at flush don't flood the caller's bounded track log), and slow-op
        audit over CFS_SLOWOP_MS."""
        name = op_name(pkt.opcode)
        if pkt.partition_id and pkt.opcode not in self.REPAIR_CLASS:
            # client-class IO only: repair/migrate streams are the cure, and
            # counting them would make the rebalancer chase its own moves
            with self._loads_lock:
                self._op_loads[pkt.partition_id] = \
                    self._op_loads.get(pkt.partition_id, 0) + 1
        traced = isinstance(pkt.arg, dict) and TRACE_ARG_KEY in pkt.arg
        span = trace_extract(pkt, f"datanode.{name}")
        trace.push_span(span)
        t0 = time.perf_counter()
        try:
            with self._reg.tp("op", {"op": name}):
                resp = self._dispatch_inner(pkt)
            span.append_track_log("datanode", start=t0)
            return trace_reply(resp, span) if traced else resp
        finally:
            span.finish()
            trace.pop_span()
            record_slow_op("datanode", name, time.perf_counter() - t0,
                           span=span)

    def _dispatch_inner(self, pkt: Packet) -> Packet:
        try:
            handler = self._HANDLERS[pkt.opcode]
        except KeyError:
            return pkt.reply(RES_ERR, arg={"error": f"bad opcode {pkt.opcode:#x}"})
        # repair lane: bulk repair queues against its own budget, never
        # against client IO (smux-port separation analog)
        lane = (self._repair_sem if pkt.opcode in self.REPAIR_CLASS
                else contextlib.nullcontext())
        try:
            with lane:
                # injected disk-lane faults surface as RES_DISK_ERR below,
                # exactly the path a real EIO from the store takes
                chaos.failpoint("datanode.op", node=self.node_id)
                return handler(self, pkt)
        except ExtentNotFound as e:
            return pkt.reply(RES_NOT_EXIST, arg={"error": str(e)})
        except FollowerAckError as e:
            return pkt.reply(RES_ERR, arg={"error": str(e)})
        except (StorageError, ReplError, OSError) as e:
            return pkt.reply(RES_DISK_ERR, arg={"error": str(e)})

    def _dp(self, pkt: Packet) -> DataPartition:
        dp = self.space.partitions.get(pkt.partition_id)
        if dp is None:
            raise ExtentNotFound(f"partition {pkt.partition_id}")
        return dp

    # admin ---------------------------------------------------------------------

    def _op_create_partition(self, pkt: Packet) -> Packet:
        a = pkt.arg
        # daemon mode: the admin task carries peer raft addresses so this
        # node's TCP raft transport can dial them (master/cluster_task.go
        # sends hosts the same way)
        raft_addrs = a.get("raft_addrs") or {}
        if raft_addrs and self.raft is not None and hasattr(self.raft.net, "set_peer"):
            for nid, addr in raft_addrs.items():
                self.raft.net.set_peer(int(nid), addr)
        # idempotent: SpaceManager updates membership for an existing pid
        self.space.create_partition(pkt.partition_id, a["peers"], a["hosts"],
                                    self.raft)
        return pkt.reply()

    def _op_raft_config(self, pkt: Packet) -> Packet:
        """Single-server membership change; only the raft leader proposes."""
        dp = self._dp(pkt)
        a = pkt.arg
        if dp.raft is None:
            dp.update_membership(a.get("peers", dp.peers),
                                 a.get("hosts", dp.hosts))
            return pkt.reply()
        if not dp.is_raft_leader:
            return pkt.reply(RES_NOT_LEADER,
                             arg={"leader": dp.raft.leader_of(dp.pid)})
        raft_addrs = a.get("raft_addrs") or {}
        if hasattr(dp.raft.net, "set_peer"):
            for nid, addr in raft_addrs.items():
                dp.raft.net.set_peer(int(nid), addr)
        peers = dp.raft.propose_config(dp.pid, a["action"], a["node_id"]).result(10)
        dp.update_membership(a.get("peers", dp.peers), a.get("hosts", dp.hosts))
        return pkt.reply(arg={"peers": peers})

    def _op_remove_partition(self, pkt: Packet) -> Packet:
        """Drop a retired replica: leave the raft group, retire the dir."""
        import shutil

        dp = self.space.partitions.pop(pkt.partition_id, None)
        if dp is not None:
            if self.raft is not None:
                self.raft.remove_group(dp.pid)
            shutil.rmtree(dp.root, ignore_errors=True)
        return pkt.reply()

    def _op_heartbeat(self, pkt: Packet) -> Packet:
        return pkt.reply(arg={"node_id": self.node_id,
                              "partitions": len(self.space.partitions)})

    def _op_metrics(self, pkt: Packet) -> Packet:
        dp = self._dp(pkt)
        wm = dp.store.watermarks()
        return pkt.reply(arg={"used": sum(wm.values()), "extents": len(wm)})

    # chain-replicated writes ----------------------------------------------------

    def _op_create_extent(self, pkt: Packet) -> Packet:
        dp = self._dp(pkt)
        if pkt.extent_id == 0:  # leader allocates, then forwards the chosen id
            pkt.extent_id = dp.alloc_extent_id()

        def operate(p: Packet) -> Packet:
            dp.store.create(p.extent_id)
            return p.reply(extent_id=p.extent_id)

        return self.server.replicate(pkt, operate)

    def _op_write(self, pkt: Packet) -> Packet:
        """Append write; tiny allocation happens here on the leader
        (datanode/wrap_prepare.go:28 Prepare analog)."""
        dp = self._dp(pkt)
        if not pkt.verify_crc():
            return pkt.reply(RES_ERR, arg={"error": "packet crc mismatch"})
        if pkt.arg.get("tiny") and pkt.extent_id == 0:
            pkt.extent_id, pkt.extent_offset = dp.store.alloc_tiny()

        def operate(p: Packet) -> Packet:
            dp.store.write(p.extent_id, p.extent_offset, p.data, crc=p.crc)
            return p.reply(extent_id=p.extent_id, extent_offset=p.extent_offset)

        return self.server.replicate(pkt, operate)

    def _op_mark_delete(self, pkt: Packet) -> Packet:
        dp = self._dp(pkt)

        def operate(p: Packet) -> Packet:
            size = p.arg.get("size", 0)
            if is_tiny_extent(p.extent_id):
                dp.store.mark_delete(p.extent_id, p.extent_offset, size)
            elif dp.store.has(p.extent_id):
                dp.store.mark_delete(p.extent_id)
            return p.reply()

        return self.server.replicate(pkt, operate)

    # raft-replicated random write ----------------------------------------------

    def _op_random_write(self, pkt: Packet) -> Packet:
        dp = self._dp(pkt)
        if dp.raft is None:
            dp.store.write(pkt.extent_id, pkt.extent_offset, pkt.data, overwrite=True)
            return pkt.reply()
        if not dp.is_raft_leader:
            return pkt.reply(RES_NOT_LEADER,
                             arg={"leader": dp.raft.leader_of(dp.pid)})
        # concurrent handler threads coalesce in the group-commit pending
        # queue: one WAL flush + one AppendEntries round per drained batch,
        # not per packet (the partition_op_by_raft.go hot path)
        try:
            fut = dp.raft.propose(
                dp.pid, ("rw", pkt.extent_id, pkt.extent_offset, pkt.data))
        except NotLeaderError as e:  # deposed between the gate and the propose
            return pkt.reply(RES_NOT_LEADER, arg={"leader": e.leader})
        t_wait = time.perf_counter()
        status, detail = fut.result(timeout=10)
        span = trace.current_span()
        if span is not None:  # waiter-side raft hop entry (commit wait)
            span.append_track_log("raft", start=t_wait)
            span.add_stage("raft", start=t_wait)
        if status != "ok":
            return pkt.reply(RES_ERR, arg={"error": detail})
        return pkt.reply()

    def _op_tiny_delete_record(self, pkt: Packet) -> Packet:
        dp = self._dp(pkt)
        size = pkt.arg.get("size", 0)

        def operate(p: Packet) -> Packet:
            dp.store.mark_delete(p.extent_id, p.extent_offset, size)
            return p.reply()

        return self.server.replicate(pkt, operate)

    # reads ---------------------------------------------------------------------

    def _op_stream_read(self, pkt: Packet) -> Packet:
        dp = self._dp(pkt)
        # client reads are leader-only when the partition rides raft: a
        # follower may not have applied the latest random overwrite yet
        # (the reference ships followerRead=false by default for the same
        # reason). A packet flagged follower_read opts INTO that relaxed
        # consistency (volume option, proto/mount_options.go FollowerRead) —
        # the follower serves from its local store without a leadership
        # check, which keeps reads alive through elections. Repair reads
        # target specific replicas and skip the gate the same way.
        if (pkt.opcode == OP_STREAM_READ and dp.raft is not None
                and not dp.is_raft_leader
                and not pkt.arg.get("follower_read")):
            return pkt.reply(RES_NOT_LEADER,
                             arg={"leader": dp.raft.leader_of(dp.pid)})
        size = pkt.arg.get("size", 0)
        data = dp.store.read(pkt.extent_id, pkt.extent_offset, size)
        return pkt.reply(data=data)

    # repair --------------------------------------------------------------------

    def _op_get_watermarks(self, pkt: Packet) -> Packet:
        dp = self._dp(pkt)
        holes = {str(eid): dp.store.tiny_holes(eid) for eid in dp.store.extent_ids()
                 if is_tiny_extent(eid)}
        return pkt.reply(arg={
            "watermarks": {str(k): v for k, v in dp.store.watermarks().items()},
            "deleted": sorted(dp.store._deleted),
            "holes": {k: v for k, v in holes.items() if v},
        })

    def _op_repair_write(self, pkt: Packet) -> Packet:
        """Local-only append used by the repair stream (no re-replication)."""
        dp = self._dp(pkt)
        if not dp.store.has(pkt.extent_id) and not is_tiny_extent(pkt.extent_id):
            dp.store.create(pkt.extent_id)
        dp.store.write(pkt.extent_id, pkt.extent_offset, pkt.data, crc=pkt.crc)
        return pkt.reply()

    _HANDLERS = {
        OP_CREATE_PARTITION: _op_create_partition,
        OP_HEARTBEAT: _op_heartbeat,
        OP_GET_PARTITION_METRICS: _op_metrics,
        OP_CREATE_EXTENT: _op_create_extent,
        OP_WRITE: _op_write,
        OP_MARK_DELETE: _op_mark_delete,
        OP_RANDOM_WRITE: _op_random_write,
        OP_TINY_DELETE_RECORD: _op_tiny_delete_record,
        OP_RAFT_CONFIG: _op_raft_config,
        OP_REMOVE_PARTITION: _op_remove_partition,
        OP_STREAM_READ: _op_stream_read,
        OP_REPAIR_READ: _op_stream_read,
        OP_GET_WATERMARKS: _op_get_watermarks,
        OP_REPAIR_WRITE: _op_repair_write,
    }

    # -- leader-driven repair (data_partition_repair.go:80 analog) ---------------

    def repair_partition(self, pid: int) -> int:
        """Reconcile every replica of pid; returns bytes streamed."""
        self._reg.counter("repair_rounds_total").add()
        dp = self.space.partitions.get(pid)
        if dp is None:
            raise ExtentNotFound(f"partition {pid}")
        views: dict[str, dict] = {}
        for host in dp.hosts:
            if host == self.addr:
                rep = self._op_get_watermarks(
                    Packet(OP_GET_WATERMARKS, partition_id=pid))
            else:
                try:
                    rep = self.server.request(
                        host, Packet(OP_GET_WATERMARKS, partition_id=pid))
                except (OSError, ReplError):
                    continue  # dead replica: repair the reachable set
            if rep.result == RES_OK:
                views[host] = rep.arg

        # union of deletes wins: an extent deleted anywhere dies everywhere
        deleted = set()
        for v in views.values():
            deleted.update(v["deleted"])
        for host, v in views.items():
            for eid in deleted - set(v["deleted"]):
                if str(eid) in v["watermarks"]:
                    self.server.request(host, Packet(
                        OP_MARK_DELETE, partition_id=pid, extent_id=eid))

        # per-extent max watermark; stream suffixes to laggards
        maxes: dict[int, tuple[int, str]] = {}
        for host, v in views.items():
            for k, size in v["watermarks"].items():
                eid = int(k)
                if eid in deleted:
                    continue
                if eid not in maxes or size > maxes[eid][0]:
                    maxes[eid] = (size, host)
        streamed = 0
        for eid, (target, source) in maxes.items():
            for host, v in views.items():
                have = v["watermarks"].get(str(eid), 0)
                if have >= target or host == source:
                    continue
                streamed += self._stream_repair_extent(
                    dp, eid, source, host, have, target)

        # replay tiny punch-hole records everywhere
        for host, v in views.items():
            for k, holes in v.get("holes", {}).items():
                eid = int(k)
                for off, size in holes:
                    for peer, pv in views.items():
                        if peer == host:
                            continue
                        if [off, size] in pv.get("holes", {}).get(k, []):
                            continue
                        self.server.request(peer, Packet(
                            OP_MARK_DELETE, partition_id=pid, extent_id=eid,
                            extent_offset=off, arg={"size": size}))
        if streamed:
            self._reg.counter("repair_bytes_total").add(streamed)
        return streamed

    def _stream_repair_extent(self, dp: DataPartition, eid: int, source: str,
                              dest: str, start: int, end: int) -> int:
        """streamRepairExtent (data_partition_repair.go:481): chunked copy.

        LOCAL chunk IO (this node is the source and/or dest — the common
        case, the coordinator is usually the most advanced replica) takes
        the repair lane the same as remote-origin repair packets do: the
        traffic-class budget bounds bulk repair at the DISK, not merely at
        the wire."""
        moved = 0
        pos = start
        while pos < end:
            n = min(REPAIR_CHUNK, end - pos)
            req = Packet(OP_REPAIR_READ, partition_id=dp.pid, extent_id=eid,
                         extent_offset=pos, arg={"size": n})
            if source == self.addr:
                with self._repair_sem:
                    blob = dp.store.read(eid, pos, n)
            else:
                rep = self.server.request(source, req)
                if rep.result != RES_OK:
                    raise ReplError(rep.error())
                blob = rep.data
            wr = Packet(OP_REPAIR_WRITE, partition_id=dp.pid, extent_id=eid,
                        extent_offset=pos, data=blob)
            if dest == self.addr:
                with self._repair_sem:
                    self._op_repair_write(wr)
            else:
                rep = self.server.request(dest, wr)
                if rep.result != RES_OK:
                    raise ReplError(rep.error())
            pos += n
            moved += n
        return moved
