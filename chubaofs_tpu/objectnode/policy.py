"""S3 bucket policy engine (objectnode/policy*.go analog).

Reference counterpart: objectnode's ~3k-LoC policy engine — JSON bucket
policies with Version/Statement[], each statement Effect Allow|Deny,
Principal, Action (s3:* wildcards), Resource (arn wildcards), evaluated
deny-overrides. Stored as the `oss:policy` xattr on the bucket root inode.
Condition operators are out of scope here (the reference supports a subset;
the evaluation order and wildcard semantics below are the load-bearing part).
"""

from __future__ import annotations

import fnmatch
import json

XATTR_POLICY = "oss:policy"

ALLOW = "Allow"
DENY = "Deny"

# objectnode action names: s3:GetObject, s3:PutObject, ...
ACTION_GET = "s3:GetObject"
ACTION_PUT = "s3:PutObject"
ACTION_DELETE = "s3:DeleteObject"
ACTION_LIST = "s3:ListBucket"


class PolicyError(ValueError):
    pass


def _as_list(v) -> list:
    return v if isinstance(v, list) else [v]


class Policy:
    def __init__(self, doc: dict):
        if "Statement" not in doc:
            raise PolicyError("policy missing Statement")
        self.doc = doc
        for st in _as_list(doc["Statement"]):
            if st.get("Effect") not in (ALLOW, DENY):
                raise PolicyError(f"bad Effect {st.get('Effect')!r}")
            if "Action" not in st or "Resource" not in st:
                raise PolicyError("statement missing Action/Resource")

    @classmethod
    def from_json(cls, raw: bytes) -> "Policy":
        try:
            return cls(json.loads(raw.decode()))
        except (ValueError, AttributeError) as e:
            raise PolicyError(str(e)) from None

    def to_json(self) -> bytes:
        return json.dumps(self.doc).encode()

    @staticmethod
    def _principal_matches(st: dict, principal: str | None) -> bool:
        p = st.get("Principal", "*")
        if p == "*" or p == {"AWS": "*"}:
            return True
        values = p.get("AWS", []) if isinstance(p, dict) else p
        return principal is not None and principal in _as_list(values)

    @staticmethod
    def _matches(patterns, value: str) -> bool:
        return any(fnmatch.fnmatchcase(value, pat) for pat in _as_list(patterns))

    def evaluate(self, action: str, resource: str, principal: str | None) -> str | None:
        """Returns Allow, Deny, or None (no statement matched).

        resource is "bucket" or "bucket/key"; statement resources use the
        arn:aws:s3::: prefix or the bare form — both accepted. Deny overrides.
        """
        verdict = None
        for st in _as_list(self.doc["Statement"]):
            if not self._principal_matches(st, principal):
                continue
            if not self._matches(st["Action"], action):
                continue
            resources = [r.removeprefix("arn:aws:s3:::")
                         for r in _as_list(st["Resource"])]
            if not self._matches(resources, resource):
                continue
            if st["Effect"] == DENY:
                return DENY
            verdict = ALLOW
        return verdict
